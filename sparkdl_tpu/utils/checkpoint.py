"""Checkpoint/resume (SURVEY.md §5.4 — the reference has model
persistence by contract but NO training checkpointing; here training
state checkpoints ride orbax, the TPU-native answer, with the same
save/restore surface the estimators use for models).

Works with sharded (GSPMD) params: orbax restores to the same
shardings when given an abstract target. Two distributed regimes are
handled distinctly (see ``TrainCheckpointer.__init__``):

- **HorovodRunner gangs** (``hvd.init()`` called): one jax world where
  ``process_index == rank`` and state is replicated per rank. Rank 0
  persists (:func:`should_save`); each rank's manager is pinned
  process-local so orbax's cross-process barriers don't deadlock when
  non-primary ranks skip the write.
- **Multihost GSPMD pjit jobs** (multi-process world, no hvd gang):
  arrays are sharded across processes, so ALL processes must
  participate in each save; orbax's default cross-process coordination
  is left in place.

Elastic resume (ISSUE 15): every :meth:`TrainCheckpointer.save` also
writes a **sharding-tree sidecar** — a jax-free, schema-versioned JSON
(``sharding_tree-<step>.json``) recording each leaf's full shape/dtype
and per-dim mesh-axis spec plus the mesh axis sizes the run was laid
out on. The sidecar is durable *before* orbax commits the step (orbax
commits by renaming the temp dir to the bare step number), so
:func:`latest_complete_step` semantics are preserved: a numeric step
dir existing implies its sidecar exists. On restore,
``restore(..., target_mesh=...)`` re-lays every param onto whatever
mesh the surviving world built — the paper's ``np=-1`` ("use what the
cluster has") contract made true end-to-end: a preempted gang
relaunched at a different np restores straight onto the shrunken (or
regrown) mesh, honoring the reshard plan's restore-time HBM high-water
mark by placing param groups one at a time when memory is tight.
"""

import json
import logging
import os
import time

logger = logging.getLogger("HorovodRunner")

SHARDING_TREE_SCHEMA = "sparkdl_tpu.checkpoint.sharding_tree/1"


class ReshardRestoreError(RuntimeError):
    """A resharded restore failed for a reason that is NOT a corrupt
    step artifact (metadata unavailable in a world that needs it, the
    grouped-placement accounting invariant broken). Deliberately
    excluded from :meth:`TrainCheckpointer.restore`'s corrupt-step
    fallback: retrying earlier steps would fail identically, and
    quarantining them would destroy healthy checkpoints."""


def _process_index():
    """This process's index in the jax world (0 when not distributed)."""
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def should_save():
    """In a gang, only rank 0 persists (workers hold replicated state)."""
    from sparkdl_tpu.hvd import _state

    st = _state.state()
    return (not st.initialized) or st.rank == 0


def latest_complete_step(directory):
    """Newest COMMITTED checkpoint step under a TrainCheckpointer
    root, by directory scan alone — no orbax (or jax) import, so the
    gang supervisor can call it from the driver between relaunches
    without initializing a backend the workers need. Orbax commits a
    step by renaming its temp dir (suffixed, non-numeric) to the bare
    step number, so numeric-named directories are exactly the durable
    steps; a worker preempted mid-save leaves only a temp dir, which
    this scan correctly ignores. Returns None when no step exists."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = [
        int(n) for n in names
        if n.isdigit() and os.path.isdir(os.path.join(directory, n))
    ]
    return max(steps, default=None)


def _committed_steps(directory):
    """All committed step numbers under a checkpoint root, by the same
    numeric-dir scan as :func:`latest_complete_step`."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(
        int(n) for n in names
        if n.isdigit() and os.path.isdir(os.path.join(directory, n))
    )


def sharding_sidecar_path(directory, step):
    """Path of one step's sharding-tree sidecar under a checkpoint
    root. Kept beside (not inside) the orbax step dir: the sidecar is
    written and durable BEFORE orbax's commit rename, so the
    numeric-dir-implies-committed invariant of
    :func:`latest_complete_step` extends to the sidecar."""
    return os.path.join(directory, f"sharding_tree-{int(step)}.json")


def load_sharding_tree(directory, step):
    """Load one step's sharding-tree sidecar, or None (absent, torn,
    or schema-mismatched — a pre-elastic checkpoint restores without
    resharding). jax-free on purpose: the gang supervisor calls this
    on the driver, between relaunches, to derive the surviving mesh
    for the restart context without initializing a backend."""
    try:
        with open(sharding_sidecar_path(directory, step)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != SHARDING_TREE_SCHEMA:
        return None
    return doc


def sidecar_mesh_axes(doc):
    """The sidecar's recorded mesh axis sizes as a plain
    ``{name: size}`` dict — the one normalization point for the
    schema field (checkpoint restore, the supervisor's restart
    context, and the analysis sidecar reader all share it)."""
    return {
        str(k): int(v)
        for k, v in ((doc or {}).get("mesh_axes") or {}).items()
    }


class TrainCheckpointer:
    """Step-indexed train-state checkpoints (params, opt_state, extras).

    Thin wrapper over ``orbax.checkpoint.CheckpointManager`` with
    keep-last-N retention and atomic writes.
    """

    def __init__(self, directory, max_to_keep=3, async_save=False):
        """``async_save=True`` returns from :meth:`save` as soon as the
        state is snapshotted to host memory; the disk write proceeds in
        the background (orbax AsyncCheckpointer) so the train loop's
        next step overlaps the IO instead of stalling on it. Restores,
        a following save, and :meth:`close` all join the pending write
        first.

        Gang semantics: a HorovodRunner gang is one jax world
        (``hvd.init()`` calls ``jax.distributed.initialize``, so
        ``process_index == rank``) with state REPLICATED per rank, so
        each rank's manager is pinned process-local (orbax's
        cross-process barriers would otherwise deadlock: the
        non-primary rank skips the write without entering the barrier
        the primary waits in). Rank 0 persists (:func:`should_save`
        gates :meth:`save`); any rank may :meth:`restore`, ordered by
        the caller (``hvd.barrier()`` between a save and a dependent
        restore).

        Multihost GSPMD pjit jobs (multi-process world WITHOUT an hvd
        gang) keep orbax's default cross-process coordination: arrays
        are sharded across processes, so every process must join each
        save — pinning here would make each process its own primary
        and corrupt/thin the write.

        The regime is decided LAZILY at the first save/restore, not at
        construction: a checkpointer built before ``hvd.init()`` in a
        gang worker would otherwise latch the GSPMD branch, and its
        first rank-0-only save would deadlock in orbax's cross-process
        barrier — exactly the failure the pinning exists to prevent."""
        self._dir = os.path.abspath(directory)
        self._async = bool(async_save)
        self._max_to_keep = max_to_keep
        os.makedirs(self._dir, exist_ok=True)
        self._mgr_instance = None
        self._gang = None
        # Stats of the most recent resharded restore (None when the
        # last restore needed none): direction, axes, bytes moved, and
        # the memory-accounted high water vs the plan's bound — what
        # the chaos acceptance asserts on and the gang.reshard
        # timeline event carries.
        self.last_reshard = None
        # The step the most recent restore() actually loaded: on a
        # corrupt-step fallback this is EARLIER than the requested
        # step, and callers tracking a resume point must re-sync from
        # it rather than from what they asked for.
        self.last_restored_step = None

    @property
    def _mgr(self):
        from sparkdl_tpu.hvd import _state

        if (self._mgr_instance is not None and not self._gang
                and _state.state().initialized):
            # hvd.init() ran AFTER the manager first materialized
            # (e.g. a pre-init latest_step() probed for a resume
            # point): rebuild with gang pinning, or the next
            # rank-0-only save deadlocks in orbax's cross-process
            # barrier. The uninitialized→initialized transition only
            # happens once, and only in a then-single-process world,
            # so the close is barrier-free.
            self._mgr_instance.close()
            self._mgr_instance = None
        if self._mgr_instance is None:
            import orbax.checkpoint as ocp

            self._gang = gang = _state.state().initialized
            if gang:
                pidx = _process_index()
                mp_options = ocp.options.MultiprocessingOptions(
                    primary_host=pidx,
                    active_processes={pidx},
                    barrier_sync_key_prefix=f"rank{pidx}",
                )
            else:
                mp_options = ocp.options.MultiprocessingOptions()
            self._mgr_instance = ocp.CheckpointManager(
                self._dir,
                options=ocp.CheckpointManagerOptions(
                    # the root dir is created in __init__ (orbax's
                    # create=True is unsupported with active_processes
                    # pinned)
                    max_to_keep=self._max_to_keep, create=False,
                    enable_async_checkpointing=self._async,
                    multiprocessing_options=mp_options,
                ),
                # Pre-register the handler: a manager that never saved
                # in this process (every relaunched worker) can
                # otherwise neither read item_metadata nor restore
                # without args — both of which the resharded-restore
                # path needs before any save happens.
                item_handlers=ocp.StandardCheckpointHandler(),
            )
        return self._mgr_instance

    @staticmethod
    def _sharding_tree_doc(step, state):
        """The sharding tree **as data** for one save: per-leaf full
        shape/dtype and per-dim mesh-axis-name spec (``[]`` = that dim
        unsharded), plus the union of mesh axis sizes the leaves were
        laid out on — the serialization
        :func:`sparkdl_tpu.parallel.sharding.sharding_tree_info`
        established, flattened to plain JSON so the sidecar loads
        without jax."""
        import jax

        leaves, _ = jax.tree_util.tree_flatten_with_path(state)
        params = []
        mesh_axes = {}
        for path, leaf in leaves:
            shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
            spec_dims = [[] for _ in shape]
            sh = getattr(leaf, "sharding", None)
            if sh is not None and hasattr(sh, "spec") \
                    and hasattr(sh, "mesh"):
                sizes = dict(zip(sh.mesh.axis_names,
                                 sh.mesh.devices.shape))
                for k, v in sizes.items():
                    mesh_axes[str(k)] = int(v)
                for dim, entry in enumerate(sh.spec):
                    if dim >= len(spec_dims):
                        break
                    names = (entry if isinstance(entry, tuple)
                             else (entry,))
                    spec_dims[dim] = [str(n) for n in names
                                      if n is not None]
            params.append({
                "path": jax.tree_util.keystr(path),
                "shape": list(shape),
                "dtype": str(getattr(leaf, "dtype", "float32")),
                "spec": spec_dims,
            })
        return {
            "schema": SHARDING_TREE_SCHEMA,
            "step": int(step),
            "mesh_axes": mesh_axes,
            "params": params,
        }

    def _write_sidecar(self, step, doc):
        """Atomic (tmp + rename) sidecar write BEFORE the orbax save:
        the numeric step dir only appears after orbax's commit rename,
        so a step visible to :func:`latest_complete_step` always has
        its sidecar on disk. Also prunes sidecars whose step the
        retention policy already deleted."""
        path = sharding_sidecar_path(self._dir, step)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        live = set(_committed_steps(self._dir))
        live.add(int(step))
        try:
            for name in os.listdir(self._dir):
                if (name.startswith("sharding_tree-")
                        and name.endswith(".json")):
                    stem = name[len("sharding_tree-"):-len(".json")]
                    if stem.isdigit() and int(stem) not in live:
                        os.unlink(os.path.join(self._dir, name))
        except OSError:
            pass  # best-effort: a stale sidecar is never load-bearing

    @staticmethod
    def _gather_cross_process(state):
        """Gang regime only: leaves sharded ACROSS the gang's
        processes cannot be written by the rank-0-pinned manager (rank
        0 holds only its own shard), so every rank joins a replicating
        identity jit (an all-gather on the wire) and the full host
        value is what rank 0 persists. The sharding-tree sidecar —
        built from the ORIGINAL leaves before this gather — is what
        lets restore re-lay them. Collective: all ranks must call
        save() (they already do; :func:`should_save` gates the write
        after this). No-op outside a gang or for fully-addressable
        trees, so GSPMD multi-process jobs keep orbax's native
        cross-process save path."""
        from sparkdl_tpu.hvd import _state

        if not _state.state().initialized:
            return state

        def cross_process(leaf):
            return (hasattr(leaf, "is_fully_addressable")
                    and not leaf.is_fully_addressable)

        import jax

        if not any(cross_process(leaf)
                   for leaf in jax.tree_util.tree_leaves(state)):
            return state
        from sparkdl_tpu.parallel.sharding import full_host_value

        return jax.tree_util.tree_map(
            lambda leaf: full_host_value(leaf) if cross_process(leaf)
            else leaf, state)

    def save(self, step, state, force=False):
        """state: any pytree (e.g. {'params': ..., 'opt_state': ...}).
        Blocks until durable unless ``async_save`` was set."""
        import orbax.checkpoint as ocp

        from sparkdl_tpu import observe

        # Sidecar doc from the ORIGINAL leaves (the gather below strips
        # their shardings); the cross-process gather itself is a
        # collective every rank joins before the rank-0 write gate.
        sidecar = self._sharding_tree_doc(step, state)
        state = self._gather_cross_process(state)
        if not should_save():
            return False
        if _process_index() == 0:
            self._write_sidecar(step, sidecar)
        t0 = time.perf_counter()
        if self._async:
            # An async save() returns once the state is snapshotted to
            # host memory — a host-side detour inside the step window,
            # so it is attributed as ``cat="host"`` (the perf
            # report's host_callback component) rather than claiming
            # the background write's dispatch as checkpoint wait.
            with observe.host_span("checkpoint.snapshot",
                                   step=int(step)):
                saved = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force
                )
        else:
            # Sync mode: the span covers snapshot + durable write.
            # Counter + duration histogram feed the alertable view (a
            # checkpoint stall is a classic silent gang killer).
            with observe.span("checkpoint.save", cat="checkpoint",
                              step=int(step), sync=True):
                saved = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force
                )
                self._mgr.wait_until_finished()
        if saved:
            observe.inc("checkpoint_saves_total")
            observe.observe_value(
                "checkpoint_save_seconds", time.perf_counter() - t0
            )
        return saved

    def wait_until_finished(self):
        """Join any in-flight async write (no-op when idle)."""
        self._mgr.wait_until_finished()

    def latest_step(self):
        if self._async:
            self._mgr.wait_until_finished()
        self._refresh_if_reader()
        return self._mgr.latest_step()

    def _refresh_if_reader(self):
        """Gang non-writers: this manager's step bookkeeping was
        scanned at construction; rescan so steps rank 0 wrote since
        (or retention deleted since) are visible. Ordering between a
        write and a dependent read is the caller's barrier. (GSPMD
        jobs write from every process — orbax keeps them in sync.)"""
        mgr = self._mgr  # materialize first (decides the regime)
        if self._gang and _process_index() != 0:
            mgr.reload()

    def restore(self, step=None, target=None, *, target_mesh=None,
                fallback=True):
        """Restore a step (default latest). Pass ``target`` (a pytree of
        like-shaped arrays or jax.ShapeDtypeStruct with shardings) to
        control placement of the restored arrays.

        ``target_mesh``: re-lay every param onto this mesh using the
        step's sharding-tree sidecar (elastic resume). When the
        recorded mesh axes differ from the target's, the restore is a
        **reshard**: params land directly on the new mesh, a
        ``gang.reshard`` span with bytes-moved/high-water lands on the
        timeline, ``gang_reshards_total{direction=shrink|grow}``
        counts it, and :attr:`last_reshard` carries the accounting.
        Memory is bounded by the reshard plan's
        ``restore_high_water_bytes``: when that approaches the HBM
        budget (or ``SPARKDL_TPU_RESHARD_GROUPED`` forces it), params
        are placed group-at-a-time instead of materializing old+new
        shards for the whole tree at once.

        ``fallback=True`` (default): if restoring the chosen step
        raises — a torn write that still got a numeric dir name — log
        loudly and fall back to the previous committed step rather
        than burning the gang's whole retry budget on the same
        poisoned checkpoint. The step actually loaded lands in
        :attr:`last_restored_step`; a caller deriving its resume point
        from the requested step must re-sync from it. Typed reshard
        refusals (:class:`~sparkdl_tpu.analysis.comms.
        ReshardPreflightError`, :class:`ReshardRestoreError`) are
        NEVER treated as corruption — they surface immediately. Pass
        ``fallback=False`` to surface any error for exactly the
        requested step.
        """
        if self._async:
            # join any in-flight write: orbax registers the step in its
            # bookkeeping synchronously, so without this a restore
            # could target a step still being committed
            self._mgr.wait_until_finished()
        self._refresh_if_reader()
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints found under {self._dir}"
            )
        from sparkdl_tpu import observe

        candidates = [int(step)]
        if fallback:
            candidates += [
                s for s in sorted(_committed_steps(self._dir),
                                  reverse=True)
                if s < int(step)
            ]
        from sparkdl_tpu.analysis.comms import ReshardPreflightError

        first_error = None
        for i, cand in enumerate(candidates):
            try:
                with observe.span("checkpoint.restore", cat="checkpoint",
                                  step=int(cand)):
                    observe.inc("checkpoint_restores_total")
                    out = self._restore_step(cand, target, target_mesh)
                    # The step actually loaded — on a fallback this is
                    # EARLIER than requested; resume-step bookkeeping
                    # must re-sync from here, not from what it asked.
                    self.last_restored_step = int(cand)
                    return out
            except (ReshardPreflightError, ReshardRestoreError):
                # Deterministic reshard refusals, not corruption:
                # every candidate would fail identically, and the
                # quarantine below would destroy healthy checkpoints.
                # Surface the typed error to the operator untouched.
                raise
            except Exception as e:  # noqa: BLE001 — every restore
                # failure mode (torn zarr, missing msgpack, orbax
                # version skew) must reach the fallback, or one
                # poisoned step kills the gang's whole retry budget.
                first_error = first_error or e
                if i + 1 >= len(candidates):
                    break
                observe.inc("checkpoint_corrupt_steps_total")
                observe.instant(
                    "checkpoint.corrupt_step", cat="checkpoint",
                    step=int(cand), error=f"{type(e).__name__}: {e}",
                    fallback_step=int(candidates[i + 1]),
                )
                logger.error(
                    "checkpoint step %d under %s failed to restore "
                    "(%s: %s) — falling back to committed step %d "
                    "instead of retrying the poisoned step",
                    cand, self._dir, type(e).__name__, e,
                    candidates[i + 1],
                )
                self._quarantine_step(cand)
        raise first_error

    def _quarantine_step(self, step):
        """Move a torn-but-numeric step dir out of the numeric
        namespace (``<step>.corrupt-<pid>``) and rebuild the manager.
        Both halves matter: orbax latches its item-layout detection
        from EVERY numeric dir at manager construction, so one torn
        step poisons restores of perfectly good steps through the same
        manager — and ``latest_complete_step`` (the supervisor's
        resume-point scan) would keep steering every relaunch back to
        the poison. Racing ranks are fine: the first rename wins,
        the rest ENOENT quietly."""
        path = os.path.join(self._dir, str(int(step)))
        try:
            os.replace(path, f"{path}.corrupt-{os.getpid()}")
            logger.error(
                "quarantined torn checkpoint step dir %s", path,
            )
        except OSError:
            pass
        if self._mgr_instance is not None:
            try:
                self._mgr_instance.close()
            except Exception:  # noqa: BLE001 — a wedged manager must
                pass           # not block the rebuild
            self._mgr_instance = None

    def _restore_step(self, step, target, target_mesh):
        import orbax.checkpoint as ocp

        if target_mesh is None:
            if target is not None:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(target)
                )
            return self._mgr.restore(step)
        return self._resharded_restore(step, target, target_mesh)

    def _resharded_restore(self, step, target, target_mesh):
        """Re-lay step ``step`` onto ``target_mesh`` per the sidecar.

        The restore-time half of the PR 8 pre-flight: the plan that
        proved the shrink feasible (per-dim divisibility, HBM
        high-water) is recomputed here over the actual saved tree
        (``state_multiplier=1.0`` — the tree IS the state) and its
        ``restore_high_water_bytes`` is the budget the placement loop
        accounts against. Grouped placement (old shard + new shard of
        one param GROUP resident at a time, not the whole tree) kicks
        in when the high water approaches the HBM budget or when
        ``SPARKDL_TPU_RESHARD_GROUPED`` pins a group size."""
        import orbax.checkpoint as ocp

        from sparkdl_tpu import observe
        from sparkdl_tpu.analysis.comms import (
            ReshardPreflightError,
            param_info_from_sidecar,
            reshard_plan,
        )
        from sparkdl_tpu.utils import knobs

        doc = load_sharding_tree(self._dir, step)
        target_axes = {
            str(k): int(v)
            for k, v in zip(target_mesh.axis_names,
                            target_mesh.devices.shape)
        }
        if doc is None:  # pre-elastic checkpoint
            # Pre-elastic checkpoint (no sidecar): nothing recorded to
            # reshard FROM. Degrade loudly to the plain restore path.
            logger.warning(
                "no sharding sidecar for step %d under %s — restoring "
                "without resharding (pre-elastic checkpoint)",
                step, self._dir,
            )
            return self._restore_step(step, target, None)
        source_axes = sidecar_mesh_axes(doc)
        info = param_info_from_sidecar(doc)
        plan = reshard_plan(
            info, source_axes or target_axes, target_axes,
            state_multiplier=1.0,
        )
        if not plan.feasible:
            # Same typed refusal as the supervisor pre-flight: an
            # indivisible dim or an over-budget high water must never
            # become an OOM or a sharding crash on the chips.
            raise ReshardPreflightError(plan.problems, plan=plan)

        def world(axes):
            n = 1
            for v in axes.values():
                n *= int(v)
            return n

        src_world, tgt_world = world(source_axes), world(target_axes)
        aligned = source_axes == target_axes
        direction = ("grow" if tgt_world > src_world
                     else "shrink" if tgt_world < src_world
                     else "relayout")
        spec_by_path = {
            p["path"]: p.get("spec") or [] for p in doc["params"]
        }
        group = knobs.read_int("SPARKDL_TPU_RESHARD_GROUPED", 0) or 0
        if group <= 0:
            # Auto: place one param at a time only when the whole-tree
            # worst case (old + new shard of EVERYTHING resident)
            # threatens the HBM budget; otherwise one shot.
            tight = (plan.hbm_bytes and plan.restore_high_water_bytes
                     > 0.5 * plan.hbm_bytes)
            group = 1 if tight else 0

        t_wall = time.time()
        t0 = time.perf_counter()
        if not group and not self._gang and target is not None:
            # Direct path: abstract targets with the re-laid
            # NamedShardings straight through orbax — every param
            # lands on the new mesh with no host detour. Gang ranks
            # skip this (their managers are process-pinned; orbax
            # cannot coordinate a cross-process placement there) and
            # take the host-mediated loop below instead.
            restored, stats = self._direct_resharded(
                step, target, target_mesh, spec_by_path, plan)
        else:
            restored, stats = self._grouped_resharded(
                step, target_mesh, spec_by_path, source_axes,
                target_axes, plan, group)
        if aligned:
            # Same topology: the params landed on their recorded
            # layout — a resume, not a reshard. No span, no counter.
            self.last_reshard = None
            return restored
        stats.update(
            step=int(step), direction=direction,
            source_axes=source_axes, target_axes=target_axes,
            restore_high_water_bytes=plan.restore_high_water_bytes,
            hbm_bytes=plan.hbm_bytes,
        )
        self.last_reshard = stats
        observe.complete(
            "gang.reshard", t_wall, time.perf_counter() - t0,
            cat="checkpoint", **stats,
        )
        observe.inc("gang_reshards_total", direction=direction)
        logger.info(
            "resharded restore of step %d: %s %s -> %s (%d param(s), "
            "%d group(s), %.1f MiB moved, accounted high-water "
            "%.1f MiB within plan %.1f MiB)",
            step, direction, source_axes, target_axes,
            stats["params"], stats["groups"],
            stats["bytes_moved"] / 2**20,
            stats["high_water_accounted_bytes"] / 2**20,
            plan.restore_high_water_bytes / 2**20,
        )
        return restored

    def _direct_resharded(self, step, target, target_mesh,
                          spec_by_path, plan):
        """One-shot orbax restore into sharded abstract targets."""
        import jax
        import orbax.checkpoint as ocp

        from sparkdl_tpu.parallel.sharding import named_sharding_for

        leaves, treedef = jax.tree_util.tree_flatten_with_path(target)
        abstract = jax.tree_util.tree_unflatten(treedef, [
            jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype,
                sharding=named_sharding_for(
                    target_mesh,
                    spec_by_path.get(jax.tree_util.keystr(path))),
            )
            for path, leaf in leaves
        ])
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        return restored, {
            "mode": "direct", "params": len(leaves), "groups": 1,
            "bytes_moved": plan.per_device_bytes_target,
            # One shot = the plan's own worst case is the bound.
            "high_water_accounted_bytes": plan.restore_high_water_bytes,
        }

    def _grouped_resharded(self, step, target_mesh, spec_by_path,
                           source_axes, target_axes, plan, group):
        """Host-mediated placement, param-group-at-a-time.

        Restores the saved tree to host memory, then places each group
        onto the target mesh via ``make_array_from_callback`` (each
        process contributes its addressable shards — the only
        placement primitive that works in both the gang regime and
        single-process worlds), freeing the host copy as it goes. The
        device-memory accounting models the plan's terms: new shards
        accumulate, and only the IN-FLIGHT group's old/full copy is
        co-resident — the measured high water must stay within the
        plan's whole-tree bound (raises if ever it would not; with
        grouping it sits far below)."""
        import numpy as _np

        import jax
        import orbax.checkpoint as ocp

        from sparkdl_tpu.parallel.sharding import named_sharding_for

        # Restore to HOST numpy via abstract targets from the step's
        # own metadata, never onto the SAVED shardings: the checkpoint
        # records the dead topology's device mesh, and materializing
        # it in the surviving world fails outright when the recorded
        # devices aren't addressable here (the whole reason this path
        # exists). The metadata tree also carries the structure the
        # flat sidecar cannot.
        meta = self._mgr.item_metadata(step)
        target_np = None
        if meta is not None:
            try:
                target_np = jax.tree_util.tree_map(
                    lambda mm: _np.empty(mm.shape, mm.dtype), meta)
            except Exception:  # noqa: BLE001 — metadata shapes are
                target_np = None  # advisory; fall through to raw
        if target_np is not None:
            raw = self._mgr.restore(
                step, args=ocp.args.StandardRestore(target_np))
        else:
            # Degraded: no metadata to build host targets from, so
            # the raw restore materializes the SAVED shardings — fine
            # for numpy/replicated saves, but a tree saved sharded on
            # the dead topology fails here. Surface that typed (NOT
            # as corruption): earlier steps would fail identically
            # and must not be quarantined for it.
            logger.warning(
                "step %d item metadata unavailable under %s — "
                "restoring via the saved shardings", step, self._dir,
            )
            try:
                raw = self._mgr.restore(
                    step, args=ocp.args.StandardRestore())
            except Exception as e:
                raise ReshardRestoreError(
                    f"step {step} under {self._dir} cannot be "
                    "restored in this world: item metadata is "
                    "unavailable and the saved shardings reference "
                    f"the recorded topology ({type(e).__name__}: {e})"
                ) from e
        flat, treedef = jax.tree_util.tree_flatten_with_path(raw)
        n = len(flat)
        group = group if group > 0 else (n or 1)

        def factor(spec_dims, axes):
            f = 1
            for dims in spec_dims or ():
                for name in dims or ():
                    f *= int(axes.get(name, 1))
            return f

        entries = []  # (key, host, nbytes, src_shard, tgt_shard)
        for path, leaf in flat:
            key = jax.tree_util.keystr(path)
            host = _np.asarray(leaf)
            spec = spec_by_path.get(key) or []
            nbytes = int(host.nbytes)
            entries.append((
                key, host, nbytes,
                nbytes // factor(spec, source_axes),
                nbytes // factor(spec, target_axes),
            ))
        del raw, flat
        out = [None] * n
        resident_new = 0
        high_water = 0
        bytes_moved = 0
        groups = 0
        for lo in range(0, n, group):
            batch = range(lo, min(lo + group, n))
            groups += 1
            inflight_src = sum(entries[i][3] for i in batch)
            high_water = max(high_water, resident_new + inflight_src)
            if high_water > plan.restore_high_water_bytes:
                raise ReshardRestoreError(
                    "resharded restore accounting exceeded the plan's "
                    f"high-water bound ({high_water} > "
                    f"{plan.restore_high_water_bytes} bytes) — the "
                    "grouped-restore invariant is broken; file a bug"
                )
            for i in batch:
                key, host, _, _, tgt_shard = entries[i]
                sharding = named_sharding_for(
                    target_mesh, spec_by_path.get(key))
                out[i] = jax.make_array_from_callback(
                    host.shape, sharding,
                    lambda idx, h=host: h[idx],
                )
                resident_new += tgt_shard
                bytes_moved += tgt_shard
                entries[i] = (key, None, 0, 0, 0)  # free the host copy
        return jax.tree_util.tree_unflatten(treedef, out), {
            "mode": "grouped", "params": n, "groups": groups,
            "bytes_moved": int(bytes_moved),
            "high_water_accounted_bytes": int(high_water),
        }

    def close(self):
        """Join any in-flight async save, THEN dispose the manager.

        A train loop's natural shutdown (``finally: ckpt.close()``)
        can land microseconds after an async ``save()`` returned —
        tearing the manager down while its background write is
        mid-flight would abandon a temp dir where a committed step
        should be, and the *final* checkpoint of a run is exactly the
        one a resume needs. ``wait_until_finished`` first makes close
        a commit point. Failures in the join still dispose the
        manager (a wedged writer must not leak it)."""
        if self._mgr_instance is not None:
            try:
                self._mgr_instance.wait_until_finished()
            finally:
                self._mgr_instance.close()
