"""Checkpoint/resume (SURVEY.md §5.4 — the reference has model
persistence by contract but NO training checkpointing; here training
state checkpoints ride orbax, the TPU-native answer, with the same
save/restore surface the estimators use for models).

Works with sharded (GSPMD) params: orbax restores to the same
shardings when given an abstract target. Two distributed regimes are
handled distinctly (see ``TrainCheckpointer.__init__``):

- **HorovodRunner gangs** (``hvd.init()`` called): one jax world where
  ``process_index == rank`` and state is replicated per rank. Rank 0
  persists (:func:`should_save`); each rank's manager is pinned
  process-local so orbax's cross-process barriers don't deadlock when
  non-primary ranks skip the write.
- **Multihost GSPMD pjit jobs** (multi-process world, no hvd gang):
  arrays are sharded across processes, so ALL processes must
  participate in each save; orbax's default cross-process coordination
  is left in place.
"""

import os
import time


def _process_index():
    """This process's index in the jax world (0 when not distributed)."""
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def should_save():
    """In a gang, only rank 0 persists (workers hold replicated state)."""
    from sparkdl_tpu.hvd import _state

    st = _state.state()
    return (not st.initialized) or st.rank == 0


def latest_complete_step(directory):
    """Newest COMMITTED checkpoint step under a TrainCheckpointer
    root, by directory scan alone — no orbax (or jax) import, so the
    gang supervisor can call it from the driver between relaunches
    without initializing a backend the workers need. Orbax commits a
    step by renaming its temp dir (suffixed, non-numeric) to the bare
    step number, so numeric-named directories are exactly the durable
    steps; a worker preempted mid-save leaves only a temp dir, which
    this scan correctly ignores. Returns None when no step exists."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = [
        int(n) for n in names
        if n.isdigit() and os.path.isdir(os.path.join(directory, n))
    ]
    return max(steps, default=None)


class TrainCheckpointer:
    """Step-indexed train-state checkpoints (params, opt_state, extras).

    Thin wrapper over ``orbax.checkpoint.CheckpointManager`` with
    keep-last-N retention and atomic writes.
    """

    def __init__(self, directory, max_to_keep=3, async_save=False):
        """``async_save=True`` returns from :meth:`save` as soon as the
        state is snapshotted to host memory; the disk write proceeds in
        the background (orbax AsyncCheckpointer) so the train loop's
        next step overlaps the IO instead of stalling on it. Restores,
        a following save, and :meth:`close` all join the pending write
        first.

        Gang semantics: a HorovodRunner gang is one jax world
        (``hvd.init()`` calls ``jax.distributed.initialize``, so
        ``process_index == rank``) with state REPLICATED per rank, so
        each rank's manager is pinned process-local (orbax's
        cross-process barriers would otherwise deadlock: the
        non-primary rank skips the write without entering the barrier
        the primary waits in). Rank 0 persists (:func:`should_save`
        gates :meth:`save`); any rank may :meth:`restore`, ordered by
        the caller (``hvd.barrier()`` between a save and a dependent
        restore).

        Multihost GSPMD pjit jobs (multi-process world WITHOUT an hvd
        gang) keep orbax's default cross-process coordination: arrays
        are sharded across processes, so every process must join each
        save — pinning here would make each process its own primary
        and corrupt/thin the write.

        The regime is decided LAZILY at the first save/restore, not at
        construction: a checkpointer built before ``hvd.init()`` in a
        gang worker would otherwise latch the GSPMD branch, and its
        first rank-0-only save would deadlock in orbax's cross-process
        barrier — exactly the failure the pinning exists to prevent."""
        self._dir = os.path.abspath(directory)
        self._async = bool(async_save)
        self._max_to_keep = max_to_keep
        os.makedirs(self._dir, exist_ok=True)
        self._mgr_instance = None
        self._gang = None

    @property
    def _mgr(self):
        from sparkdl_tpu.hvd import _state

        if (self._mgr_instance is not None and not self._gang
                and _state.state().initialized):
            # hvd.init() ran AFTER the manager first materialized
            # (e.g. a pre-init latest_step() probed for a resume
            # point): rebuild with gang pinning, or the next
            # rank-0-only save deadlocks in orbax's cross-process
            # barrier. The uninitialized→initialized transition only
            # happens once, and only in a then-single-process world,
            # so the close is barrier-free.
            self._mgr_instance.close()
            self._mgr_instance = None
        if self._mgr_instance is None:
            import orbax.checkpoint as ocp

            self._gang = gang = _state.state().initialized
            if gang:
                pidx = _process_index()
                mp_options = ocp.options.MultiprocessingOptions(
                    primary_host=pidx,
                    active_processes={pidx},
                    barrier_sync_key_prefix=f"rank{pidx}",
                )
            else:
                mp_options = ocp.options.MultiprocessingOptions()
            self._mgr_instance = ocp.CheckpointManager(
                self._dir,
                options=ocp.CheckpointManagerOptions(
                    # the root dir is created in __init__ (orbax's
                    # create=True is unsupported with active_processes
                    # pinned)
                    max_to_keep=self._max_to_keep, create=False,
                    enable_async_checkpointing=self._async,
                    multiprocessing_options=mp_options,
                ),
            )
        return self._mgr_instance

    def save(self, step, state, force=False):
        """state: any pytree (e.g. {'params': ..., 'opt_state': ...}).
        Blocks until durable unless ``async_save`` was set."""
        import orbax.checkpoint as ocp

        from sparkdl_tpu import observe

        if not should_save():
            return False
        t0 = time.perf_counter()
        if self._async:
            # An async save() returns once the state is snapshotted to
            # host memory — a host-side detour inside the step window,
            # so it is attributed as ``cat="host"`` (the perf
            # report's host_callback component) rather than claiming
            # the background write's dispatch as checkpoint wait.
            with observe.host_span("checkpoint.snapshot",
                                   step=int(step)):
                saved = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force
                )
        else:
            # Sync mode: the span covers snapshot + durable write.
            # Counter + duration histogram feed the alertable view (a
            # checkpoint stall is a classic silent gang killer).
            with observe.span("checkpoint.save", cat="checkpoint",
                              step=int(step), sync=True):
                saved = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force
                )
                self._mgr.wait_until_finished()
        if saved:
            observe.inc("checkpoint_saves_total")
            observe.observe_value(
                "checkpoint_save_seconds", time.perf_counter() - t0
            )
        return saved

    def wait_until_finished(self):
        """Join any in-flight async write (no-op when idle)."""
        self._mgr.wait_until_finished()

    def latest_step(self):
        if self._async:
            self._mgr.wait_until_finished()
        self._refresh_if_reader()
        return self._mgr.latest_step()

    def _refresh_if_reader(self):
        """Gang non-writers: this manager's step bookkeeping was
        scanned at construction; rescan so steps rank 0 wrote since
        (or retention deleted since) are visible. Ordering between a
        write and a dependent read is the caller's barrier. (GSPMD
        jobs write from every process — orbax keeps them in sync.)"""
        mgr = self._mgr  # materialize first (decides the regime)
        if self._gang and _process_index() != 0:
            mgr.reload()

    def restore(self, step=None, target=None):
        """Restore a step (default latest). Pass ``target`` (a pytree of
        like-shaped arrays or jax.ShapeDtypeStruct with shardings) to
        control placement of the restored arrays."""
        import orbax.checkpoint as ocp

        if self._async:
            # join any in-flight write: orbax registers the step in its
            # bookkeeping synchronously, so without this a restore
            # could target a step still being committed
            self._mgr.wait_until_finished()
        self._refresh_if_reader()
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints found under {self._dir}"
            )
        from sparkdl_tpu import observe

        with observe.span("checkpoint.restore", cat="checkpoint",
                          step=int(step)):
            observe.inc("checkpoint_restores_total")
            if target is not None:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(target)
                )
            return self._mgr.restore(step)

    def close(self):
        """Join any in-flight async save, THEN dispose the manager.

        A train loop's natural shutdown (``finally: ckpt.close()``)
        can land microseconds after an async ``save()`` returned —
        tearing the manager down while its background write is
        mid-flight would abandon a temp dir where a committed step
        should be, and the *final* checkpoint of a run is exactly the
        one a resume needs. ``wait_until_finished`` first makes close
        a commit point. Failures in the join still dispose the
        manager (a wedged writer must not leak it)."""
        if self._mgr_instance is not None:
            try:
                self._mgr_instance.wait_until_finished()
            finally:
                self._mgr_instance.close()
