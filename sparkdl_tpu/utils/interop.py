"""Framework tensor interop: numpy <-> jax / torch / tensorflow.

The Horovod shim accepts tensors from any of the frameworks a user
``main`` might use (tf.keras, PyTorch, JAX — the north-star requirement
that existing training functions run unmodified, BASELINE.json) and
routes them through JAX collectives. Conversions go through numpy;
framework libraries are only touched if the user already imported them
(``sys.modules`` check), so importing sparkdl_tpu never drags in tf or
torch.
"""

import sys

import numpy as np


def _torch():
    return sys.modules.get("torch")


def _tf():
    return sys.modules.get("tensorflow")


def is_torch_tensor(x):
    t = _torch()
    return t is not None and isinstance(x, t.Tensor)


def is_tf_tensor(x):
    tf = _tf()
    return tf is not None and isinstance(x, (tf.Tensor, tf.Variable))


def to_numpy(x):
    """Convert a framework tensor (or scalar) to a numpy array."""
    if isinstance(x, np.ndarray):
        return x
    if is_torch_tensor(x):
        return x.detach().cpu().numpy()
    if is_tf_tensor(x):
        return x.numpy()
    # jax.Array and python scalars both take this path; np.asarray on a
    # jax.Array device-transfers without copy when already on host.
    return np.asarray(x)


def from_numpy_like(result, template):
    """Convert numpy ``result`` back to the framework/type of ``template``."""
    if isinstance(template, np.ndarray):
        return result
    if is_torch_tensor(template):
        t = _torch()
        out = t.from_numpy(np.ascontiguousarray(result))
        return out.to(device=template.device, dtype=template.dtype)
    if is_tf_tensor(template):
        tf = _tf()
        return tf.convert_to_tensor(result, dtype=template.dtype)
    if "jax" in sys.modules:
        import jax
        import jax.numpy as jnp

        if isinstance(template, jax.Array):
            return jnp.asarray(result)
    if np.isscalar(template) or isinstance(template, (int, float)):
        return result.item() if np.ndim(result) == 0 else result
    return result
