"""Input pipeline utilities: keep the MXU fed.

The reference has no in-tree data loader (SURVEY.md §2.1); the TPU
framing is simple — host batches must be on-device BEFORE the step
needs them. :func:`prefetch_to_device` runs host batch *production* on
a background daemon thread feeding a bounded queue, and double-buffers
the device transfers on the consuming thread: while step N computes,
batch N+1 is already transferring AND batch N+2 is being produced —
neither host production nor host→HBM latency sits between steps.
"""

import collections
import os

PREFETCH_DEPTH_ENV = "SPARKDL_TPU_PREFETCH_DEPTH"

_PREFETCH_THREAD_NAME = "sparkdl-tpu-prefetch"

# producer → consumer queue message kinds
_ITEM, _END, _ERR = "item", "end", "err"


def prefetch_to_device(iterator, size=2, sharding=None):
    """Wrap a host-batch iterator so both host batch production and
    device transfer overlap compute.

    :param iterator: yields pytrees of numpy arrays.
    :param size: device-side buffer depth (2 = classic double
        buffering). Also the default bound of the host-side producer
        queue; ``SPARKDL_TPU_PREFETCH_DEPTH`` overrides the queue
        bound alone (deeper host read-ahead for spiky producers).
    :param sharding: optional ``jax.sharding.Sharding`` (or pytree of
        them) for multi-chip placement; default = default device.

    **Truly-background production**: ``next(iterator)`` runs on a
    daemon producer thread (named ``sparkdl-tpu-prefetch``) into a
    bounded queue, so host batch production time is hidden even in the
    canonical ``for batch in prefetch_to_device(...): stepped(batch)``
    pattern — the consuming thread only dequeues and dispatches the
    (async) ``device_put``, keeping every transfer's dispatch order
    identical to the old synchronous refill. A producer exception is
    re-raised at the consumption point of the batch that failed, after
    the batches produced before it have been delivered. Closing the
    generator (``break`` + GC, or an explicit ``.close()``) stops and
    joins the producer thread and closes the underlying iterator — an
    abandoned pipeline leaves no live state behind.

    With telemetry opted in, each refill *wait* (the dequeue + the
    transfer dispatch) is a ``data.wait`` span on the consuming
    thread; these fall BETWEEN the instrumented step windows, so a
    starved pipeline (producer slower than the step) still surfaces
    as ``inter_step_data_wait_s`` in the ``observe.perf`` attribution
    report. A well-fed pipeline now shows near-zero wait even when
    producing a batch is slow — that cost moved off the consuming
    thread entirely.
    """
    import queue as queue_mod
    import threading

    import jax

    from sparkdl_tpu import observe

    depth = int(os.environ.get(PREFETCH_DEPTH_ENV, 0) or 0) or size
    hostq = queue_mod.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    # Host bytes sitting in the producer queue, exposed as the
    # "host_prefetch" accounting category. Single-writer counters
    # (producer bumps "in", consumer bumps "out") plus a FIFO of
    # per-batch sizes — queue order IS the dequeue order, so the
    # consumer charges off exactly what the producer charged on.
    # All of it latch-gated: telemetry off pays nothing per batch.
    mem_sizes = collections.deque()
    mem_acct = {"in": 0, "out": 0}
    if observe.enabled():
        from sparkdl_tpu.observe import mem as _mem

        _mem.register_tree(
            "host_prefetch",
            lambda: max(0, mem_acct["in"] - mem_acct["out"]))
        _batch_nbytes = _mem.tree_nbytes
    else:
        _batch_nbytes = None

    def produce():
        def put(msg):
            # bounded-blocking put that stays responsive to close():
            # a consumer gone away must not wedge this thread forever
            while not stop.is_set():
                try:
                    hostq.put(msg, timeout=0.05)
                    return True
                except queue_mod.Full:
                    continue
            return False

        try:
            for batch in iterator:
                if _batch_nbytes is not None:
                    nb = _batch_nbytes(batch)
                    mem_sizes.append(nb)
                    mem_acct["in"] += nb
                if not put((_ITEM, batch)):
                    return
            put((_END, None))
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            put((_ERR, e))

    thread = threading.Thread(
        target=produce, name=_PREFETCH_THREAD_NAME, daemon=True
    )

    devq = collections.deque()
    state = {"live": True, "err": None}

    def refill():
        """Move one produced host batch into the device buffer
        (dispatching its async transfer); flips ``live`` at end/error."""
        kind, val = hostq.get()
        if kind == _END:
            state["live"] = False
        elif kind == _ERR:
            state["live"] = False
            state["err"] = val
        else:
            if _batch_nbytes is not None and mem_sizes:
                mem_acct["out"] += mem_sizes.popleft()
            if sharding is None:
                devq.append(jax.device_put(val))
            else:
                devq.append(jax.device_put(val, sharding))

    def close():
        stop.set()
        thread.join(timeout=5.0)
        it_close = getattr(iterator, "close", None)
        if callable(it_close):
            try:
                it_close()
            except ValueError:
                # a generator source still executing inside a wedged
                # producer refuses close(); the daemon thread drops it
                pass

    thread.start()
    try:
        with observe.span("data.wait", cat="data", phase="prime"):
            for _ in range(size):
                if not state["live"]:
                    break
                refill()
        while devq:
            out = devq.popleft()
            if state["live"]:
                with observe.span("data.wait", cat="data"):
                    refill()
            yield out
        if state["err"] is not None:
            raise state["err"]
    finally:
        close()


def shard_for_rank(arrays, rank=None, size=None, *, drop_last=True):
    """Slice each leaf's leading axis to this gang member's contiguous
    shard (the data-parallel input split: each HorovodRunner worker
    reads only its 1/size of the epoch).

    rank/size default to the initialized gang
    (:mod:`sparkdl_tpu.hvd`); pass them explicitly outside a gang.
    """
    import jax

    if rank is None:
        from sparkdl_tpu import hvd

        rank = hvd.rank()
    if size is None:
        from sparkdl_tpu import hvd

        size = hvd.size()
    if not 0 <= rank < size:
        raise ValueError(f"rank {rank} outside [0, {size})")

    leaves = jax.tree.leaves(arrays)
    n = leaves[0].shape[0]
    if n < size:
        raise ValueError(
            f"cannot shard {n} rows across {size} ranks — every rank "
            f"would train on an empty shard (pass size<=n or feed "
            f"more data)"
        )
    if drop_last:
        per = n // size
        lo, hi = rank * per, (rank + 1) * per
    else:
        lo, hi = rank * n // size, (rank + 1) * n // size
    return jax.tree.map(lambda x: x[lo:hi], arrays)


def batched(arrays, batch_size, *, shuffle=False, seed=0, drop_last=True):
    """Minimal epoch iterator over a pytree of equally-long arrays."""
    import numpy as np

    import jax

    leaves = jax.tree.leaves(arrays)
    n = leaves[0].shape[0]
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    end = n - (n % batch_size) if drop_last else n
    for start in range(0, end, batch_size):
        sel = idx[start:start + batch_size]
        yield jax.tree.map(lambda x: x[sel], arrays)
