"""Input pipeline utilities: keep the MXU fed.

The reference has no in-tree data loader (SURVEY.md §2.1); the TPU
framing is simple — host batches must be on-device BEFORE the step
needs them. :func:`prefetch_to_device` double-buffers: while step N
computes, batch N+1 is already transferring, hiding host→HBM latency
behind compute.
"""

import collections
import itertools


def prefetch_to_device(iterator, size=2, sharding=None):
    """Wrap a host-batch iterator so device transfer overlaps compute.

    :param iterator: yields pytrees of numpy arrays.
    :param size: buffer depth (2 = classic double buffering).
    :param sharding: optional ``jax.sharding.Sharding`` (or pytree of
        them) for multi-chip placement; default = default device.

    With telemetry opted in, each refill (host batch production +
    dispatch of its device transfer) is a ``data.wait`` span on the
    consuming thread. In the canonical ``for batch in
    prefetch_to_device(...): stepped(batch)`` pattern these spans
    fall BETWEEN the instrumented step windows, so a starved pipeline
    surfaces as ``inter_step_data_wait_s`` in the ``observe.perf``
    attribution report (the per-step ``data_wait`` component only
    catches iterators consumed *inside* the step function). A
    well-fed pipeline shows near-zero wait either way.
    """
    import jax

    from sparkdl_tpu import observe

    queue = collections.deque()

    def put(batch):
        if sharding is None:
            queue.append(jax.device_put(batch))
        else:
            queue.append(jax.device_put(batch, sharding))

    with observe.span("data.wait", cat="data", phase="prime"):
        for batch in itertools.islice(iterator, size):
            put(batch)
    it = iterator
    while queue:
        out = queue.popleft()
        with observe.span("data.wait", cat="data"):
            for batch in itertools.islice(it, 1):
                put(batch)
        yield out


def shard_for_rank(arrays, rank=None, size=None, *, drop_last=True):
    """Slice each leaf's leading axis to this gang member's contiguous
    shard (the data-parallel input split: each HorovodRunner worker
    reads only its 1/size of the epoch).

    rank/size default to the initialized gang
    (:mod:`sparkdl_tpu.hvd`); pass them explicitly outside a gang.
    """
    import jax

    if rank is None:
        from sparkdl_tpu import hvd

        rank = hvd.rank()
    if size is None:
        from sparkdl_tpu import hvd

        size = hvd.size()
    if not 0 <= rank < size:
        raise ValueError(f"rank {rank} outside [0, {size})")

    leaves = jax.tree.leaves(arrays)
    n = leaves[0].shape[0]
    if n < size:
        raise ValueError(
            f"cannot shard {n} rows across {size} ranks — every rank "
            f"would train on an empty shard (pass size<=n or feed "
            f"more data)"
        )
    if drop_last:
        per = n // size
        lo, hi = rank * per, (rank + 1) * per
    else:
        lo, hi = rank * n // size, (rank + 1) * n // size
    return jax.tree.map(lambda x: x[lo:hi], arrays)


def batched(arrays, batch_size, *, shuffle=False, seed=0, drop_last=True):
    """Minimal epoch iterator over a pytree of equally-long arrays."""
    import numpy as np

    import jax

    leaves = jax.tree.leaves(arrays)
    n = leaves[0].shape[0]
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    end = n - (n % batch_size) if drop_last else n
    for start in range(0, end, batch_size):
        sel = idx[start:start + batch_size]
        yield jax.tree.map(lambda x: x[sel], arrays)
