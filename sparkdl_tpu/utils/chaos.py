"""Fault-injection harness for gang fault-tolerance testing.

The supervisor's retry/resume loop (:mod:`sparkdl_tpu.horovod.
supervisor`) is only trustworthy if it has been exercised under an
adversarial schedule — a preempted rank mid-step, a stalled
rendezvous, dropped control-plane frames. This module provides those
faults as **env-driven hooks**: entirely inert (a cached boolean
check) unless a ``SPARKDL_TPU_CHAOS_*`` variable is set in the
worker's environment, so production gangs pay nothing.

Hook points:

- ``chaos_step(step)`` — called by chaos-aware training mains once
  per step: kills this process with the configured signal when this
  rank/step matches (``KILL_RANK`` / ``KILL_STEP``). SIGKILL is the
  default because that is what preemption looks like from the driver:
  no EXC frame, a negative exit code.
- ``on_worker_boot(rank)`` — called by ``_worker.py`` before the gang
  rendezvous: stalls (``RENDEZVOUS_STALL_S``) or kills
  (``KILL_PHASE=boot``) the chosen rank, exercising the launcher's
  fail-fast rendezvous abort and start-timeout paths.
- ``control_frame_fate(mtype)`` — consulted by the worker-side
  control-plane client per frame: returns ``"drop"``, a delay in
  seconds, or ``None`` (``CP_DROP`` / ``CP_DELAY_S``). Dropping READY
  stalls the gang barrier; dropping RESULT exercises the lost-result
  path. (The native log ring is not hooked: log frames are droppable
  by design.)

Env contract (all read in the WORKER process, so the launcher's
per-gang env — or a test's monkeypatch before launch — scopes them):

- ``SPARKDL_TPU_CHAOS_KILL_RANK``: rank to kill (int).
- ``SPARKDL_TPU_CHAOS_KILL_STEP``: step at which ``chaos_step`` fires
  (default 0).
- ``SPARKDL_TPU_CHAOS_KILL_SIGNAL``: signal number (default SIGKILL).
- ``SPARKDL_TPU_CHAOS_KILL_PHASE``: ``step`` (default) or ``boot``.
- ``SPARKDL_TPU_CHAOS_ONCE_FILE``: path; the kill fires only if this
  file does not exist and is claimed atomically first — ONE injected
  death per path, so a supervised relaunch completes.
- ``SPARKDL_TPU_CHAOS_RENDEZVOUS_STALL_S``: seconds to stall before
  the rendezvous.
- ``SPARKDL_TPU_CHAOS_RENDEZVOUS_STALL_RANK``: rank that stalls
  (default: all ranks).
- ``SPARKDL_TPU_CHAOS_CP_DELAY_S``: delay every control frame.
- ``SPARKDL_TPU_CHAOS_CP_DROP``: comma list of frame names to drop:
  READY, LOG, USERLOG, RESULT, EXC, BYE, HEARTBEAT, STACK_DUMP.
- ``SPARKDL_TPU_CHAOS_STALL_STEP``: step at which ``chaos_step``
  hangs this rank INSIDE the step, forever — the process stays
  alive and its heartbeat thread keeps beating, which is exactly
  the silent-hang signature the driver's HangDetector exists to
  catch (docs/observability.rst). Honors the ONCE file so a
  supervised relaunch runs clean.
- ``SPARKDL_TPU_CHAOS_STALL_STEP_RANK``: rank that stalls in-step
  (default 0).
- ``SPARKDL_TPU_CHAOS_MUTE_HEARTBEAT``: rank whose heartbeat
  beacons stop while the process stays alive — exercises the
  detector's *silent* verdict (beats lost without a process death).
- ``SPARKDL_TPU_CHAOS_LEAK_BYTES_PER_STEP``: bytes of host memory
  deliberately leaked by ``chaos_step`` on EVERY step (no ONCE
  gating — a leak is a trend, not an event), held in a module-level
  list so RSS grows at a known per-step slope. This is the
  end-to-end proof harness for the mem-doctor leak rules
  (``host_rss_growth`` / ``hbm_leak``, ISSUE 18): inject → sampler
  sees RSS grow → alert fires → doctor names the category.
- ``SPARKDL_TPU_CHAOS_LEAK_RANK``: rank that leaks (default: all
  ranks).
"""

import os
import signal
import time

_PREFIX = "SPARKDL_TPU_CHAOS_"

KILL_RANK_ENV = _PREFIX + "KILL_RANK"
KILL_STEP_ENV = _PREFIX + "KILL_STEP"
KILL_SIGNAL_ENV = _PREFIX + "KILL_SIGNAL"
KILL_PHASE_ENV = _PREFIX + "KILL_PHASE"
ONCE_FILE_ENV = _PREFIX + "ONCE_FILE"
STALL_S_ENV = _PREFIX + "RENDEZVOUS_STALL_S"
STALL_RANK_ENV = _PREFIX + "RENDEZVOUS_STALL_RANK"
CP_DELAY_ENV = _PREFIX + "CP_DELAY_S"
CP_DROP_ENV = _PREFIX + "CP_DROP"
STALL_STEP_ENV = _PREFIX + "STALL_STEP"
STALL_STEP_RANK_ENV = _PREFIX + "STALL_STEP_RANK"
MUTE_HEARTBEAT_ENV = _PREFIX + "MUTE_HEARTBEAT"
LEAK_BYTES_PER_STEP_ENV = _PREFIX + "LEAK_BYTES_PER_STEP"
LEAK_RANK_ENV = _PREFIX + "LEAK_RANK"

# The injected leak: one bytearray per step, never released. Written
# (not just reserved) so the kernel actually backs the pages and VmRSS
# moves — a reserved-but-untouched mapping leaks nothing measurable.
_leaked = []

# Lazily-latched per process: gangs ship chaos env at spawn, so one
# check at first hook call suffices and the common (chaos-off) path
# stays a single `is False` test forever after.
_active = None


def _chaos_active():
    global _active
    if _active is None:
        _active = any(k.startswith(_PREFIX) for k in os.environ)
    return _active


def _reset_cache_for_tests():
    global _active
    _active = None
    del _leaked[:]


def _rank():
    return int(os.environ.get("SPARKDL_TPU_RANK", "0"))


def _claim_once():
    """Atomically claim the one-shot kill token. True = this process
    owns the kill. With no ONCE file configured every match kills
    (the retry-budget-exhaustion schedule)."""
    path = os.environ.get(ONCE_FILE_ENV)
    if not path:
        return True
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    except OSError:
        return False  # unwritable token dir: fail safe, don't kill
    os.close(fd)
    return True


def _kill_self(phase="step", step=None):
    sig = int(os.environ.get(KILL_SIGNAL_ENV, str(int(signal.SIGKILL))))
    # The injection is a first-class timeline instant, flushed
    # SYNCHRONOUSLY over the control plane before the signal: SIGKILL
    # leaves no other trace, and the merged gang timeline must show
    # the kill at its true (rank, step) for the chaos story to read
    # kill → classified → resumed. Inert when telemetry is off.
    from sparkdl_tpu import observe

    observe.instant("chaos.kill", cat="chaos", rank=_rank(),
                    phase=phase, step=step, sig=sig)
    observe.flush()
    # Flush whatever the tee has buffered: the postmortem log should
    # show the last step line before the "preemption".
    try:
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    os.kill(os.getpid(), sig)
    # A catchable signal (e.g. SIGTERM under test) may not have fired
    # yet; give delivery a beat rather than racing ahead.
    time.sleep(5)


def _stall_in_step(step):
    """Hang this rank inside the step, forever. The process — and
    crucially its heartbeat thread — stays alive: from the driver
    this is a rank whose beats continue while its progress counter
    freezes, the signature the HangDetector turns into stall → hang
    verdicts, a stack dump naming THIS frame, and a supervised
    relaunch under the HANG cause."""
    from sparkdl_tpu import observe

    observe.instant("chaos.stall_in_step", cat="chaos", rank=_rank(),
                    step=int(step))
    observe.flush()
    try:
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    while True:         # until the launcher reaps the hung gang
        time.sleep(1)


def chaos_step(step):
    """Training-main hook: die (or hang) here if this (rank, step) is
    the configured injection point. No-op without chaos env."""
    if not _chaos_active():
        return
    leak = os.environ.get(LEAK_BYTES_PER_STEP_ENV)
    if leak is not None:
        leak_rank = os.environ.get(LEAK_RANK_ENV)
        if leak_rank is None or int(leak_rank) == _rank():
            n = int(leak)
            if n > 0:
                buf = bytearray(n)
                buf[::4096] = b"\x01" * len(buf[::4096])  # touch pages
                _leaked.append(buf)
    stall_step = os.environ.get(STALL_STEP_ENV)
    if (stall_step is not None
            and int(stall_step) == int(step)
            and int(os.environ.get(STALL_STEP_RANK_ENV, "0")) == _rank()
            and _claim_once()):
        _stall_in_step(step)
    kill_rank = os.environ.get(KILL_RANK_ENV)
    if kill_rank is None or int(kill_rank) != _rank():
        return
    if os.environ.get(KILL_PHASE_ENV, "step") != "step":
        return
    if int(step) != int(os.environ.get(KILL_STEP_ENV, "0")):
        return
    if _claim_once():
        _kill_self(phase="step", step=int(step))


def heartbeat_muted(rank):
    """Heartbeat-sender hook: True when this rank's beacons are
    chaos-muted (process alive, beats gone — the detector's *silent*
    verdict). No-op without chaos env."""
    if not _chaos_active():
        return False
    muted = os.environ.get(MUTE_HEARTBEAT_ENV)
    return muted is not None and int(muted) == int(rank)


def on_worker_boot(rank):
    """Worker bootstrap hook (before the gang rendezvous): stall or
    kill the chosen rank. No-op without chaos env."""
    if not _chaos_active():
        return
    stall = float(os.environ.get(STALL_S_ENV, "0") or 0)
    if stall > 0:
        stall_rank = os.environ.get(STALL_RANK_ENV)
        if stall_rank is None or int(stall_rank) == rank:
            from sparkdl_tpu import observe

            observe.instant("chaos.stall", cat="chaos", rank=rank,
                            stall_s=stall)
            time.sleep(stall)
    if os.environ.get(KILL_PHASE_ENV) == "boot":
        kill_rank = os.environ.get(KILL_RANK_ENV)
        if kill_rank is not None and int(kill_rank) == rank:
            if _claim_once():
                _kill_self(phase="boot")


def control_frame_fate(mtype_name):
    """Control-plane client hook: ``"drop"``, a float delay in
    seconds, or ``None`` for the given frame name."""
    if not _chaos_active():
        return None
    drop = os.environ.get(CP_DROP_ENV, "")
    if drop and mtype_name in {
        t.strip().upper() for t in drop.split(",") if t.strip()
    }:
        # Recorded, not flushed: this runs inside the control-plane
        # send path, and a flush here would recurse into it.
        from sparkdl_tpu import observe

        observe.instant("chaos.frame_drop", cat="chaos", rank=_rank(),
                        frame=mtype_name)
        return "drop"
    delay = float(os.environ.get(CP_DELAY_ENV, "0") or 0)
    return delay if delay > 0 else None
