"""Per-rank profiling (SURVEY.md §5.1 — absent in the reference, where
the only observability is log-based; here every worker can capture a
JAX profiler trace viewable in TensorBoard/Perfetto/xprof).

Enable for a whole HorovodRunner job by exporting
``SPARKDL_TPU_PROFILE=/path/to/dir`` on the driver: each worker writes
``<dir>/rank-<r>`` (wired in the worker bootstrap). Or use
:func:`trace` directly around any region.
"""

import contextlib
import os

PROFILE_ENV = "SPARKDL_TPU_PROFILE"


@contextlib.contextmanager
def trace(log_dir):
    """Capture a JAX profiler trace of the enclosed region."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def maybe_trace_worker(rank):
    """Trace this worker if the job was launched with profiling on."""
    base = os.environ.get(PROFILE_ENV)
    if not base:
        yield None
        return
    with trace(os.path.join(base, f"rank-{rank}")) as d:
        yield d


@contextlib.contextmanager
def annotate(name):
    """Named region in BOTH trace timelines under the SAME name:

    - the xprof trace (``jax.profiler.TraceAnnotation``) captured by
      :func:`trace`/:func:`maybe_trace_worker`, viewable per rank in
      TensorBoard/Perfetto; and
    - the gang event timeline (:func:`sparkdl_tpu.observe.span`,
      ``cat="xprof"``), merged across ranks into
      ``SPARKDL_TPU_TELEMETRY_DIR/run-*/timeline.json``.

    The shared name is the correlation key: find a region in the
    merged gang timeline, then open that rank's xprof trace and search
    the same name to drill from gang-level wall time into per-op
    device time (``docs/observability.rst``). The observe side is a
    no-op when telemetry is off; the xprof side is a no-op outside a
    capture, so ``annotate`` is always safe to leave in."""
    import jax

    from sparkdl_tpu import observe

    with jax.profiler.TraceAnnotation(name):
        with observe.span(name, cat="xprof"):
            yield
