"""Per-rank profiling (SURVEY.md §5.1 — absent in the reference, where
the only observability is log-based; here every worker can capture a
JAX profiler trace viewable in TensorBoard/Perfetto/xprof).

Enable for a whole HorovodRunner job by exporting
``SPARKDL_TPU_PROFILE=/path/to/dir`` on the driver: each worker writes
``<dir>/rank-<r>`` (wired in the worker bootstrap). Or use
:func:`trace` directly around any region.
"""

import contextlib
import os

PROFILE_ENV = "SPARKDL_TPU_PROFILE"


@contextlib.contextmanager
def trace(log_dir):
    """Capture a JAX profiler trace of the enclosed region."""
    import jax

    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def maybe_trace_worker(rank):
    """Trace this worker if the job was launched with profiling on."""
    base = os.environ.get(PROFILE_ENV)
    if not base:
        yield None
        return
    with trace(os.path.join(base, f"rank-{rank}")) as d:
        yield d


def annotate(name):
    """Named region in the trace timeline (jax.profiler.TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
