"""Pipeline parallelism: GPipe-style microbatch streaming over a
``stage`` mesh axis.

Each device owns one stage's parameters (the stacked per-stage param
tree is sharded on its leading axis); microbatches enter at stage 0,
ride neighbor-to-neighbor ``ppermute`` hops (pure ICI traffic) through
the stages, and the final stage's outputs are collected.

Two hop schedules:

- ``overlap=True`` (default): the stage-to-stage hop is software-
  pipelined — each tick's ``ppermute`` ships the PREVIOUS tick's
  output while this tick's ``stage_fn`` computes on the activation
  that already arrived, so the wire transfer and the stage compute
  have no data dependence inside the tick and XLA's async collective
  scheduler can overlap them. An activation spends one compute tick
  plus one (hidden) transit tick per stage, so the schedule runs
  ``M + 2(P-1)`` ticks — bubble fraction ``2(P-1)/(M+2(P-1))``; pick
  M >= 8P to keep >80% utilization. Worth it exactly when the hop is
  ICI-bound: the serialized schedule pays the full wire latency on
  every tick of every stage.
- ``overlap=False``: the legacy serialized schedule — ``stage_fn``
  then the hop inside one tick, ``M + P - 1`` ticks, every hop a
  barrier between two ticks' compute.

Both schedules apply the same stage compositions to the same
microbatches — outputs are identical (pinned by tests).

Differentiable end to end: JAX transposes ``ppermute``/``scan``
automatically, so ``jax.grad`` through :func:`pipeline_apply` yields
the standard GPipe backward schedule without extra code — idiomatic
XLA pipelining rather than a hand-scheduled runtime (the reference has
no pipeline parallelism at all, SURVEY.md §2.3).
"""

import jax
import jax.numpy as jnp

from sparkdl_tpu.utils.jax_compat import axis_size
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn, stacked_params, microbatches, *,
                   axis_name="stage", overlap=None):
    """Run inside ``shard_map``: stream microbatches through stages.

    :param stage_fn: ``f(params_i, x) -> y`` applied by each stage
        (y.shape == x.shape — e.g. a group of transformer blocks).
    :param stacked_params: this device's stage params, leading axis 1
        (the shard of a (P, ...) stacked tree).
    :param microbatches: (M, mb, ...) — replicated across stages; only
        stage 0 reads them.
    :param overlap: software-pipelined hop schedule (default; ``None``
        resolves the ``SPARKDL_TPU_OVERLAP`` env knob) vs the
        serialized legacy lowering (see module docstring).
    :return: (M, mb, ...) outputs, replicated (psum-collected from the
        last stage).
    """
    from sparkdl_tpu.parallel.ring_attention import resolve_overlap

    overlap = resolve_overlap(overlap)
    n_stages = axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    params_local = jax.tree.map(lambda x: x[0], stacked_params)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    mb_shape = microbatches.shape[1:]
    # ticks an activation needs to clear the pipe: one compute tick
    # per stage, plus (overlap) one transit tick per hop
    lag = (2 if overlap else 1) * (n_stages - 1)
    n_ticks = m + lag

    def inject(cur, t):
        # stage 0 injects microbatch t (while t < m)
        mb = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, m - 1), axis=0, keepdims=False
        )
        return jnp.where(jnp.logical_and(stage == 0, t < m), mb, cur)

    def collect(outputs, y, t):
        # last stage collects finished microbatch t - lag
        out_idx = t - lag
        take = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        return jax.lax.cond(
            take,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )

    if overlap:
        def tick(carry, t):
            cur, sent, outputs = carry
            # ship the PREVIOUS tick's output first: the hop's only
            # dependence is an already-computed activation, so it
            # rides the interconnect while stage_fn computes below
            recv = jax.lax.ppermute(sent, axis_name, perm)
            cur = inject(cur, t)
            y = stage_fn(params_local, cur)
            outputs = collect(outputs, y, t)
            # next tick computes on what just arrived and ships y
            return (recv, y, outputs), None
    else:
        def tick(carry, t):
            cur, outputs = carry
            cur = inject(cur, t)
            y = stage_fn(params_local, cur)
            outputs = collect(outputs, y, t)
            # hop to the next stage (ICI neighbor exchange)
            cur = jax.lax.ppermute(y, axis_name, perm)
            return (cur, outputs), None

    cur0 = jnp.zeros(mb_shape, microbatches.dtype)
    out0 = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    carry0 = (cur0, cur0, out0) if overlap else (cur0, out0)
    carry, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
    outputs = carry[-1]
    # replicate the last stage's collected outputs to every stage
    keep = (stage == n_stages - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * keep, axis_name)


def make_pipeline(mesh, stage_fn, *, axis_name="stage", overlap=True):
    """Bind a pipeline to a mesh: returns ``f(stacked_params,
    microbatches) -> outputs`` on GLOBAL arrays, where stacked_params'
    leading axis (= number of stages) is sharded over ``axis_name`` and
    microbatches are replicated. ``overlap`` selects the hop schedule
    (see :func:`pipeline_apply`)."""

    def run(stacked_params, microbatches):
        return pipeline_apply(
            stage_fn, stacked_params, microbatches, axis_name=axis_name,
            overlap=overlap,
        )

    n_stages = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    def spec_for(leaf):
        return P(axis_name, *([None] * (leaf.ndim - 1)))

    def call(stacked_params, microbatches):
        in_specs = (
            jax.tree.map(spec_for, stacked_params),
            P(),
        )
        from sparkdl_tpu.utils.jax_compat import shard_map

        fn = shard_map(
            run, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False,
        )
        return fn(stacked_params, microbatches)

    call.n_stages = n_stages
    return call
