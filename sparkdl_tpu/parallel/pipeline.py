"""Pipeline parallelism: GPipe-style microbatch streaming over a
``stage`` mesh axis.

Each device owns one stage's parameters (the stacked per-stage param
tree is sharded on its leading axis); microbatches enter at stage 0,
ride neighbor-to-neighbor ``ppermute`` hops (pure ICI traffic) through
the stages, and the final stage's outputs are collected. With M
microbatches and P stages the schedule runs M + P - 1 ticks; bubble
fraction (P-1)/(M+P-1) — pick M >= 4P for >80% utilization.

Differentiable end to end: JAX transposes ``ppermute``/``scan``
automatically, so ``jax.grad`` through :func:`pipeline_apply` yields
the standard GPipe backward schedule without extra code — idiomatic
XLA pipelining rather than a hand-scheduled runtime (the reference has
no pipeline parallelism at all, SURVEY.md §2.3).
"""

import jax
import jax.numpy as jnp

from sparkdl_tpu.utils.jax_compat import axis_size
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn, stacked_params, microbatches, *,
                   axis_name="stage"):
    """Run inside ``shard_map``: stream microbatches through stages.

    :param stage_fn: ``f(params_i, x) -> y`` applied by each stage
        (y.shape == x.shape — e.g. a group of transformer blocks).
    :param stacked_params: this device's stage params, leading axis 1
        (the shard of a (P, ...) stacked tree).
    :param microbatches: (M, mb, ...) — replicated across stages; only
        stage 0 reads them.
    :return: (M, mb, ...) outputs, replicated (psum-collected from the
        last stage).
    """
    n_stages = axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    params_local = jax.tree.map(lambda x: x[0], stacked_params)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    mb_shape = microbatches.shape[1:]
    n_ticks = m + n_stages - 1

    def tick(carry, t):
        cur, outputs = carry
        # stage 0 injects microbatch t (while t < m)
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, m - 1), axis=0, keepdims=False
        )
        cur = jnp.where(
            jnp.logical_and(stage == 0, t < m), inject, cur
        )
        y = stage_fn(params_local, cur)
        # last stage collects finished microbatch t - (P-1)
        out_idx = t - (n_stages - 1)
        collect = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        outputs = jax.lax.cond(
            collect,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), axis=0
            ),
            lambda o: o,
            outputs,
        )
        # hop to the next stage (ICI neighbor exchange)
        cur = jax.lax.ppermute(y, axis_name, perm)
        return (cur, outputs), None

    cur0 = jnp.zeros(mb_shape, microbatches.dtype)
    out0 = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    (cur, outputs), _ = jax.lax.scan(
        tick, (cur0, out0), jnp.arange(n_ticks)
    )
    # replicate the last stage's collected outputs to every stage
    keep = (stage == n_stages - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * keep, axis_name)


def make_pipeline(mesh, stage_fn, *, axis_name="stage"):
    """Bind a pipeline to a mesh: returns ``f(stacked_params,
    microbatches) -> outputs`` on GLOBAL arrays, where stacked_params'
    leading axis (= number of stages) is sharded over ``axis_name`` and
    microbatches are replicated."""

    def run(stacked_params, microbatches):
        return pipeline_apply(
            stage_fn, stacked_params, microbatches, axis_name=axis_name
        )

    n_stages = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    def spec_for(leaf):
        return P(axis_name, *([None] * (leaf.ndim - 1)))

    def call(stacked_params, microbatches):
        in_specs = (
            jax.tree.map(spec_for, stacked_params),
            P(),
        )
        from sparkdl_tpu.utils.jax_compat import shard_map

        fn = shard_map(
            run, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False,
        )
        return fn(stacked_params, microbatches)

    call.n_stages = n_stages
    return call
