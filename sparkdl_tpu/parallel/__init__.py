"""Multi-chip parallelism: mesh construction, sharding rules, pjit
train steps, sequence-parallel ring attention, and pipeline stages.

The reference supports only Horovod-style data parallelism (SURVEY.md
§2.3: "DP — the only one"); this package is the TPU-native superset the
build plan calls for — a ``('data','fsdp','seq','model')`` mesh where
DP is one axis among several, so the same runner scales JAX mains from
MNIST to the Llama-LoRA north-star config (BASELINE.json) without
changing the launcher.
"""

from sparkdl_tpu.parallel.mesh import MeshSpec, best_mesh, make_mesh  # noqa: F401
from sparkdl_tpu.parallel.sharding import (  # noqa: F401
    constrain,
    param_sharding,
)
