"""Warm-start compilation: persistent XLA compile cache + serialized
AOT step executables across gang relaunches.

PR 1 made supervised relaunch the *normal* recovery path for a
preempted gang — but every relaunched attempt still re-paid the full
trace + XLA compile of the train step (minutes at Llama scale) before
the first resumed step executed. Production trainers (MaxText et al.)
solve exactly this with ahead-of-time compilation plus JAX's
persistent compilation cache; this module is that story for
HorovodRunner gangs, in two layers:

1. :func:`enable_persistent_cache` — turn on JAX's *persistent
   compilation cache* (``jax_compilation_cache_dir``), version-shimmed
   via :mod:`sparkdl_tpu.utils.jax_compat`, with sane
   min-compile-time/min-entry-size knobs. Every ``jit`` in the process
   then reuses on-disk XLA artifacts across process restarts — no code
   changes in user mains.
2. :class:`CompiledStepCache` — serialize the *whole compiled step
   executable* (``jax.experimental.serialize_executable``) keyed by a
   fingerprint of (jax version, backend/platform, topology, compile
   options, StableHLO module hash). ``load_or_compile(lowered)`` turns
   restart-to-first-step from a compile-bound stall into a
   deserialize-and-go, and reuses the single lowering
   :func:`sparkdl_tpu.parallel.train.lower_train_step` /
   ``analysis.register_preflight`` already produce — nothing is traced
   twice::

       lowered = lower_train_step(step, params, opt_state, batch,
                                  mesh=mesh)
       analysis.register_preflight(lowered)        # graph lint
       compiled = CompiledStepCache().load_or_compile(lowered)

Gang wiring: set ``SPARKDL_TPU_COMPILE_CACHE_DIR`` on the driver; the
launcher ships it to every worker (local, remote and supervised
relaunches alike) and ``_worker.py`` calls
:func:`enable_persistent_cache` *before* backend init, so a preempted
rank's replacement warm-starts from its predecessor's cache entries.

Degradation contract: a corrupt, truncated, or fingerprint-mismatched
AOT entry falls back to a cold ``lowered.compile()`` with a WARNING —
never an exception — and the entry is rewritten. Cache files are
host-local pickles; treat the cache dir with the same trust as the
code dir (the operator owns both).

Observability (:mod:`sparkdl_tpu.observe`, off by default):
``compile_cache_hits_total`` / ``compile_cache_misses_total``
counters, a ``compile_seconds{source="cache"|"xla"}`` histogram, and
``compile_cache.hit`` / ``compile_cache.miss`` timeline instants — so
a chaos run's merged trace visibly shows cold-compile on attempt 1
and cache-hit on attempt 2.
"""

import hashlib
import logging
import os
import pickle
import tempfile
import time

logger = logging.getLogger("HorovodRunner")

COMPILE_CACHE_DIR_ENV = "SPARKDL_TPU_COMPILE_CACHE_DIR"
MIN_COMPILE_S_ENV = "SPARKDL_TPU_COMPILE_CACHE_MIN_COMPILE_S"
MIN_ENTRY_BYTES_ENV = "SPARKDL_TPU_COMPILE_CACHE_MIN_BYTES"
MAX_AOT_ENTRIES_ENV = "SPARKDL_TPU_COMPILE_CACHE_MAX_AOT"

# AOT entries have no natural eviction (every jax upgrade or graph
# change strands the old fingerprint's file forever), so writes prune
# beyond a cap, oldest-mtime first. The default leaves room for a
# full pod host's worth of per-rank entries across a few program
# versions; real Llama-scale executables are large, so the cap is
# deliberately modest.
DEFAULT_MAX_AOT_ENTRIES = 64

# Persist anything that took >= 1s to compile regardless of size, and
# anything at all above 0 bytes after that gate: the cache exists for
# the minutes-long train-step compile, but a relaunch also re-pays
# many sub-second helper jits whose artifacts are cheap to keep.
DEFAULT_MIN_COMPILE_S = 1.0
DEFAULT_MIN_ENTRY_BYTES = 0

# Format 2 added `memory_stats` to the entry (recorded at write time —
# a deserialized executable's memory_analysis drops alias accounting,
# and the bench's step_peak_bytes contract needs the real figures on
# warm starts too). Format-1 entries simply cold-recompile once.
_AOT_FORMAT = 2

_persistent_cache_dir = None  # latched by enable_persistent_cache


def persistent_cache_dir(environ=None):
    """The configured cache root (env), or None when warm-start
    compilation is not opted in."""
    env = os.environ if environ is None else environ
    return env.get(COMPILE_CACHE_DIR_ENV) or None


def enable_persistent_cache(cache_dir=None):
    """Turn on JAX's persistent compilation cache under ``cache_dir``
    (default: ``SPARKDL_TPU_COMPILE_CACHE_DIR``). Returns the resolved
    directory, or None when no directory is configured (no-op — the
    opt-out path costs one env read).

    Must run before the first compilation to be effective; the gang
    worker bootstrap calls it before backend init. Idempotent: calling
    again with the same dir is free, with a different dir re-points
    the cache (jax re-reads the config at the next compile).
    """
    cache_dir = cache_dir or persistent_cache_dir()
    if not cache_dir:
        return None
    global _persistent_cache_dir
    # The whole degrade contract applies HERE too: this runs at worker
    # bootstrap before the control plane exists, so an unwritable dir
    # (a mount one host lacks) or a malformed threshold env must WARN
    # and continue cold — raising would kill every rank of every
    # supervised attempt with a boot death the driver can't explain.
    try:
        cache_dir = os.path.abspath(cache_dir)
        os.makedirs(cache_dir, exist_ok=True)
        from sparkdl_tpu.utils import jax_compat

        jax_compat.enable_compilation_cache(
            cache_dir,
            min_compile_time_secs=float(
                os.environ.get(MIN_COMPILE_S_ENV, DEFAULT_MIN_COMPILE_S)),
            min_entry_size_bytes=int(
                os.environ.get(MIN_ENTRY_BYTES_ENV,
                               DEFAULT_MIN_ENTRY_BYTES)),
        )
    except Exception as e:
        logger.warning(
            "persistent compile cache unavailable under %s (%s: %s); "
            "continuing with cold compiles",
            cache_dir, type(e).__name__, e,
        )
        return None
    if _persistent_cache_dir != cache_dir:
        _persistent_cache_dir = cache_dir
        logger.info("persistent XLA compile cache enabled: %s", cache_dir)
    return cache_dir


def topology_descriptor():
    """A stable string naming the world this process compiles for:
    platform, device kind, device/process counts, this process's index
    and its local device ids. Any change (a v5e cache served to a v4
    gang, a resized gang) must miss — a serialized executable is only
    valid on the topology it was built for. The per-process fields
    matter inside a gang: each rank's single-device step executable
    embeds ITS device assignment, so rank 1 must never deserialize
    rank 0's entry (the runtime would reject it — "does not have any
    local devices"). Same-rank relaunches land on the same index/ids
    and hit."""
    import jax

    devs = jax.devices()
    return "|".join((
        devs[0].platform,
        getattr(devs[0], "device_kind", "") or "",
        f"d{len(devs)}",
        f"p{jax.process_count()}",
        f"i{jax.process_index()}",
        "l" + ",".join(str(d.id) for d in jax.local_devices()),
    ))


def step_fingerprint(stablehlo_text, *, topology=None,
                     compiler_options=None):
    """Content-address one lowered program for the AOT executable
    cache: sha256 over (jax version, topology descriptor, compile
    options, StableHLO module text). The StableHLO hash — not the
    Python function — is the identity, so an edited-but-equivalent
    main still hits and any real graph change misses."""
    from sparkdl_tpu.utils import jax_compat

    if topology is None:
        topology = topology_descriptor()
    h = hashlib.sha256()
    h.update(f"aot{_AOT_FORMAT}".encode())
    h.update(("." .join(map(str, jax_compat.jax_version()))).encode())
    h.update(b"\0" + topology.encode())
    opts = sorted((compiler_options or {}).items())
    h.update(b"\0" + repr(opts).encode())
    h.update(b"\0" + stablehlo_text.encode())
    return h.hexdigest()


class CompiledStepCache:
    """Disk cache of AOT-compiled step executables.

    One entry per :func:`step_fingerprint`, written atomically
    (tmp + rename) so a preemption mid-write leaves no torn entry for
    the replacement rank to trip on. ``hits`` / ``misses`` count this
    instance's outcomes (the bench reports ``warm_start`` off them);
    the gang-wide view rides the observe counters.
    """

    def __init__(self, cache_dir=None):
        cache_dir = cache_dir or persistent_cache_dir()
        if not cache_dir:
            raise ValueError(
                "CompiledStepCache needs a cache directory: pass one or "
                f"set {COMPILE_CACHE_DIR_ENV}"
            )
        self.cache_dir = os.path.abspath(cache_dir)
        os.makedirs(self.cache_dir, exist_ok=True)
        self.hits = 0
        self.misses = 0
        # Compiled memory analysis of the newest load_or_compile
        # result. Persisted inside the cache entry at write time
        # because a DESERIALIZED executable's runtime drops the alias
        # accounting (alias_size reads 0) — without the stored stats a
        # warm-started bench would overstate its own peak.
        self.last_memory_stats = None
        # Device-side program footprint of every executable this
        # instance served (generated_code_size_in_bytes per
        # fingerprint), exposed as the "compile_cache" accounting
        # category — no-op without the telemetry latch.
        self._code_bytes = {}
        from sparkdl_tpu.observe import mem as mem_acct

        mem_acct.register_tree(
            "compile_cache", lambda: sum(self._code_bytes.values()))

    def _entry_path(self, fingerprint):
        return os.path.join(self.cache_dir, f"aot-{fingerprint}.bin")

    def fingerprint(self, lowered, compiler_options=None, topology=None):
        from sparkdl_tpu.utils import jax_compat

        return step_fingerprint(
            jax_compat.lowered_stablehlo(lowered),
            topology=topology,
            compiler_options=compiler_options,
        )

    def _try_load(self, path, fingerprint):
        """The deserialization path, wrapped so EVERY failure mode —
        missing file, truncated pickle, foreign format, fingerprint
        drift, a deserialize the runtime rejects — degrades to a cold
        compile. Returns a Compiled or None."""
        from sparkdl_tpu.utils import jax_compat

        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
            if (entry.get("format") != _AOT_FORMAT
                    or entry.get("fingerprint") != fingerprint):
                raise ValueError(
                    f"entry format/fingerprint mismatch "
                    f"(format={entry.get('format')!r})"
                )
            compiled = jax_compat.deserialize_compiled(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
            # Stats recorded at write time (guaranteed present since
            # format 2): the deserialized runtime's own
            # memory_analysis loses alias accounting.
            self.last_memory_stats = entry.get("memory_stats")
            return compiled
        except FileNotFoundError:
            return None
        except Exception as e:
            logger.warning(
                "compile cache entry %s unusable (%s: %s); falling back "
                "to cold compile and rewriting it",
                os.path.basename(path), type(e).__name__, e,
            )
            return None

    def _write(self, path, fingerprint, compiled):
        from sparkdl_tpu.utils import jax_compat

        try:
            payload, in_tree, out_tree = jax_compat.serialize_compiled(
                compiled)
            blob = pickle.dumps({
                "format": _AOT_FORMAT,
                "fingerprint": fingerprint,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
                # Kept alongside the executable: deserialization loses
                # the alias accounting, so a warm start reads the peak
                # from here instead of a zeroed memory_analysis().
                # load_or_compile records (and alias-corrects) the
                # stats just before every _write.
                "memory_stats": self.last_memory_stats,
            })
            fd, tmp = tempfile.mkstemp(
                dir=self.cache_dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:
            # An unwritable/full cache dir must never fail the step
            # that just compiled fine.
            logger.warning(
                "could not persist AOT step executable to %s (%s: %s)",
                path, type(e).__name__, e,
            )
            return
        self._prune()

    def _prune(self):
        """Drop the oldest AOT entries beyond the cap — superseded
        fingerprints (jax upgrades, graph edits) can never hit again
        and would otherwise accumulate forever. Best-effort: a
        concurrent rank unlinking the same file is fine."""
        try:
            cap = int(os.environ.get(
                MAX_AOT_ENTRIES_ENV, DEFAULT_MAX_AOT_ENTRIES))
            entries = []
            for name in os.listdir(self.cache_dir):
                if not (name.startswith("aot-") and name.endswith(".bin")):
                    continue
                p = os.path.join(self.cache_dir, name)
                try:
                    entries.append((os.stat(p).st_mtime, p))
                except OSError:
                    continue
            for _, p in sorted(entries)[:max(0, len(entries) - cap)]:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        except Exception:
            pass

    def load_or_compile(self, lowered, *, name="train_step",
                        compiler_options=None):
        """Return a ready ``jax.stages.Compiled`` for ``lowered``:
        deserialized from the cache on a fingerprint hit, else cold-
        compiled (and the entry written for the next incarnation).
        ``compiler_options`` are part of the fingerprint AND forwarded
        to the cold compile, so an options change can never serve a
        stale executable."""
        from sparkdl_tpu import observe

        fp = self.fingerprint(lowered, compiler_options=compiler_options)
        path = self._entry_path(fp)
        t0 = time.perf_counter()
        compiled = self._try_load(path, fp)
        if compiled is not None:
            dt = time.perf_counter() - t0
            self.hits += 1
            observe.inc("compile_cache_hits_total")
            observe.observe_value("compile_seconds", dt, source="cache")
            observe.instant("compile_cache.hit", cat="compile",
                            fn=name, fingerprint=fp[:12],
                            seconds=round(dt, 4))
            logger.info(
                "warm start: %s served from AOT cache in %.3fs "
                "(fingerprint %s)", name, dt, fp[:12],
            )
            self._register_cost(name, compiled, lowered)
            self._note_code_size(fp)
            return compiled
        self.misses += 1
        with observe.span("compile", cat="compile", fn=name,
                          fingerprint=fp[:12]):
            if compiler_options:
                compiled = lowered.compile(
                    compiler_options=dict(compiler_options))
            else:
                compiled = lowered.compile()
        from sparkdl_tpu.utils import jax_compat

        stats = jax_compat.memory_analysis(compiled)
        if stats is not None and not stats.get("alias_size_in_bytes"):
            # `.compile()` may have been served by the XLA persistent
            # cache (still an AOT miss here), and a deserialized
            # executable reports alias 0 even for donated programs.
            # Restore the donated bytes from the lowering's own
            # donation attrs so the stats this entry persists — and
            # every warm start after it — stay truthful.
            from sparkdl_tpu.analysis.fixes import donated_bytes_static

            static = donated_bytes_static(
                jax_compat.lowered_stablehlo(lowered))
            if static:
                stats = dict(stats, alias_size_in_bytes=static)
        self.last_memory_stats = stats
        dt = time.perf_counter() - t0
        observe.inc("compile_cache_misses_total")
        observe.observe_value("compile_seconds", dt, source="xla")
        observe.instant("compile_cache.miss", cat="compile",
                        fn=name, fingerprint=fp[:12],
                        seconds=round(dt, 4))
        self._write(path, fp, compiled)
        self._register_cost(name, compiled, lowered)
        self._note_code_size(fp)
        return compiled

    def _note_code_size(self, fingerprint):
        """Fold this executable's program size into the
        "compile_cache" accounting category (its generated code lives
        in device memory for as long as the executable does)."""
        size = (self.last_memory_stats or {}).get(
            "generated_code_size_in_bytes")
        if size:
            self._code_bytes[fingerprint] = int(size)

    @staticmethod
    def _register_cost(name, compiled, lowered):
        """Feed the executable's analytic FLOPs/bytes into
        :mod:`sparkdl_tpu.observe.perf` so every instrumented step of
        this program reports achieved-FLOPs/s and MFU. Behind the
        telemetry latch inside ``register_step_cost``; a deserialized
        executable whose runtime refuses the cost model falls back to
        the lowering's estimate, and no cost model at all just means
        the gauges never appear."""
        from sparkdl_tpu import observe
        from sparkdl_tpu.observe import perf

        if not observe.enabled():
            return
        if perf.register_step_cost(name, compiled) is None:
            perf.register_step_cost(name, lowered)


def load_or_compile(lowered, *, name="train_step", compiler_options=None):
    """Module-level convenience: :meth:`CompiledStepCache.
    load_or_compile` against the env-configured cache dir, or a plain
    cold compile when warm-start compilation is not opted in — so
    library code can call this unconditionally."""
    if persistent_cache_dir() is None:
        if compiler_options:
            return lowered.compile(compiler_options=dict(compiler_options))
        return lowered.compile()
    return CompiledStepCache().load_or_compile(
        lowered, name=name, compiler_options=compiler_options
    )
