"""Ring attention: exact attention over sequences sharded across the
``seq`` mesh axis.

Long-context training shards the sequence dimension across chips; each
chip holds a Q/K/V block and K/V blocks rotate around the ring via
``lax.ppermute`` (neighbor exchange → pure ICI traffic, no all-to-all),
while softmax statistics accumulate in the numerically stable
flash-attention form (running max + rescaled partial sums). After
``seq`` steps every query block has attended to every key block —
bit-exact full attention with O(S/N) activation memory per chip.

The reference has no sequence parallelism at all (SURVEY.md §5.7); this
is the capability the build brief requires beyond parity. Use under
``shard_map`` with Q/K/V sharded on the sequence dimension.
"""

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_attend(q, k, v, mask, scale):
    """One (q-block × kv-block) attention piece with its own softmax
    stats. Shapes: q (B,Sq,H,D), k/v (B,Sk,H,D), mask (Sq,Sk) or None.
    Returns (o, m, l): unnormalized output, row max, row sum.

    Matmuls run in the INPUT dtype with fp32 accumulation
    (``preferred_element_type``): upcasting bf16 operands to fp32
    first would push the MXU to its multi-pass fp32 rate (the same
    throttle the round-4 flash-kernel fix removed), while softmax
    statistics and the accumulators stay fp32 for stability — the
    standard flash-attention numerics."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                      # (B,H,Sq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_self_attention(q, k, v, *, axis_name, causal=True, scale=None):
    """Exact (flash-accumulated) self-attention with K/V ring rotation.

    Args: q, k, v of shape (batch, seq_local, heads, head_dim) — the
    local sequence shard; must be called inside ``shard_map`` with the
    sequence dimension sharded over ``axis_name``.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = scale or (d ** -0.5)

    q_pos = idx * s_local + jnp.arange(s_local)

    def make_mask(src):
        if not causal:
            return None
        k_pos = src * s_local + jnp.arange(s_local)
        return q_pos[:, None] >= k_pos[None, :]

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        k_blk, v_blk, src, acc_o, acc_m, acc_l = carry
        mask = make_mask(src) if causal else None
        o, m, l = _block_attend(q, k_blk, v_blk, mask, scale)
        new_m = jnp.maximum(acc_m, m)
        a = jnp.exp(acc_m - new_m)
        bfac = jnp.exp(m - new_m)
        acc_o = (acc_o * a[..., None].transpose(0, 2, 1, 3)
                 + o * bfac[..., None].transpose(0, 2, 1, 3))
        acc_l = acc_l * a + l * bfac
        # rotate kv to the next rank (neighbor exchange on the ring)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        src_nxt = (src - 1) % n
        return (k_nxt, v_nxt, src_nxt, acc_o, new_m, acc_l), None

    acc_o = jnp.zeros((b, s_local, h, d), jnp.float32)
    acc_m = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    acc_l = jnp.zeros((b, h, s_local), jnp.float32)
    carry = (k, v, idx, acc_o, acc_m, acc_l)
    (_, _, _, acc_o, _, acc_l), _ = jax.lax.scan(
        step, carry, None, length=n
    )
    denom = jnp.maximum(acc_l, 1e-30)[..., None].transpose(0, 2, 1, 3)
    return (acc_o / denom).astype(q.dtype)


def attention_reference(q, k, v, *, causal=True, scale=None):
    """Dense single-device attention (test oracle / the headline
    TRAINING path — ``LlamaConfig.attention="reference"``).

    Same MXU discipline as :func:`_block_attend`: scores and the PV
    product run in the input dtype with fp32 accumulation; only the
    softmax itself is fp32. For fp32 inputs (every oracle test) this
    is bit-identical to the old always-upcast version; for the bf16
    training path it keeps the two big einsums at full MXU rate."""
    d = q.shape[-1]
    scale = scale or (d ** -0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    # back to the activation dtype: a silently-fp32 output would
    # upcast the caller's o_proj matmul (the throttle this fix removes)
    return o.astype(v.dtype)


def make_ring_attention(mesh, *, causal=True):
    """Bind ring attention to a mesh: returns f(q, k, v) taking GLOBAL
    (b, s, h, d) arrays sharded (data, seq, None, None)."""
    from jax.sharding import PartitionSpec as P

    spec = P("data", "seq", None, None)
    fn = functools.partial(
        ring_self_attention, axis_name="seq", causal=causal
    )
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    ))
