"""Ring attention: exact attention over sequences sharded across the
``seq`` mesh axis.

Long-context training shards the sequence dimension across chips; each
chip holds a Q/K/V block and K/V blocks rotate around the ring via
``lax.ppermute`` (neighbor exchange → pure ICI traffic, no all-to-all),
while softmax statistics accumulate in the numerically stable
flash-attention form (running max + rescaled partial sums). After
``seq`` steps every query block has attended to every key block —
bit-exact full attention with O(S/N) activation memory per chip.

**Communication/compute overlap** (the default, ``overlap=True``): the
ring is software-pipelined so the ``ppermute`` moving the NEXT K/V
block is issued *before* the CURRENT block is attended — the hop's
only data dependence is the block that already arrived, so XLA's async
collective scheduler (``collective-permute-start``/``-done`` plus the
while-loop collective pipeliner) can run the wire transfer concurrently
with the block attention instead of serializing attend → hop → attend.
Same blocks, same merge order, same hop count as the serialized
schedule — outputs are bit-exact against ``overlap=False`` (pinned by
tests) and against :func:`attention_reference`.

The reference has no sequence parallelism at all (SURVEY.md §5.7); this
is the capability the build brief requires beyond parity. Use under
``shard_map`` with Q/K/V sharded on the sequence dimension.
"""

import functools

import jax
import jax.numpy as jnp

from sparkdl_tpu.utils.jax_compat import axis_size

NEG_INF = -1e30


def _block_attend(q, k, v, mask, scale):
    """One (q-block × kv-block) attention piece with its own softmax
    stats. Shapes: q (B,Sq,H,D), k/v (B,Sk,H,D), mask (Sq,Sk) or None.
    Returns (o, m, l): unnormalized output, row max, row sum.

    Matmuls run in the INPUT dtype with fp32 accumulation
    (``preferred_element_type``): upcasting bf16 operands to fp32
    first would push the MXU to its multi-pass fp32 rate (the same
    throttle the round-4 flash-kernel fix removed), while softmax
    statistics and the accumulators stay fp32 for stability — the
    standard flash-attention numerics."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                      # (B,H,Sq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def _merge_stats(acc_o, acc_m, acc_l, o, m, l):
    """Fold one block's (o, m, l) into the running flash accumulators —
    the ONE merge both ring schedules share, so the overlapped lowering
    stays bit-exact against the serialized one."""
    new_m = jnp.maximum(acc_m, m)
    a = jnp.exp(acc_m - new_m)
    bfac = jnp.exp(m - new_m)
    acc_o = (acc_o * a[..., None].transpose(0, 2, 1, 3)
             + o * bfac[..., None].transpose(0, 2, 1, 3))
    acc_l = acc_l * a + l * bfac
    return acc_o, new_m, acc_l


def resolve_overlap(overlap):
    """The hop-schedule default: an explicit ``overlap`` wins; ``None``
    resolves the ``SPARKDL_TPU_OVERLAP`` env knob (registered in
    :mod:`sparkdl_tpu.utils.knobs`; on when unset) — the seam an
    autotuned profile flips per device kind without touching call
    sites. Read at trace time, like every other schedule choice."""
    if overlap is not None:
        return bool(overlap)
    from sparkdl_tpu.utils.knobs import read_bool

    return read_bool("SPARKDL_TPU_OVERLAP")


def ring_self_attention(q, k, v, *, axis_name, causal=True, scale=None,
                        overlap=None):
    """Exact (flash-accumulated) self-attention with K/V ring rotation.

    Args: q, k, v of shape (batch, seq_local, heads, head_dim) — the
    local sequence shard; must be called inside ``shard_map`` with the
    sequence dimension sharded over ``axis_name``.

    ``overlap=True`` (default; ``None`` resolves the
    ``SPARKDL_TPU_OVERLAP`` knob) issues each hop's ``ppermute`` before
    attending the block that already arrived (double-buffered carry:
    the resident block is consumed while its successor is on the
    wire), so the transfer hides under the block attention.
    ``overlap=False`` keeps the serialized attend → hop schedule — the
    equivalence oracle and the analysis bad-corpus generator.
    """
    overlap = resolve_overlap(overlap)
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = scale or (d ** -0.5)

    q_pos = idx * s_local + jnp.arange(s_local)

    def make_mask(src):
        if not causal:
            return None
        k_pos = src * s_local + jnp.arange(s_local)
        return q_pos[:, None] >= k_pos[None, :]

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend_merge(acc, k_blk, v_blk, src):
        mask = make_mask(src) if causal else None
        o, m, l = _block_attend(q, k_blk, v_blk, mask, scale)
        return _merge_stats(*acc, o, m, l)

    acc = (
        jnp.zeros((b, s_local, h, d), jnp.float32),
        jnp.full((b, h, s_local), NEG_INF, jnp.float32),
        jnp.zeros((b, h, s_local), jnp.float32),
    )

    if not overlap:
        def step(carry, _):
            k_blk, v_blk, src, acc_o, acc_m, acc_l = carry
            acc_o, acc_m, acc_l = attend_merge(
                (acc_o, acc_m, acc_l), k_blk, v_blk, src)
            # rotate kv to the next rank (neighbor exchange on the ring)
            k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
            src_nxt = (src - 1) % n
            return (k_nxt, v_nxt, src_nxt, acc_o, acc_m, acc_l), None

        carry = (k, v, idx) + acc
        (_, _, _, acc_o, _, acc_l), _ = jax.lax.scan(
            step, carry, None, length=n
        )
    else:
        # Hop 0 is the resident block; hop 1's permute is issued BEFORE
        # attending it, so the first transfer is already in flight while
        # the diagonal block computes.
        if n == 1:
            acc_o, _, acc_l = attend_merge(acc, k, v, idx)
        else:
            k_cur = jax.lax.ppermute(k, axis_name, perm)
            v_cur = jax.lax.ppermute(v, axis_name, perm)
            acc = attend_merge(acc, k, v, idx)

            def step(carry, _):
                k_cur, v_cur, src, acc_o, acc_m, acc_l = carry
                # issue the NEXT hop first: its only dependence is the
                # block that already arrived, so the wire transfer and
                # the block attention below can run concurrently
                k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
                v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
                acc_o, acc_m, acc_l = attend_merge(
                    (acc_o, acc_m, acc_l), k_cur, v_cur, src)
                return (k_nxt, v_nxt, (src - 1) % n,
                        acc_o, acc_m, acc_l), None

            carry = (k_cur, v_cur, (idx - 1) % n) + acc
            (_, _, _, acc_o, _, acc_l), _ = jax.lax.scan(
                step, carry, None, length=n - 1
            )
    denom = jnp.maximum(acc_l, 1e-30)[..., None].transpose(0, 2, 1, 3)
    return (acc_o / denom).astype(q.dtype)


def attention_reference(q, k, v, *, causal=True, scale=None):
    """Dense single-device attention (test oracle / the headline
    TRAINING path — ``LlamaConfig.attention="reference"``).

    Same MXU discipline as :func:`_block_attend`: scores and the PV
    product run in the input dtype with fp32 accumulation; only the
    softmax itself is fp32. For fp32 inputs (every oracle test) this
    is bit-identical to the old always-upcast version; for the bf16
    training path it keeps the two big einsums at full MXU rate."""
    d = q.shape[-1]
    scale = scale or (d ** -0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    # back to the activation dtype: a silently-fp32 output would
    # upcast the caller's o_proj matmul (the throttle this fix removes)
    return o.astype(v.dtype)


# ---------------------------------------------------------------------------
# Ring-flash: pallas flash kernels INSIDE the ring (long-context scale).
#
# The dense ring above materializes a (B, H, S_local, S_local) fp32
# score matrix every ring step — at the sequence lengths sequence
# parallelism exists for (S_local in the thousands), that buffer is the
# memory wall.  Here each ring step runs the fused pallas forward on
# the resident K/V block (O(S_local · D) memory), and normalized
# partials merge in logsumexp form.  The backward is a SECOND ring
# pass (custom_vjp): with the forward's final lse and delta = Σ do·o,
# the flash backward restricted to one K/V block is exactly the
# block's contribution, so dq accumulates locally while dk/dv
# accumulators rotate WITH their blocks and arrive home after n hops
# (blockwise-parallel ring attention; same decomposition the in-tree
# dq/dkv kernels already implement across tiles within a block).
#
# Both rings are software-pipelined like the dense one (overlap=True):
# the K/V hop — and, in the backward, the dk/dv accumulator hop, whose
# incoming value is only needed AFTER the block backward — is issued
# before the resident block's kernel runs, so the ICI transfer hides
# under the pallas compute.
#
# Visibility schedule (causal): at hop t the resident block came from
# rank src = (idx - t) mod n — src == idx is the causal diagonal
# (t = 0, unrolled before the scan), src < idx is fully visible,
# src > idx is fully masked and skipped without touching the MXU.
# ---------------------------------------------------------------------------


def _lse_merge(acc_o, acc_lse, o, lse):
    """Merge one normalized block partial in logsumexp form — shared
    by both flash-ring schedules (bit-exactness contract)."""
    new_lse = jnp.logaddexp(acc_lse, lse)
    acc_o = (acc_o * jnp.exp(acc_lse - new_lse)
             + o * jnp.exp(lse - new_lse))
    return acc_o, new_lse


def _ring_flash_fwd_pass(qt, k0, v0, axis_name, causal, scale, bq, bk,
                         interpret, overlap=True):
    """Ring of flash-forward blocks. qt/k0/v0 are (B,H,S,D) local
    shards; returns (o_norm f32, lse f32 (B,H,S,1))."""
    from sparkdl_tpu.ops.pallas.flash_attention import (
        flash_attention_bhsd,
    )

    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, h, s, d = qt.shape

    def attend(k_blk, v_blk, diag):
        o, lse = flash_attention_bhsd(
            qt, k_blk, v_blk, causal=diag and causal, scale=scale,
            bq=bq, bk=bk, interpret=interpret, return_lse=True,
        )
        return o.astype(jnp.float32), lse

    def masked_attend(k_blk, v_blk, src):
        if causal:
            return jax.lax.cond(
                src < idx,
                lambda: attend(k_blk, v_blk, diag=False),
                lambda: (jnp.zeros((b, h, s, d), jnp.float32),
                         jnp.full((b, h, s, 1), NEG_INF, jnp.float32)),
            )
        return attend(k_blk, v_blk, diag=False)

    if not overlap:
        # hop 0: the resident (own) block — the causal diagonal
        acc_o, acc_lse = attend(k0, v0, diag=True)

        def step(carry, _):
            k_blk, v_blk, src, acc_o, acc_lse = carry
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            src = (src - 1) % n
            o, lse = masked_attend(k_blk, v_blk, src)
            acc_o, acc_lse = _lse_merge(acc_o, acc_lse, o, lse)
            return (k_blk, v_blk, src, acc_o, acc_lse), None

        (_, _, _, acc_o, acc_lse), _ = jax.lax.scan(
            step, (k0, v0, idx, acc_o, acc_lse), None, length=n - 1
        )
        return acc_o, acc_lse

    if n == 1:
        return attend(k0, v0, diag=True)
    # hop 1's permute is issued BEFORE the diagonal kernel runs
    k_cur = jax.lax.ppermute(k0, axis_name, perm)
    v_cur = jax.lax.ppermute(v0, axis_name, perm)
    acc_o, acc_lse = attend(k0, v0, diag=True)

    def step(carry, _):
        k_cur, v_cur, src, acc_o, acc_lse = carry
        # next hop rides the wire while the resident block computes
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        o, lse = masked_attend(k_cur, v_cur, src)
        acc_o, acc_lse = _lse_merge(acc_o, acc_lse, o, lse)
        return (k_nxt, v_nxt, (src - 1) % n, acc_o, acc_lse), None

    (k_cur, v_cur, src, acc_o, acc_lse), _ = jax.lax.scan(
        step, (k_cur, v_cur, (idx - 1) % n, acc_o, acc_lse), None,
        length=n - 2,
    )
    # epilogue: the final block needs no further hop — attending it
    # outside the scan keeps the hop count identical to the serialized
    # schedule (n-1 permutes per tensor)
    o, lse = masked_attend(k_cur, v_cur, src)
    acc_o, acc_lse = _lse_merge(acc_o, acc_lse, o, lse)
    return acc_o, acc_lse


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_flash(q, k, v, axis_name, causal, scale, bq, bk, interpret,
                overlap):
    out, _ = _ring_flash_core(q, k, v, axis_name, causal, scale, bq,
                              bk, interpret, overlap)
    return out


def _ring_flash_core(q, k, v, axis_name, causal, scale, bq, bk,
                     interpret, overlap):
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    acc_o, acc_lse = _ring_flash_fwd_pass(
        qt, kt, vt, axis_name, causal, scale, bq, bk, interpret,
        overlap,
    )
    out = acc_o.astype(q.dtype).transpose(0, 2, 1, 3)
    return out, acc_lse


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, bq, bk,
                    interpret, overlap):
    out, lse = _ring_flash_core(q, k, v, axis_name, causal, scale, bq,
                                bk, interpret, overlap)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, bq, bk, interpret,
                    overlap, res, do):
    from sparkdl_tpu.ops.pallas.flash_attention import (
        flash_attention_bwd_bhsd,
    )

    q, k, v, out, lse = res
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.astype(jnp.float32).transpose(0, 2, 1, 3)
    ot = out.astype(jnp.float32).transpose(0, 2, 1, 3)
    delta = jnp.sum(dot * ot, axis=-1, keepdims=True)  # (B,H,S,1)
    dot = dot.astype(qt.dtype)

    def block_bwd(k_blk, v_blk, diag):
        return flash_attention_bwd_bhsd(
            qt, k_blk, v_blk, dot, lse, delta,
            causal=diag and causal, scale=scale, bq=bq, bk=bk,
            interpret=interpret,
        )

    zeros_kv = jnp.zeros(kt.shape, jnp.float32)

    def masked_block_bwd(k_blk, v_blk, src):
        def live():
            dq_c, dk_c, dv_c = block_bwd(k_blk, v_blk, diag=False)
            return (dq_c.astype(jnp.float32),
                    dk_c.astype(jnp.float32),
                    dv_c.astype(jnp.float32))

        if causal:
            return jax.lax.cond(
                src < idx,
                live,
                lambda: (jnp.zeros(qt.shape, jnp.float32), zeros_kv,
                         zeros_kv),
            )
        return live()

    def finish(dq_acc, dk_acc, dv_acc):
        dq = dq_acc.astype(q.dtype).transpose(0, 2, 1, 3)
        dk = dk_acc.astype(k.dtype).transpose(0, 2, 1, 3)
        dv = dv_acc.astype(v.dtype).transpose(0, 2, 1, 3)
        return dq, dk, dv

    if not overlap:
        # hop 0: diagonal block (own k/v)
        dq0, dk0, dv0 = block_bwd(kt, vt, diag=True)
        dq_acc = dq0.astype(jnp.float32)

        def step(carry, _):
            k_blk, v_blk, dk_acc, dv_acc, src, dq_acc = carry
            # rotate the block AND its gradient accumulator together:
            # after the remaining n-1 hops both are back on the
            # block's home rank
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
            src = (src - 1) % n
            dq_c, dk_c, dv_c = masked_block_bwd(k_blk, v_blk, src)
            return (k_blk, v_blk, dk_acc + dk_c, dv_acc + dv_c, src,
                    dq_acc + dq_c), None

        carry = (kt, vt, dk0.astype(jnp.float32),
                 dv0.astype(jnp.float32), idx, dq_acc)
        (k_blk, v_blk, dk_acc, dv_acc, _, dq_acc), _ = jax.lax.scan(
            step, carry, None, length=n - 1
        )
        # one more hop brings each accumulator from the rank that
        # computed the LAST contribution back to the block's home rank
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        return finish(dq_acc, dk_acc, dv_acc)

    # overlapped second ring: K/V hop issued before the diagonal
    # kernel; in the body, the incoming accumulator is only needed
    # AFTER the block backward, so its permute hides under the kernel
    # exactly like the K/V one.
    dq_hop0, dk0, dv0 = block_bwd(kt, vt, diag=True)
    if n == 1:
        return finish(dq_hop0.astype(jnp.float32),
                      dk0.astype(jnp.float32),
                      dv0.astype(jnp.float32))
    k_cur = jax.lax.ppermute(kt, axis_name, perm)
    v_cur = jax.lax.ppermute(vt, axis_name, perm)
    dq_acc = dq_hop0.astype(jnp.float32)

    def step(carry, _):
        k_cur, v_cur, dk_acc, dv_acc, src, dq_acc = carry
        # all four permutes are independent of this hop's block
        # backward — K/V for the NEXT block, plus the accumulator for
        # the CURRENT block arriving from the previous rank
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_in = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_in = jax.lax.ppermute(dv_acc, axis_name, perm)
        dq_c, dk_c, dv_c = masked_block_bwd(k_cur, v_cur, src)
        return (k_nxt, v_nxt, dk_in + dk_c, dv_in + dv_c,
                (src - 1) % n, dq_acc + dq_c), None

    carry = (k_cur, v_cur, dk0.astype(jnp.float32),
             dv0.astype(jnp.float32), (idx - 1) % n, dq_acc)
    (k_cur, v_cur, dk_acc, dv_acc, src, dq_acc), _ = jax.lax.scan(
        step, carry, None, length=n - 2
    )
    # epilogue: the final block's contribution, then the homing hop
    dk_in = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv_in = jax.lax.ppermute(dv_acc, axis_name, perm)
    dq_c, dk_c, dv_c = masked_block_bwd(k_cur, v_cur, src)
    dk_acc = jax.lax.ppermute(dk_in + dk_c, axis_name, perm)
    dv_acc = jax.lax.ppermute(dv_in + dv_c, axis_name, perm)
    return finish(dq_acc + dq_c, dk_acc, dv_acc)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, *, axis_name, causal=True, scale=None,
                         bq=128, bk=128, interpret=False, overlap=None):
    """Ring attention whose per-block compute is the fused pallas flash
    kernel — O(S_local · D) memory per hop instead of the dense ring's
    O(S_local²) score matrix, with a fused two-ring backward.  Same
    contract as :func:`ring_self_attention`: (batch, seq_local, heads,
    head_dim) shards inside ``shard_map`` over ``axis_name``;
    ``overlap`` selects the software-pipelined (default; ``None``
    resolves ``SPARKDL_TPU_OVERLAP``) vs serialized hop schedule in
    BOTH rings."""
    d = q.shape[-1]
    scale = scale or (d ** -0.5)
    return _ring_flash(q, k, v, axis_name, causal, scale, bq, bk,
                       interpret, resolve_overlap(overlap))


def make_ring_attention(mesh, *, causal=True, impl=None,
                        interpret=False, overlap=None):
    """Bind ring attention to a mesh: returns f(q, k, v) taking GLOBAL
    (b, s, h, d) arrays sharded (data, seq, None, None).

    ``impl``: "dense" (XLA block attend — any backend, the test
    oracle's numerics), "flash" (pallas blocks — the long-context
    TPU path; ``interpret=True`` runs the kernels interpreted for
    tests off-TPU), or None = flash on TPU, dense elsewhere.
    ``overlap``: software-pipelined hop schedule (default) vs the
    serialized legacy lowering."""
    from jax.sharding import PartitionSpec as P

    from sparkdl_tpu.ops._dispatch import use_pallas

    if impl is None:
        impl = "flash" if use_pallas() else "dense"
    spec = P("data", "seq", None, None)
    if impl == "flash":
        fn = functools.partial(
            ring_flash_attention, axis_name="seq", causal=causal,
            interpret=interpret, overlap=overlap,
        )
    elif impl == "dense":
        fn = functools.partial(
            ring_self_attention, axis_name="seq", causal=causal,
            overlap=overlap,
        )
    else:
        raise ValueError(f"impl must be 'dense' or 'flash', got {impl!r}")
    from sparkdl_tpu.utils.jax_compat import shard_map

    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    ))
