"""Device-mesh construction.

Axes (any may be 1 and is then effectively absent):

- ``data``  — pure data parallelism (the reference's only axis).
- ``fsdp``  — data parallelism with parameter sharding (ZeRO-3 style;
  XLA inserts all-gathers/reduce-scatters from the shardings).
- ``seq``   — sequence/context parallelism (ring attention).
- ``model`` — tensor parallelism (Megatron-style column/row splits).

Collectives ride ICI within a slice; `jax.experimental.mesh_utils`
orders devices so neighboring mesh coordinates are ICI neighbors.
"""

import dataclasses
import math

import numpy as np

AXES = ("data", "fsdp", "seq", "model")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    data: int = 1
    fsdp: int = 1
    seq: int = 1
    model: int = 1

    @property
    def size(self):
        return self.data * self.fsdp * self.seq * self.model

    def axis_sizes(self):
        return (self.data, self.fsdp, self.seq, self.model)


def make_mesh(spec=None, devices=None):
    """Build a Mesh over ``devices`` (default: all) shaped by ``spec``
    (default: all devices on the ``data`` axis — reference-parity DP)."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if spec is None:
        spec = MeshSpec(data=n)
    if spec.size != n:
        raise ValueError(
            f"MeshSpec {spec} needs {spec.size} devices, got {n}"
        )
    if devices == jax.devices() and n > 1:
        try:
            dev_array = mesh_utils.create_device_mesh(
                spec.axis_sizes(), devices=devices
            )
        except (ValueError, AssertionError):
            dev_array = np.array(devices).reshape(spec.axis_sizes())
    else:
        dev_array = np.array(devices).reshape(spec.axis_sizes())
    return Mesh(dev_array, AXES)


def make_mesh_from_axes(axes, devices=None):
    """Mesh from an axis-size dict (``{"data": 2, "model": 4}``) — the
    restart context's ``target_axes`` contract: a relaunched worker
    main rebuilds the supervisor-derived (shrunken or regrown) mesh
    without guessing. Unknown axis names are an error; absent axes
    default to 1."""
    unknown = sorted(set(axes) - set(AXES))
    if unknown:
        raise ValueError(
            f"unknown mesh axes {unknown}; this runtime's axes are "
            f"{list(AXES)}"
        )
    spec = MeshSpec(**{a: int(axes.get(a, 1)) for a in AXES})
    return make_mesh(spec, devices=devices)


def best_mesh(n_devices, *, model_parallel=1, seq_parallel=1, fsdp=False):
    """Heuristic spec: give `model`/`seq` what was asked, put the rest
    on `data` (or `fsdp`)."""
    rest = n_devices // (model_parallel * seq_parallel)
    if rest * model_parallel * seq_parallel != n_devices:
        raise ValueError(
            f"{n_devices} devices not divisible by model_parallel="
            f"{model_parallel} * seq_parallel={seq_parallel}"
        )
    if fsdp:
        return MeshSpec(data=1, fsdp=rest, seq=seq_parallel,
                        model=model_parallel)
    return MeshSpec(data=rest, fsdp=1, seq=seq_parallel,
                    model=model_parallel)


def log2_factors(n):
    """(a, b) with a*b == n, as square as possible (both powers of 2
    when n is)."""
    a = 2 ** (int(math.log2(n)) // 2) if n > 1 else 1
    while n % a:
        a //= 2
    return a, n // a
