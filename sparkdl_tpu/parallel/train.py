"""pjit train-step factory: the path a JAX ``main`` uses under
HorovodRunner (SURVEY.md §7 step 7 — mesh ('data','model') so the
Llama-LoRA north-star config launches through the same runner).

The step is GSPMD-sharded end to end: params carry NamedShardings from
:func:`sparkdl_tpu.parallel.sharding.param_sharding`, the batch is
sharded on ``data`` (and optionally ``seq``), gradients reduce over the
data axes automatically because XLA derives the collectives from the
shardings — no explicit psum, no hand-scheduled overlap.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def make_train_step(loss_fn, optimizer, *, grad_accum=1, remat=False,
                    param_mask=None):
    """Build ``step(params, opt_state, batch, *extra) -> (params,
    opt_state, metrics)``.

    :param loss_fn: ``f(params, batch, *extra) -> scalar loss``.
    :param optimizer: an optax GradientTransformation.
    :param grad_accum: microbatch count; the batch's leading axis is
        split and gradients averaged via ``lax.scan`` (HBM-friendly:
        activations live one microbatch at a time).
    :param remat: wrap loss_fn in ``jax.checkpoint`` — trade FLOPs for
        HBM on long sequences.
    :param param_mask: optional pytree of bools; False leaves are
        frozen (LoRA-style partial training). BOTH gradients and final
        updates are masked — masking grads alone would let decoupled
        weight decay (adamw) silently erode frozen weights.
    """
    f = jax.checkpoint(loss_fn) if remat else loss_fn
    grad_fn = jax.value_and_grad(f)

    def apply_mask(tree):
        if param_mask is None:
            return tree
        return jax.tree.map(
            lambda g, m: g if m else jnp.zeros_like(g), tree, param_mask
        )

    def single(params, opt_state, batch, *extra):
        loss, grads = grad_fn(params, batch, *extra)
        grads = apply_mask(grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        updates = apply_mask(updates)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, {"loss": loss}

    if grad_accum == 1:
        return single

    def accumulated(params, opt_state, batch, *extra):
        micro = jax.tree.map(
            lambda x: x.reshape((grad_accum, x.shape[0] // grad_accum)
                                + x.shape[1:]),
            batch,
        )

        def acc_step(carry, mb):
            g_acc, l_acc = carry
            loss, grads = grad_fn(params, mb, *extra)
            g_acc = jax.tree.map(jnp.add, g_acc, grads)
            return (g_acc, l_acc + loss), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (g_sum, l_sum), _ = jax.lax.scan(acc_step, (zeros, 0.0), micro)
        grads = jax.tree.map(lambda g: g / grad_accum, g_sum)
        grads = apply_mask(grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        updates = apply_mask(updates)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, {"loss": l_sum / grad_accum}

    return accumulated


def shard_batch(batch, mesh, *, seq_axis=False):
    """Device-put a host batch with (data[, seq]) sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        if x.ndim >= 2 and seq_axis:
            spec = P(("data", "fsdp"), "seq")
        else:
            spec = P(("data", "fsdp"))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def replicate(tree, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(tree, NamedSharding(mesh, P()))


def cross_entropy_loss(logits, labels, *, ignore_index=None):
    """Token-level softmax cross entropy, fp32 accumulation."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if ignore_index is not None:
        mask = labels != ignore_index
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)
    return nll.mean()


def global_batch(rng, vocab, batch, seq):
    """Synthetic LM batch (benchmarks and dryruns)."""
    tokens = np.asarray(
        rng.integers(0, vocab, size=(batch, seq + 1)), np.int32
    )
    return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


def param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def tree_cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating)
        else x, tree
    )
