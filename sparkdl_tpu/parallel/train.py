"""pjit train-step factory: the path a JAX ``main`` uses under
HorovodRunner (SURVEY.md §7 step 7 — mesh ('data','model') so the
Llama-LoRA north-star config launches through the same runner).

The step is GSPMD-sharded end to end: params carry NamedShardings from
:func:`sparkdl_tpu.parallel.sharding.param_sharding`, the batch is
sharded on ``data`` (and optionally ``seq``), gradients reduce over the
data axes automatically because XLA derives the collectives from the
shardings — no explicit psum, no hand-scheduled overlap.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


def make_train_step(loss_fn, optimizer, *, grad_accum=1, remat=False,
                    param_mask=None):
    """Build ``step(params, opt_state, batch, *extra) -> (params,
    opt_state, metrics)``.

    :param loss_fn: ``f(params, batch, *extra) -> scalar loss``.
    :param optimizer: an optax GradientTransformation.
    :param grad_accum: microbatch count; the batch's leading axis is
        split and gradients averaged via ``lax.scan`` (HBM-friendly:
        activations live one microbatch at a time).
    :param remat: wrap loss_fn in ``jax.checkpoint`` — trade FLOPs for
        HBM on long sequences.
    :param param_mask: optional pytree of bools; False leaves are
        frozen (LoRA-style partial training). Frozen leaves are
        ``stop_gradient``-ed going INTO the loss so XLA never emits
        their dW matmuls (the x^T·dy pass — ~1/3 of backward FLOPs
        when most of the model is frozen); activation gradients still
        flow through them. BOTH the resulting (zero) gradients and
        final updates are masked — masking grads alone would let
        decoupled weight decay (adamw) silently erode frozen weights.
    """
    if param_mask is not None:
        inner_loss = loss_fn

        def loss_fn(params, *a):  # noqa: F811 — deliberate wrap
            params = jax.tree.map(
                lambda p, m: p if m else jax.lax.stop_gradient(p),
                params, param_mask,
            )
            return inner_loss(params, *a)

    f = jax.checkpoint(loss_fn) if remat else loss_fn
    grad_fn = jax.value_and_grad(f)

    def apply_mask(tree):
        if param_mask is None:
            return tree
        return jax.tree.map(
            lambda g, m: g if m else jnp.zeros_like(g), tree, param_mask
        )

    def single(params, opt_state, batch, *extra):
        loss, grads = grad_fn(params, batch, *extra)
        grads = apply_mask(grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        updates = apply_mask(updates)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, {"loss": loss}

    if grad_accum == 1:
        return single

    def accumulated(params, opt_state, batch, *extra):
        micro = jax.tree.map(
            lambda x: x.reshape((grad_accum, x.shape[0] // grad_accum)
                                + x.shape[1:]),
            batch,
        )

        def acc_step(carry, mb):
            g_acc, l_acc = carry
            loss, grads = grad_fn(params, mb, *extra)
            g_acc = jax.tree.map(jnp.add, g_acc, grads)
            return (g_acc, l_acc + loss), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (g_sum, l_sum), _ = jax.lax.scan(acc_step, (zeros, 0.0), micro)
        grads = jax.tree.map(lambda g: g / grad_accum, g_sum)
        grads = apply_mask(grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        updates = apply_mask(updates)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, {"loss": l_sum / grad_accum}

    return accumulated


def instrument_step(step_fn, name="train_step"):
    """Wrap a (possibly jitted) train step with gang telemetry
    (:mod:`sparkdl_tpu.observe`): a timeline span per call, a
    wall-time histogram split ``phase="compile"`` (first call — under
    jit that call pays trace + XLA compile) vs ``phase="execute"``,
    a call counter, and a running ``<name>_per_second`` gauge over the
    execute calls. Telemetry off (the default): one cached-boolean
    check, then straight through to ``step_fn``.

    Timing is dispatch wall-time, deliberately: blocking on the result
    every step would serialize the async dispatch pipeline the whole
    runner exists to keep full. Steady-state steps/sec is still
    accurate — a saturated pipeline's dispatch rate IS its device
    rate — and the compile-vs-execute split isolates the one honest
    outlier (the first call blocks on XLA anyway).

    When an executable cost was registered for ``name``
    (:func:`sparkdl_tpu.observe.perf.register_step_cost` — the
    compile cache and :func:`lower_train_step` both do), each execute
    call also updates the achieved-FLOPs/s, achieved-bytes/s, MFU and
    memory-bandwidth-utilization gauges against the per-device-kind
    peak table.
    """
    from sparkdl_tpu import observe

    state = {"calls": 0, "first_exec_t0": None}

    @functools.wraps(step_fn)
    def stepped(*args, **kwargs):
        if not observe.enabled():
            return step_fn(*args, **kwargs)
        from sparkdl_tpu.observe import health

        # Step ENTRY is the gang-health progress marker: a rank that
        # stops entering steps stops moving this counter, which is
        # what the driver's HangDetector declares a stall on. Entry
        # (not exit) so a long first-step compile pins the counter
        # for at most one compile.
        health.note_step(state["calls"])
        phase = "compile" if state["calls"] == 0 else "execute"
        t0 = time.perf_counter()
        from sparkdl_tpu.observe import mem

        # OOM forensics (ISSUE 18): an allocation failure inside the
        # step writes oom_report.json (category table, sample tail,
        # hints) before the exception unwinds the worker.
        with mem.oom_guard(phase="step"), \
                observe.span(name, cat="train", step=state["calls"],
                             phase=phase):
            out = step_fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        state["calls"] += 1
        observe.observe_value(f"{name}_seconds", dt, phase=phase)
        observe.inc(f"{name}_total", phase=phase)
        if phase == "execute":
            from sparkdl_tpu.observe import perf

            perf.note_step(name, dt)
            if state["first_exec_t0"] is None:
                state["first_exec_t0"] = t0
            elapsed = time.perf_counter() - state["first_exec_t0"]
            if elapsed > 0:
                observe.set_gauge(
                    f"{name}_per_second",
                    (state["calls"] - 1) / elapsed,
                )
        return out

    return stepped


def lower_train_step(step, *example_args, mesh=None,
                     cost_name="train_step", donate_argnums=None):
    """Version-stable lowered-module access for a (jitted or plain)
    train step: returns the ``jax.stages.Lowered`` for
    ``step(*example_args)``, entering ``mesh`` around lowering when
    given (GSPMD programs lower against the ambient mesh).

    ``donate_argnums`` re-jits the step with the given arguments
    donated before lowering (an outer ``jax.jit`` restores donation
    even on an already-jitted undonated step) — the manual seam for
    applying a ``donate-step-buffers`` fix's inferred argnums
    (:mod:`sparkdl_tpu.analysis.fixes`) by hand, so the repaired
    step's buffers alias in the same artifact the compile cache
    serializes.

    This is the artifact the static-analysis passes consume
    (:mod:`sparkdl_tpu.analysis`): lower once on the driver, then
    lint and ``.compile()`` the same object — nothing is traced
    twice. (Compilation is separate: lint the *Compiled* via
    ``analysis.lint_compiled`` / ``register_preflight`` when you will
    compile anyway, so the expensive compile runs once too.)

    With telemetry opted in, the lowering's analytic FLOPs/bytes are
    registered under ``cost_name`` so :func:`instrument_step` can
    report achieved-FLOPs/s and MFU for it (the compile cache later
    refines the estimate with the *compiled* cost model when the same
    program goes through ``load_or_compile``). ``cost_name=None``
    skips registration.
    """
    import contextlib

    from sparkdl_tpu.utils import jax_compat

    if donate_argnums is not None:
        step = jax.jit(step, donate_argnums=tuple(donate_argnums))
    ctx = mesh if mesh is not None else contextlib.nullcontext()
    with ctx:
        lowered = jax_compat.lower(step, *example_args)
    if cost_name is not None:
        from sparkdl_tpu import observe
        from sparkdl_tpu.observe import perf

        if observe.enabled():
            perf.register_step_cost(cost_name, lowered)
    return lowered


def shard_batch(batch, mesh, *, seq_axis=False):
    """Device-put a host batch with (data[, seq]) sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        if x.ndim >= 2 and seq_axis:
            spec = P(("data", "fsdp"), "seq")
        else:
            spec = P(("data", "fsdp"))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def replicate(tree, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(tree, NamedSharding(mesh, P()))


def cross_entropy_loss(logits, labels, *, ignore_index=None):
    """Token-level softmax cross entropy, fp32 accumulation."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if ignore_index is not None:
        mask = labels != ignore_index
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)
    return nll.mean()


def fused_cross_entropy(hidden, w_head, labels, *, chunk_size=256,
                        ignore_index=None, matmul_dtype=None,
                        freeze_head=False):
    """Chunked linear + softmax cross entropy: ``loss = CE(hidden @
    w_head, labels)`` without ever materializing the ``(B, S, V)``
    logits tensor in HBM.

    The sequence axis is scanned in ``chunk_size`` slices; each slice's
    logits live only inside one fused chunk (``jax.checkpoint`` makes
    the backward recompute them instead of saving them). For a 32k
    vocab at batch 8 x seq 1024 this replaces a ~1 GiB fp32 logits
    round-trip (plus its log_softmax twin) with a ~32 MiB working set.

    :param hidden: ``(B, S, D)`` final hidden states (any float dtype).
    :param w_head: ``(D, V)`` unembedding matrix.
    :param labels: ``(B, S)`` int targets.
    :param chunk_size: tokens per scanned slice of the sequence axis.
    :param ignore_index: label value excluded from the mean.
    :param matmul_dtype: cast both matmul operands (e.g. bf16 halves
        the ``w_head`` HBM read; accumulation stays fp32 via
        ``preferred_element_type``).
    :param freeze_head: ``stop_gradient`` the head (LoRA-style frozen
        unembedding) so its dW matmul is never emitted.
    """
    b, s, d = hidden.shape
    chunk = min(chunk_size, s)
    n = -(-s // chunk)
    pad = n * chunk - s
    valid = jnp.ones((b, s), jnp.float32) if ignore_index is None else \
        (labels != ignore_index).astype(jnp.float32)
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    hc = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)   # (n, B, c, D)
    lc = labels.reshape(b, n, chunk).swapaxes(0, 1)
    vc = valid.reshape(b, n, chunk).swapaxes(0, 1)

    if freeze_head:
        w_head = jax.lax.stop_gradient(w_head)
    w = w_head if matmul_dtype is None else w_head.astype(matmul_dtype)

    def chunk_nll(h, lbl):
        hm = h if matmul_dtype is None else h.astype(matmul_dtype)
        logits = jax.lax.dot_general(
            hm, w, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lbl[..., None], axis=-1)[..., 0]
        return lse - gold                                # (B, c)

    chunk_nll = jax.checkpoint(chunk_nll)

    def body(acc, xs):
        h, lbl, m = xs
        nll = chunk_nll(h, lbl)
        return (acc[0] + (nll * m).sum(), acc[1] + m.sum()), None

    (loss_sum, count), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (hc, lc, vc),
    )
    return loss_sum / jnp.maximum(count, 1.0)


def global_batch(rng, vocab, batch, seq):
    """Synthetic LM batch (benchmarks and dryruns)."""
    tokens = np.asarray(
        rng.integers(0, vocab, size=(batch, seq + 1)), np.int32
    )
    return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


def make_lm_loss_fn(model, *, loss="logits", chunk=512, ce_bf16=False):
    """The language-model loss closure used by BOTH the headline bench
    and the bench_variants sweep — one definition, so a variant the
    sweep measured is exactly what a promotion into bench.py runs.

    ``loss="logits"``: materialized logits + standard CE.
    ``loss="fused"``: hidden states into :func:`fused_cross_entropy`
    (chunked unembed+CE, frozen head, optional bf16 unembed matmul) —
    the (B,S,V) fp32 logits tensor never hits HBM.
    """
    import jax.numpy as jnp

    if loss == "fused":
        def loss_fn(p, b):
            hidden = model.apply({"params": p}, b["inputs"],
                                 return_hidden=True)
            return fused_cross_entropy(
                hidden, p["lm_head"]["kernel"], b["targets"],
                chunk_size=chunk, freeze_head=True,
                matmul_dtype=jnp.bfloat16 if ce_bf16 else None,
            )
        return loss_fn
    if loss != "logits":
        raise ValueError(f"unknown loss path {loss!r}")

    def loss_fn(p, b):
        logits = model.apply({"params": p}, b["inputs"])
        return cross_entropy_loss(logits, b["targets"])
    return loss_fn


def param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def tree_cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating)
        else x, tree
    )
