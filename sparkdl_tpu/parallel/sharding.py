"""Sharding rules: map parameter pytrees and activations onto the mesh.

Rules are name-based regex → PartitionSpec, applied over the flattened
param tree (flax params are nested dicts; the joined path is matched).
This is the GSPMD recipe: annotate shardings, let XLA insert the
collectives (scaling-book methodology referenced by the build brief).
"""

import re

import numpy as np


def constrain(x, *spec):
    """``with_sharding_constraint`` sugar usable inside pjit."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.lax.with_sharding_constraint(x, P(*spec))


def _match(rules, path):
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return None


def param_sharding(params, rules, mesh):
    """PartitionSpec pytree for ``params``: first matching rule wins;
    unmatched params are replicated. Specs whose sharded dims don't
    divide the param's shape fall back to replication (safe default for
    tiny test configs)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def spec_for(path, leaf):
        spec = _match(rules, path)
        if spec is None:
            return P()
        spec = P(*spec) if not isinstance(spec, P) else spec
        if (len(spec) < leaf.ndim
                and re.search(r"lora_(a|b)$", path)):
            # Stacked multi-adapter leaves (n_adapters, ...) reuse the
            # 2-D adapter rules: LEFT-pad so the trailing (in/out)
            # dims keep their Megatron split — without this, lora_b's
            # (None, 'model') would shard the RANK dim of a 3-D leaf.
            spec = P(*([None] * (leaf.ndim - len(spec)) + list(spec)))
        # validate divisibility
        for dim, names in enumerate(spec):
            if names is None:
                continue
            names = names if isinstance(names, tuple) else (names,)
            total = int(np.prod([axis_sizes[a] for a in names]))
            if dim >= leaf.ndim or leaf.shape[dim] % total:
                return P()
        return spec

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(params)
    flat_specs = []
    for path, leaf in leaves_with_path:
        key = "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        flat_specs.append(spec_for(key, leaf))
    tree = jax.tree_util.tree_unflatten(treedef, flat_specs)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), tree,
                        is_leaf=lambda s: isinstance(s, P))


def tp_param_info(params, shardings):
    """Describe which params the given shardings actually split (mesh
    axes of size 1 excluded): the input the full-param all-gather
    analysis pass needs to know what "a full TP parameter" means for
    THIS program. Returns :class:`sparkdl_tpu.analysis.ParamInfo`
    entries for every leaf; entries with empty ``sharded_axes`` are
    replicated."""
    from sparkdl_tpu.analysis import param_info_from

    return param_info_from(params, shardings)


def named_sharding_for(mesh, spec_dims):
    """Re-lay one recorded per-dim spec (tuples/lists of mesh axis
    names, the sharding-tree-as-data serialization) onto ``mesh``:
    axis names the target mesh doesn't have are dropped (that dim goes
    replicated), everything else keeps its split. The inverse of the
    ``ParamInfo.spec`` encoding, used by the resharded-restore path to
    land checkpointed params directly on the surviving mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    have = {str(a) for a in mesh.axis_names}
    entries = []
    for dims in (spec_dims or ()):
        kept = tuple(str(n) for n in (dims or ()) if str(n) in have)
        if not kept:
            entries.append(None)
        elif len(kept) == 1:
            entries.append(kept[0])
        else:
            entries.append(kept)
    return NamedSharding(mesh, P(*entries))


def full_host_value(x):
    """Full (unsharded) host value of an array, whatever its layout:
    fully-addressable arrays are materialized directly; arrays sharded
    across processes are first replicated by an identity jit (an
    all-gather on the wire — collective, so every participating
    process must call this in the same order). The gang checkpoint
    path uses it to persist cross-process GSPMD state from rank 0."""
    import jax
    import numpy as np

    if not hasattr(x, "sharding") or getattr(
            x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.sharding import NamedSharding, PartitionSpec as P

    replicate = jax.jit(
        lambda v: v,
        out_shardings=NamedSharding(x.sharding.mesh, P()),
    )
    return np.asarray(replicate(x))


def sharding_tree_info(params, shardings):
    """The sharding tree **as data**: one
    :class:`~sparkdl_tpu.analysis.ParamInfo` per leaf carrying the full
    shape/dtype, the per-dim mesh-axis spec (``.spec``) and the mesh
    axis sizes the sharding was built against (``.mesh_axes``) — no
    live jax sharding objects, so the result pickles, diffs, and can
    be re-laid onto any *target* mesh. This is the input
    :func:`sparkdl_tpu.analysis.comms.reshard_plan` (the elastic
    pre-flight), the ``implicit-reshard`` pass, and the target-mesh
    mode of ``hbm-overcommit`` consume."""
    from sparkdl_tpu.analysis import param_info_from

    return param_info_from(params, shardings)


# Megatron-style rules for the transformer models in
# sparkdl_tpu.models: column-parallel up-projections, row-parallel
# down-projections, replicated norms.
TRANSFORMER_RULES = [
    (r"embed.*embedding", (None, "model")),
    (r"(q_proj|k_proj|v_proj|qkv).*kernel", (("fsdp",), "model")),
    (r"o_proj.*kernel", ("model", ("fsdp",))),
    (r"(gate_proj|up_proj|fc1).*kernel", (("fsdp",), "model")),
    (r"(down_proj|fc2).*kernel", ("model", ("fsdp",))),
    (r"lm_head.*kernel", (("fsdp",), "model")),
    (r"lora_a$", (None, None)),
    (r"lora_b$", (None, "model")),
    # Stacked MoE expert weights (E, d, f): experts over 'model' (the
    # expert-parallel axis of the GSPMD path; router stays replicated)
    # and the per-expert matrix over 'fsdp' like every dense kernel —
    # expert weights are the dominant memory, they must not lose ZeRO-3.
    (r"w_(gate|up|down)$", ("model", ("fsdp",))),
    (r"(norm|ln|layernorm).*", ()),
]
