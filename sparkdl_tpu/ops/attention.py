"""Attention dispatch: pallas flash kernels on TPU, XLA reference
elsewhere, with padding and layout handling.

Public shape convention matches the models: (batch, seq, heads,
head_dim). Both directions are fused pallas kernels: the forward saves
only the per-row logsumexp, and the custom_vjp backward recomputes
probabilities tile-by-tile (dq kernel + dk/dv kernel) — O(S·D) memory
for training end to end.
"""

import functools
import os

import jax

from sparkdl_tpu.ops._dispatch import block_for, pad_to as _pad_to, use_pallas as _use_pallas
from sparkdl_tpu.parallel.ring_attention import attention_reference

# Process-level default tile, read ONCE at import (see flash_attention's
# docstring for why a trace-time env read would be a footgun).
_DEFAULT_FLASH_BLOCK = int(os.environ.get("SPARKDL_TPU_FLASH_BLOCK", 128))


# custom_vjp over the PADDED (B, H, S, D) core: both forward and
# backward are fused pallas kernels; padding/layout transforms sit
# outside and differentiate through standard XLA transposes.


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, causal, scale, block, interpret):
    from sparkdl_tpu.ops.pallas.flash_attention import flash_attention_bhsd

    return flash_attention_bhsd(
        q, k, v, causal=causal, scale=scale, bq=block, bk=block,
        interpret=interpret,
    )


def _flash_core_fwd(q, k, v, causal, scale, block, interpret):
    from sparkdl_tpu.ops.pallas.flash_attention import flash_attention_bhsd

    o, lse = flash_attention_bhsd(
        q, k, v, causal=causal, scale=scale, bq=block, bk=block,
        interpret=interpret, return_lse=True,
    )
    return o, (q, k, v, o, lse)


def _flash_core_bwd(causal, scale, block, interpret, res, do):
    import jax.numpy as jnp

    from sparkdl_tpu.ops.pallas.flash_attention import (
        flash_attention_bwd_bhsd,
    )

    q, k, v, o, lse = res
    # keepdims: lse/delta ride (B, H, S, 1) blocks (TPU tiling, see
    # flash_attention_bhsd docstring).
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    dq, dk, dv = flash_attention_bwd_bhsd(
        q, k, v, do, lse, delta, causal=causal, scale=scale,
        bq=block, bk=block, interpret=interpret,
    )
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, *, causal=True, scale=None, interpret=None,
                    block=None):
    """Fused attention on (batch, seq, heads, head_dim) tensors —
    pallas forward AND backward on TPU (or ``interpret=True`` for
    tests); XLA reference elsewhere.

    ``block``: q/k tile size (larger tiles amortize K/V streaming and
    widen the per-program matmuls at short seq). Defaults to
    ``SPARKDL_TPU_FLASH_BLOCK`` read ONCE at import — callers are
    jitted and the env var is not part of the jit cache key, so a
    mid-process env change must never silently retune (or fail to
    retune) an already-traced program. Sweeps pass ``block``
    explicitly (via ``LlamaConfig.flash_block``), which changes the
    traced call and therefore the cache key.
    """
    if interpret is None:
        if not _use_pallas():
            return attention_reference(q, k, v, causal=causal, scale=scale)
        interpret = False
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    s = qt.shape[2]
    tile = int(block) if block else _DEFAULT_FLASH_BLOCK
    block = block_for(s, tile=tile)
    qt, pad = _pad_to(qt, block, 2)
    if pad and not causal:
        # padded keys must not receive attention weight: causal masking
        # excludes them (queries come first); for bidirectional
        # attention fall back to the reference path.
        return attention_reference(q, k, v, causal=False, scale=scale)
    kt, _ = _pad_to(kt, block, 2)
    vt, _ = _pad_to(vt, block, 2)
    out = _flash_core(qt, kt, vt, causal, scale, block, interpret)
    if pad:
        out = out[:, :, :s, :]
    return out.transpose(0, 2, 1, 3)
