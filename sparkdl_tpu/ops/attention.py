"""Attention dispatch: pallas flash kernels on TPU, XLA reference
elsewhere, with padding and layout handling.

Public shape convention matches the models: (batch, seq, heads,
head_dim). Both directions are fused pallas kernels: the forward saves
only the per-row logsumexp, and the custom_vjp backward recomputes
probabilities tile-by-tile (dq kernel + dk/dv kernel) — O(S·D) memory
for training end to end.
"""

import functools
import math
import os

import jax

from sparkdl_tpu.ops._dispatch import block_for, pad_to as _pad_to, use_pallas as _use_pallas
from sparkdl_tpu.parallel.ring_attention import attention_reference

# Process-level default tiles, read ONCE at import (see
# flash_attention's docstring for why a trace-time env read would be a
# footgun). The per-dimension q/kv tiles are the autotuner's targets
# (registered tunable knobs); unset they inherit the legacy square
# block.
_DEFAULT_FLASH_BLOCK = int(os.environ.get("SPARKDL_TPU_FLASH_BLOCK", 128))
_DEFAULT_FLASH_BLOCK_Q = int(
    os.environ.get("SPARKDL_TPU_FLASH_BLOCK_Q", 0)) or _DEFAULT_FLASH_BLOCK
_DEFAULT_FLASH_BLOCK_KV = int(
    os.environ.get("SPARKDL_TPU_FLASH_BLOCK_KV", 0)) or _DEFAULT_FLASH_BLOCK


# custom_vjp over the PADDED (B, H, S, D) core: both forward and
# backward are fused pallas kernels; padding/layout transforms sit
# outside and differentiate through standard XLA transposes.


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, causal, scale, bq, bk, interpret):
    from sparkdl_tpu.ops.pallas.flash_attention import flash_attention_bhsd

    return flash_attention_bhsd(
        q, k, v, causal=causal, scale=scale, bq=bq, bk=bk,
        interpret=interpret,
    )


def _flash_core_fwd(q, k, v, causal, scale, bq, bk, interpret):
    from sparkdl_tpu.ops.pallas.flash_attention import flash_attention_bhsd

    o, lse = flash_attention_bhsd(
        q, k, v, causal=causal, scale=scale, bq=bq, bk=bk,
        interpret=interpret, return_lse=True,
    )
    return o, (q, k, v, o, lse)


def _flash_core_bwd(causal, scale, bq, bk, interpret, res, do):
    import jax.numpy as jnp

    from sparkdl_tpu.ops.pallas.flash_attention import (
        flash_attention_bwd_bhsd,
    )

    q, k, v, o, lse = res
    # keepdims: lse/delta ride (B, H, S, 1) blocks (TPU tiling, see
    # flash_attention_bhsd docstring).
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
        keepdims=True,
    )
    dq, dk, dv = flash_attention_bwd_bhsd(
        q, k, v, do, lse, delta, causal=causal, scale=scale,
        bq=bq, bk=bk, interpret=interpret,
    )
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, *, causal=True, scale=None, interpret=None,
                    block=None, block_q=None, block_kv=None):
    """Fused attention on (batch, seq, heads, head_dim) tensors —
    pallas forward AND backward on TPU (or ``interpret=True`` for
    tests); XLA reference elsewhere.

    ``block``: square q/k tile size (larger tiles amortize K/V
    streaming and widen the per-program matmuls at short seq).
    ``block_q`` / ``block_kv`` override the q and kv tiles
    independently — the shapes the autotuner searches via the
    ``SPARKDL_TPU_FLASH_BLOCK_Q`` / ``SPARKDL_TPU_FLASH_BLOCK_KV``
    knobs. All tile defaults are read ONCE at import — callers are
    jitted and env vars are not part of the jit cache key, so a
    mid-process env change must never silently retune (or fail to
    retune) an already-traced program. Sweeps pass tiles explicitly
    (via ``LlamaConfig.flash_block``), which changes the traced call
    and therefore the cache key.
    """
    if interpret is None:
        if not _use_pallas():
            return attention_reference(q, k, v, causal=causal, scale=scale)
        interpret = False
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    s = qt.shape[2]
    tile_q = int(block_q) if block_q else (
        int(block) if block else _DEFAULT_FLASH_BLOCK_Q)
    tile_kv = int(block_kv) if block_kv else (
        int(block) if block else _DEFAULT_FLASH_BLOCK_KV)
    bq = block_for(s, tile=tile_q)
    bk = block_for(s, tile=tile_kv)
    # the kernel needs the (padded) seq divisible by BOTH tiles
    mult = bq * bk // math.gcd(bq, bk)
    qt, pad = _pad_to(qt, mult, 2)
    if pad and not causal:
        # padded keys must not receive attention weight: causal masking
        # excludes them (queries come first); for bidirectional
        # attention fall back to the reference path.
        return attention_reference(q, k, v, causal=False, scale=scale)
    kt, _ = _pad_to(kt, mult, 2)
    vt, _ = _pad_to(vt, mult, 2)
    out = _flash_core(qt, kt, vt, causal, scale, bq, bk, interpret)
    if pad:
        out = out[:, :, :s, :]
    return out.transpose(0, 2, 1, 3)
