"""Attention dispatch: pallas flash kernel on TPU, XLA reference
elsewhere, with padding and layout handling.

Public shape convention matches the models: (batch, seq, heads,
head_dim). Gradients flow through a custom_vjp whose backward
recomputes via the XLA reference path (fused backward kernel is on the
kernel roadmap; the forward kernel is what serving latency sees).
"""

import functools

import jax

from sparkdl_tpu.ops._dispatch import block_for, pad_to as _pad_to, use_pallas as _use_pallas
from sparkdl_tpu.parallel.ring_attention import attention_reference


def _flash_fwd(q, k, v, causal, scale, interpret):
    from sparkdl_tpu.ops.pallas.flash_attention import flash_attention_bhsd

    # (B, S, H, D) -> (B, H, S, D); pad S to the 128 tile
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    s = qt.shape[2]
    block = block_for(s)
    qt, pad = _pad_to(qt, block, 2)
    kt, _ = _pad_to(kt, block, 2)
    vt, _ = _pad_to(vt, block, 2)
    if pad and not causal:
        # padded keys must not receive attention weight: causal masking
        # already excludes them for causal=True (queries come first);
        # for bidirectional attention fall back to the reference path.
        return attention_reference(q, k, v, causal=False, scale=scale)
    out = flash_attention_bhsd(
        qt, kt, vt, causal=causal, scale=scale, bq=block, bk=block,
        interpret=interpret,
    )
    if pad:
        out = out[:, :, : s, :]
    return out.transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, interpret):
    return _flash_fwd(q, k, v, causal, scale, interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, interpret):
    return _flash_fwd(q, k, v, causal, scale, interpret), (q, k, v)


def _flash_vjp_bwd(causal, scale, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(
            q_, k_, v_, causal=causal, scale=scale
        ),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal=True, scale=None, interpret=None):
    """Fused attention on (batch, seq, heads, head_dim) tensors.

    Uses the pallas TPU kernel when running on TPU (or when
    ``interpret=True`` for testing on CPU); otherwise the XLA reference
    implementation.
    """
    if interpret is None:
        if not _use_pallas():
            return attention_reference(q, k, v, causal=causal, scale=scale)
        interpret = False
    return _flash(q, k, v, causal, scale, interpret)
