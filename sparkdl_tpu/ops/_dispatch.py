"""Shared kernel-dispatch helpers: backend probe, tile-padding."""

import jax
import jax.numpy as jnp


def use_pallas():
    """True when the default backend compiles pallas TPU kernels."""
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def block_for(size, tile=128, floor=8):
    """Tile size for a dimension: the full tile when it fits, else a
    small multiple that at least satisfies sublane constraints."""
    return tile if size >= tile else max(floor, size)


def pad_to(x, multiple, axis):
    """Zero-pad ``axis`` up to a multiple; returns (padded, pad)."""
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad
