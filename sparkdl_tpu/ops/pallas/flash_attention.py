"""Pallas TPU flash attention (forward).

The hot op of every transformer in the model zoo. Design (per the
pallas TPU playbook):

- grid ``(batch, heads, q_blocks)``; each program holds one q tile in
  VMEM and streams K/V tiles of its (batch, head) slice through the
  MXU, maintaining the numerically stable running-softmax state
  (m, l, acc) in fp32 registers — attention scores never materialize
  in HBM, so memory is O(S·D) instead of O(S²).
- causal masking prunes the k-loop: q block i only visits k blocks
  ``<= ceil((i+1)·BQ / BK)`` (no wasted MXU work on fully-masked
  tiles); the partial diagonal tile is masked with an iota compare.
- fp32 accumulation with ``preferred_element_type`` on both matmuls;
  bf16 inputs hit the MXU natively.

The public wrapper pads S to the tile size and handles (B, S, H, D)
layout. The BACKWARD is fused too: the forward saves only the per-row
logsumexp (B, H, S); backward recomputes attention probabilities
tile-by-tile from (q, k, lse) and accumulates dq (one kernel, grid over
q tiles) and dk/dv (one kernel, grid over kv tiles) — standard
flash-attention backward, O(S·D) memory end to end, causal-pruned in
both directions.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _causal_keep(q_start, k_start, bq, bk):
    """Block-local causal visibility mask (q_pos >= k_pos), shared by
    the forward and both backward kernels so masking semantics can
    never diverge between them."""
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos



def _make_kernel(bq, bk, seq_len, causal, scale, with_lse=False):
    from jax.experimental import pallas as pl

    n_k_blocks = seq_len // bk

    def kernel(q_ref, k_ref, v_ref, o_ref, *maybe_lse):
        qi = pl.program_id(2)
        # Matmul INPUTS stay in the storage dtype (bf16 on TPU): the
        # MXU takes bf16 natively at full rate, while fp32 operands
        # run as multi-pass bf16 splits — casting up front would
        # throttle both matmuls. fp32 happens where it matters: the
        # accumulators (preferred_element_type) and the softmax state.
        q = q_ref[0, 0]                                      # (bq, d)
        d = q.shape[-1]

        def body(j, carry):
            m, l, acc = carry
            kb = k_ref[0, 0, pl.ds(j * bk, bk), :]
            vb = v_ref[0, 0, pl.ds(j * bk, bk), :]
            s_ij = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                         # (bq, bk)
            if causal:
                s_ij = jnp.where(
                    _causal_keep(qi * bq, j * bk, bq, bk), s_ij, NEG_INF
                )
            m_blk = jnp.max(s_ij, axis=-1)
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp(s_ij - m_new[:, None])
            p = jnp.where((m_new <= NEG_INF / 2)[:, None], 0.0, p)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            # p in [0,1] keeps full relative precision through the
            # bf16 cast; the accumulation below stays fp32
            pv = jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_new = acc * alpha[:, None] + pv
            return m_new, l_new, acc_new

        m0 = jnp.full((bq,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((bq,), jnp.float32)
        acc0 = jnp.zeros((bq, q.shape[-1]), jnp.float32)
        if causal:
            # last k block this q block can see (prunes future tiles)
            upper = jnp.minimum(
                (qi * bq + bq + bk - 1) // bk, n_k_blocks
            )
        else:
            upper = n_k_blocks
        m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
        out = acc / jnp.maximum(l, 1e-30)[:, None]
        o_ref[0, 0] = out.astype(o_ref.dtype)
        if with_lse:
            # logsumexp per row: softmax probs are exp(s - lse) in bwd.
            # Carried as (..., bq, 1): TPU tiling requires the last two
            # block dims to be (mult of 8, mult of 128 | full dim) — a
            # rank-3 (1, 1, bq) block violates that on real hardware.
            maybe_lse[0][0, 0] = (
                m + jnp.log(jnp.maximum(l, 1e-30))
            )[:, None]

    return kernel


def flash_attention_bhsd(q, k, v, *, causal=True, scale=None, bq=128,
                         bk=128, interpret=False, return_lse=False):
    """Flash attention on (batch, heads, seq, head_dim) arrays.

    seq must be divisible by the block sizes (the public wrapper in
    :mod:`sparkdl_tpu.ops.attention` pads). With ``return_lse`` also
    returns the per-row logsumexp (B, H, S, 1) for the fused backward
    (trailing singleton: see the tiling note in the kernel).
    """
    from jax.experimental import pallas as pl
    from sparkdl_tpu.utils.jax_compat import tpu_compiler_params

    b, h, s, d = q.shape
    scale = scale or (d ** -0.5)
    bq = min(bq, s)
    bk = min(bk, s)
    if s % bq or s % bk:
        raise ValueError(f"seq {s} must be divisible by bq={bq}, bk={bk}")

    kernel = _make_kernel(bq, bk, s, causal, scale, with_lse=return_lse)
    grid = (b, h, s // bq)
    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, i: (bi, hi, i, 0))
    kv_spec = pl.BlockSpec((1, 1, s, d), lambda bi, hi, i: (bi, hi, 0, 0))
    lse_spec = pl.BlockSpec(
        (1, 1, bq, 1), lambda bi, hi, i: (bi, hi, i, 0)
    )
    out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    if return_lse:
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        )
    out = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=(q_spec, lse_spec) if return_lse else q_spec,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out


def _make_dq_kernel(bq, bk, seq_len, causal, scale):
    from jax.experimental import pallas as pl

    n_k_blocks = seq_len // bk

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref):
        qi = pl.program_id(2)
        # bf16 operands into every matmul (MXU-native rate), fp32
        # accumulators — see the forward kernel's dtype note.
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]                           # (bq,)
        delta = delta_ref[0, 0, :, 0]                       # (bq,)

        def body(j, dq):
            kb = k_ref[0, 0, pl.ds(j * bk, bk), :]
            vb = v_ref[0, 0, pl.ds(j * bk, bk), :]
            s_ij = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            p = jnp.exp(s_ij - lse[:, None])
            if causal:
                p = jnp.where(
                    _causal_keep(qi * bq, j * bk, bq, bk), p, 0.0
                )
            dp = jax.lax.dot_general(
                do, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta[:, None]) * scale
            return dq + jax.lax.dot_general(
                ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        upper = (
            jnp.minimum((qi * bq + bq + bk - 1) // bk, n_k_blocks)
            if causal else n_k_blocks
        )
        dq = jax.lax.fori_loop(
            0, upper, body, jnp.zeros(q.shape, jnp.float32)
        )
        dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(bq, bk, seq_len, causal, scale):
    from jax.experimental import pallas as pl

    n_q_blocks = seq_len // bq

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dk_ref, dv_ref):
        ki = pl.program_id(2)
        # bf16 operands into every matmul (MXU-native rate), fp32
        # accumulators — see the forward kernel's dtype note.
        kb = k_ref[0, 0]                                    # (bk, d)
        vb = v_ref[0, 0]

        def body(i, carry):
            dk, dv = carry
            qb = q_ref[0, 0, pl.ds(i * bq, bq), :]
            dob = do_ref[0, 0, pl.ds(i * bq, bq), :]
            lse = lse_ref[0, 0, pl.ds(i * bq, bq), 0]
            delta = delta_ref[0, 0, pl.ds(i * bq, bq), 0]
            s_ij = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                       # (bq, bk)
            p = jnp.exp(s_ij - lse[:, None])
            if causal:
                p = jnp.where(
                    _causal_keep(i * bq, ki * bk, bq, bk), p, 0.0
                )
            dv = dv + jax.lax.dot_general(
                p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta[:, None]) * scale
            return dk + jax.lax.dot_general(
                ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ), dv

        # causal: only q blocks at or after this kv block contribute
        lower = (ki * bk) // bq if causal else 0
        dk0 = jnp.zeros(kb.shape, jnp.float32)
        dv0 = jnp.zeros(vb.shape, jnp.float32)
        dk, dv = jax.lax.fori_loop(lower, n_q_blocks, body, (dk0, dv0))
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv.astype(dv_ref.dtype)

    return kernel


def flash_attention_bwd_bhsd(q, k, v, do, lse, delta, *, causal=True,
                             scale=None, bq=128, bk=128, interpret=False):
    """Fused backward: (dq, dk, dv) from saved (q, k, v, lse) and the
    output-gradient rowsum delta = sum(do * o, -1, keepdims=True); lse
    and delta are (B, H, S, 1) per the forward's tiling note."""
    from jax.experimental import pallas as pl
    from sparkdl_tpu.utils.jax_compat import tpu_compiler_params

    b, h, s, d = q.shape
    scale = scale or (d ** -0.5)
    bq = min(bq, s)
    bk = min(bk, s)
    if s % bq or s % bk:
        raise ValueError(f"seq {s} must be divisible by bq={bq}, bk={bk}")

    q_tile = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, i: (bi, hi, i, 0))
    k_tile = pl.BlockSpec((1, 1, bk, d), lambda bi, hi, i: (bi, hi, i, 0))
    full_s = pl.BlockSpec((1, 1, s, d), lambda bi, hi, i: (bi, hi, 0, 0))
    vec_q = pl.BlockSpec(
        (1, 1, bq, 1), lambda bi, hi, i: (bi, hi, i, 0)
    )
    vec_full = pl.BlockSpec(
        (1, 1, s, 1), lambda bi, hi, i: (bi, hi, 0, 0)
    )
    params = tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "parallel"),
    )

    dq = pl.pallas_call(
        _make_dq_kernel(bq, bk, s, causal, scale),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(b, h, s // bq),
        in_specs=[q_tile, full_s, full_s, q_tile, vec_q, vec_q],
        out_specs=q_tile,
        compiler_params=params,
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        _make_dkv_kernel(bq, bk, s, causal, scale),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        grid=(b, h, s // bk),
        in_specs=[full_s, k_tile, k_tile, full_s, vec_full, vec_full],
        out_specs=(k_tile, k_tile),
        compiler_params=params,
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv
