"""Pallas TPU flash attention (forward).

The hot op of every transformer in the model zoo. Design (per the
pallas TPU playbook):

- grid ``(batch, heads, q_blocks)``; each program holds one q tile in
  VMEM and streams K/V tiles of its (batch, head) slice through the
  MXU, maintaining the numerically stable running-softmax state
  (m, l, acc) in fp32 registers — attention scores never materialize
  in HBM, so memory is O(S·D) instead of O(S²).
- causal masking prunes the k-loop: q block i only visits k blocks
  ``<= ceil((i+1)·BQ / BK)`` (no wasted MXU work on fully-masked
  tiles); the partial diagonal tile is masked with an iota compare.
- fp32 accumulation with ``preferred_element_type`` on both matmuls;
  bf16 inputs hit the MXU natively.

The public wrapper pads S to the tile size and handles (B, S, H, D)
layout; backward currently recomputes through the XLA reference path
via custom_vjp (a fused backward kernel is the next kernel on the
roadmap — forward is where inference/serving time goes).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _make_kernel(bq, bk, seq_len, causal, scale):
    from jax.experimental import pallas as pl

    n_k_blocks = seq_len // bk

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(2)
        q = q_ref[0, 0].astype(jnp.float32) * scale          # (bq, d)
        d = q.shape[-1]

        def body(j, carry):
            m, l, acc = carry
            kb = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
            vb = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
            s_ij = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                 # (bq, bk)
            if causal:
                q_pos = qi * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0
                )
                k_pos = j * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                s_ij = jnp.where(q_pos >= k_pos, s_ij, NEG_INF)
            m_blk = jnp.max(s_ij, axis=-1)
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp(s_ij - m_new[:, None])
            p = jnp.where((m_new <= NEG_INF / 2)[:, None], 0.0, p)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            pv = jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_new = acc * alpha[:, None] + pv
            return m_new, l_new, acc_new

        m0 = jnp.full((bq,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((bq,), jnp.float32)
        acc0 = jnp.zeros((bq, q.shape[-1]), jnp.float32)
        if causal:
            # last k block this q block can see (prunes future tiles)
            upper = jnp.minimum(
                (qi * bq + bq + bk - 1) // bk, n_k_blocks
            )
        else:
            upper = n_k_blocks
        m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
        out = acc / jnp.maximum(l, 1e-30)[:, None]
        o_ref[0, 0] = out.astype(o_ref.dtype)

    return kernel


def flash_attention_bhsd(q, k, v, *, causal=True, scale=None, bq=128,
                         bk=128, interpret=False):
    """Flash attention on (batch, heads, seq, head_dim) arrays.

    seq must be divisible by the block sizes (the public wrapper in
    :mod:`sparkdl_tpu.ops.attention` pads).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    scale = scale or (d ** -0.5)
    bq = min(bq, s)
    bk = min(bk, s)
    if s % bq or s % bk:
        raise ValueError(f"seq {s} must be divisible by bq={bq}, bk={bk}")

    kernel = _make_kernel(bq, bk, s, causal, scale)
    grid = (b, h, s // bq)
    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, i: (bi, hi, i, 0))
    kv_spec = pl.BlockSpec((1, 1, s, d), lambda bi, hi, i: (bi, hi, 0, 0))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel"),
        ),
        interpret=interpret,
    )(q, k, v)
