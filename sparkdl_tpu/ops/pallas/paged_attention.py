"""Paged-attention decode kernel: attend a single-step query over a
POOLED paged KV cache through per-row block tables, reading only the
pages a row actually owns.

The XLA fallback in the model (``llama.py`` paged decode) gathers the
whole logical view first — ``pool[tables]`` materializes a
(B, max_pages·page, Hkv, D) copy in HBM and then reads it again for
attention, plus a ``jnp.repeat`` copy of K/V for GQA. Decode is
HBM-bandwidth-bound, so that ~3x traffic is ~3x step time at capacity.
This kernel instead:

- prefetches the block table and per-row lengths as SCALARS
  (``PrefetchScalarGridSpec``) so each grid step's page index is known
  before the body runs, and the pipeline DMAs exactly ONE (page, D)
  K/V tile per (row, kv-head, page) program — pages beyond a row's
  length are masked out, and rows share nothing;
- keeps the whole GQA query group (``rep`` query heads per kv head) in
  VMEM against that one tile — no repeated K/V, the MXU sees a
  (rep, page) × (page, D) pair per step;
- accumulates in the numerically-stable flash form (running max +
  rescaled sums) across the sequential page axis in VMEM scratch.

vLLM's paged_attention (CUDA) and the jax-in-tree TPU port are the
published precedents for the scalar-prefetch pattern; this kernel is
written for THIS engine's pool layout (page-major (n_pages, page,
Hkv, D), dump-page 0 for padding junk — see models/serving.py).
"""

import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Pages DMA'd per grid step (registered tunable knob). Read ONCE at
# import: the kernel is traced inside jitted engine programs and env
# vars are not part of the jit cache key — a mid-process flip must
# never silently retune an already-traced program. The autotuner runs
# each trial in a fresh subprocess, so trials see their own value;
# per-call overrides go through ``pages_per_block=``.
_DEFAULT_PAGES_PER_BLOCK = int(
    os.environ.get("SPARKDL_TPU_PAGED_PAGES_PER_BLOCK", 1))


def _kernel(page, rep, scale, n_grid, ppb):
    from jax.experimental import pallas as pl

    def kernel(tables_ref, lens_ref, q_ref, *refs):
        k_refs = refs[:ppb]
        v_refs = refs[ppb:2 * ppb]
        o_ref = refs[2 * ppb]
        acc_ref, m_ref, l_ref = refs[2 * ppb + 1:]
        b = pl.program_id(0)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)

        length = lens_ref[b]

        # unrolled over the ppb page tiles of this grid step; each
        # logical page jj masks itself against the row length (jj*page
        # < length also implies jj < max_pages, so the clamped index
        # map for the ragged final step can never let a duplicate
        # page through)
        for t in range(ppb):
            jj = j * ppb + t

            @pl.when(jj * page < length)
            def _attend(t=t, jj=jj):
                q = q_ref[0, 0]                       # (rep, D)
                k = k_refs[t][0, :, 0, :]             # (page, D)
                v = v_refs[t][0, :, 0, :]             # (page, D)
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale                             # (rep, page)
                pos = jj * page + jax.lax.broadcasted_iota(
                    jnp.int32, (1, page), 1)
                s = jnp.where(pos < length, s, NEG_INF)
                m_prev = m_ref[...]                   # (rep, 1)
                l_prev = l_ref[...]
                m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
                alpha = jnp.exp(m_prev - m_new)
                p = jnp.exp(s - m_new)                # (rep, page)
                l_ref[...] = (
                    l_prev * alpha + p.sum(axis=-1, keepdims=True))
                m_ref[...] = m_new
                acc_ref[...] = (
                    acc_ref[...] * alpha
                    + jax.lax.dot_general(
                        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )

        @pl.when(j == n_grid - 1)
        def _finalize():
            o_ref[0, 0] = (
                acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
            ).astype(o_ref.dtype)

    return kernel


def paged_attention_decode(q, k_pool, v_pool, tables, lens, *,
                           scale=None, interpret=False,
                           pages_per_block=None):
    """One decode step over the paged pool.

    Args:
      q: (B, H, D) — this step's queries, H = Hkv * rep (GQA).
      k_pool, v_pool: (n_pages, page, Hkv, D) pooled physical cache.
      tables: (B, max_pages) int32 block tables (unused slots may
        point anywhere valid — typically the dump page 0; they are
        masked by ``lens``).
      lens: (B,) int32 — number of visible tokens per row (the row's
        current position + 1: the just-written token attends to
        itself).
      pages_per_block: K/V page tiles DMA'd per grid step (default:
        the ``SPARKDL_TPU_PAGED_PAGES_PER_BLOCK`` knob). The pool's
        pages are physically discontiguous, so a wider step is not one
        bigger block — the pool rides the call once per tile, each
        with its own table-indexed BlockSpec, and the kernel unrolls
        over the tiles. More pages per step amortize grid overhead at
        long contexts; the tradeoff is VMEM and is device-shaped,
        which is why it is an autotuner target.
    Returns: (B, H, D) attention output in q.dtype.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from sparkdl_tpu.utils.jax_compat import tpu_compiler_params

    b, h, d = q.shape
    n_pages, page, hkv, dk = k_pool.shape
    assert dk == d and h % hkv == 0, (q.shape, k_pool.shape)
    rep = h // hkv
    max_pages = tables.shape[1]
    scale = scale if scale is not None else d ** -0.5
    ppb = int(pages_per_block or _DEFAULT_PAGES_PER_BLOCK)
    ppb = max(1, min(ppb, max_pages))

    qg = q.reshape(b, hkv, rep, d)
    tables = tables.astype(jnp.int32)
    lens = lens.astype(jnp.int32)

    n_grid = pl.cdiv(max_pages, ppb)
    grid = (b, hkv, n_grid)
    # index maps see (grid..., *scalar_prefetch_refs)
    q_spec = pl.BlockSpec(
        (1, 1, rep, d), lambda bi, hi, j, tbl, ln: (bi, hi, 0, 0))

    def kv_spec(t):
        # tile t of a grid step covers logical page j*ppb + t; the
        # ragged final step clamps the table column (the duplicate
        # reads it causes are masked in-kernel by the lens check)
        def index(bi, hi, j, tbl, ln, t=t):
            jj = jnp.minimum(j * ppb + t, max_pages - 1)
            return (tbl[bi, jj], 0, hi, 0)

        return pl.BlockSpec((1, page, 1, d), index)

    out_spec = pl.BlockSpec(
        (1, 1, rep, d), lambda bi, hi, j, tbl, ln: (bi, hi, 0, 0))

    out = pl.pallas_call(
        _kernel(page, rep, scale, n_grid, ppb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=([q_spec]
                      + [kv_spec(t) for t in range(ppb)]
                      + [kv_spec(t) for t in range(ppb)]),
            out_specs=out_spec,
            scratch_shapes=[
                pltpu.VMEM((rep, d), jnp.float32),   # acc
                pltpu.VMEM((rep, 1), jnp.float32),   # running max
                pltpu.VMEM((rep, 1), jnp.float32),   # running sum
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(tables, lens, qg, *([k_pool] * ppb), *([v_pool] * ppb))
    return out.reshape(b, h, d)


def paged_attention_decode_sharded(mesh, *, axis_name="model",
                                   scale=None, interpret=False,
                                   pages_per_block=None):
    """Bind the paged decode kernel to a TP mesh: the pool is sharded
    over its kv-head axis on ``axis_name`` (exactly the serving
    engine's cache sharding) and each device runs the kernel on its
    LOCAL kv heads — every kv head's GQA query group is co-resident
    with it, so the shard_map needs no collectives at all; the o_proj
    that follows does the psum, same as the gather path.

    Returns ``f(q, k_pool, v_pool, tables, lens)`` on GLOBAL arrays:
    q (B, H, D) sharded over heads, pools (P, page, Hkv, D) sharded
    over kv heads, tables/lens replicated."""
    from jax.sharding import PartitionSpec as P

    def local_fn(q, k_pool, v_pool, tables, lens):
        return paged_attention_decode(
            q, k_pool, v_pool, tables, lens, scale=scale,
            interpret=interpret, pages_per_block=pages_per_block,
        )

    from sparkdl_tpu.utils.jax_compat import shard_map

    return shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(None, axis_name, None),
                  P(None, None, axis_name, None),
                  P(None, None, axis_name, None),
                  P(), P()),
        out_specs=P(None, axis_name, None),
        check_vma=False,
    )
