"""Pallas TPU weight-only int8/int4 matmul with fused dequant.

Serving-side kernels (pallas guide §Quantization): weights live in HBM
as int8 (or nibble-packed int4) with fp32 scales — half/quarter the
bytes of bf16/fp32, which matters because decode-time matmuls are
HBM-bandwidth bound. The kernels are K-blocked: each (i, j) output
tile owns an fp32 VMEM accumulator and streams quantized weight tiles
through the MXU, dequantizing on the fly in the inner loop. Edge tiles
of non-divisible M/N/K shapes are masked in-kernel (no host-side
padding copies).

Dispatch is governed by the ``SPARKDL_TPU_KERNEL_QUANT_MATMUL`` knob
(``auto`` | ``off`` | ``force_interpret``): ``auto`` runs the kernel
on TPU and the XLA dequant lowering elsewhere, ``off`` pins the XLA
lowering everywhere, and ``force_interpret`` emulates the kernel on
any backend (the CPU equivalence oracle). Shapes the kernel cannot
serve degrade to the XLA lowering loudly (RuntimeWarning) — never to
a wrong answer.
"""

import functools
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np

# int4 group size (rows per scale); defined up top because
# quantize_params defaults to it
INT4_GROUP = 64

KERNEL_MODE_ENV = "SPARKDL_TPU_KERNEL_QUANT_MATMUL"
KERNEL_MODES = ("auto", "off", "force_interpret")

# Read ONCE at import: quantized_matmul runs under jit inside serving
# programs and env vars are not part of the jit cache key — a
# mid-process flip must never silently re-route already-traced
# programs (same rationale as ops.attention's flash block defaults).
# Per-call overrides go through the ``mode=`` argument, which callers
# thread from LlamaConfig.quant_kernel (part of the program cache key).
_DEFAULT_MODE = os.environ.get(KERNEL_MODE_ENV, "auto")


def _kernel_plan(mode):
    """Resolve a kernel mode to ``(use_kernel, interpret)``.

    ``mode`` "" falls back to the import-time knob default."""
    from sparkdl_tpu.ops._dispatch import use_pallas

    mode = mode or _DEFAULT_MODE
    if mode not in KERNEL_MODES:
        raise ValueError(
            f"unknown quant-matmul kernel mode {mode!r}; expected one "
            f"of {KERNEL_MODES} (knob {KERNEL_MODE_ENV})")
    if mode == "off":
        return False, False
    if mode == "force_interpret":
        return True, True
    return use_pallas(), False


def _fallback_warn(reason):
    warnings.warn(
        f"quant-matmul kernel unsupported ({reason}); degrading to the "
        "XLA dequant lowering", RuntimeWarning, stacklevel=3)


def quantize_int8(w):
    """Per-output-channel symmetric int8 quantization of a (K, N)
    weight matrix → (w_q int8 (K, N), scales fp32 (N,))."""
    w = np.asarray(w, np.float32)
    scales = np.abs(w).max(axis=0) / 127.0
    scales = np.where(scales == 0.0, 1.0, scales).astype(np.float32)
    w_q = np.clip(np.round(w / scales[None, :]), -127, 127).astype(np.int8)
    return w_q, scales


def _qmm_kernel(nk, k, bk, x_ref, wq_ref, scale_ref, o_ref, acc_ref):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:].astype(jnp.float32)
    if k % bk:
        # ragged final K tile: columns past K are block padding and may
        # hold anything — zero them out of the contraction (the int8
        # weight tile is finite garbage there, so masking x suffices)
        kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(kpos < k, x, 0.0)
    w = wq_ref[:].astype(jnp.float32)
    acc_ref[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == nk - 1)
    def _finalize():
        # per-column scales factor out of the K-sum, so one multiply at
        # the end is exact — the int8→fp32 dequant itself happens in
        # the inner loop feeding the MXU
        o_ref[:] = (acc_ref[:] * scale_ref[:][None, :]).astype(o_ref.dtype)


def quantized_matmul_pallas(x, w_q, scales, *, block_m=128, block_n=128,
                            block_k=512, interpret=False):
    """x (M, K) @ dequant(w_q (K, N)) with per-column scales (N,).

    K-blocked with an fp32 VMEM accumulator; non-divisible M/N/K are
    served by masked edge tiles, not host padding."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from sparkdl_tpu.ops._dispatch import block_for
    from sparkdl_tpu.utils.jax_compat import tpu_compiler_params

    m, k = x.shape
    _, n = w_q.shape
    bm = block_for(m, tile=block_m)
    bn = block_for(n, tile=block_n, floor=128)
    bk = min(block_k, k)
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn), pl.cdiv(k, bk))
    return pl.pallas_call(
        functools.partial(_qmm_kernel, grid[2], k, bk),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, ki: (i, ki)),
            pl.BlockSpec((bk, bn), lambda i, j, ki: (ki, j)),
            pl.BlockSpec((bn,), lambda i, j, ki: (j,)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, ki: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=tpu_compiler_params(
            # K innermost and sequential: the accumulator carries
            # across k steps of one (i, j) tile
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x, w_q, scales)


def quantized_matmul(x, w_q, scales, *, interpret=None, mode=""):
    """Dispatch: pallas kernel per the ``mode`` plan (see module
    docstring), XLA dequant-matmul otherwise.

    ``interpret`` is the legacy per-call override (True → interpreted
    kernel, False → compiled kernel) and wins over ``mode``."""
    if scales.shape != (w_q.shape[1],):
        # caller bug, not a kernel limitation: the XLA lowering would
        # broadcast a mis-shaped scale vector into a wrong-SHAPED
        # product, so there is no correct lowering to degrade to
        raise ValueError(
            f"scales shape {scales.shape} does not match N={w_q.shape[1]}")
    if interpret is not None:
        use_kernel, interp = True, bool(interpret)
    else:
        use_kernel, interp = _kernel_plan(mode)
    if use_kernel and w_q.dtype != jnp.int8:
        _fallback_warn(f"w_q dtype {w_q.dtype} is not int8")
        use_kernel = False
    if not use_kernel:
        w = w_q.astype(jnp.float32) * scales[None, :]
        return (x.astype(jnp.float32) @ w).astype(x.dtype)
    return quantized_matmul_pallas(x, w_q, scales, interpret=interp)


# Dense layers quantized by default: every 2-D projection of the
# decoder family; embeddings stay dense (a lookup reads one row).
DEFAULT_QUANT_TARGETS = ("gate_proj", "up_proj", "down_proj",
                         "q_proj", "k_proj", "v_proj",
                         "o_proj", "lm_head")


def quantize_params(params, targets=DEFAULT_QUANT_TARGETS, bits=8,
                    group=INT4_GROUP):
    """Quantize matching kernel leaves of a flax param tree →
    (new_params, bytes saved). ``bits=8``: per-column int8
    ('kernel_q' + 'kernel_scale'). ``bits=4``: group-wise nibble-packed
    int4 ('kernel_q4' + 'kernel_scale4')."""
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    saved = [0]

    def walk(node, name=""):
        if isinstance(node, dict):
            if ("kernel" in node and any(t in name for t in targets)
                    and getattr(node["kernel"], "ndim", 0) == 2):
                orig = node["kernel"]
                if bits == 8:
                    w_q, s = quantize_int8(np.asarray(orig, np.float32))
                    names = ("kernel_q", "kernel_scale")
                else:
                    w_q, s = quantize_int4(
                        np.asarray(orig, np.float32), group=group)
                    names = ("kernel_q4", "kernel_scale4")
                # savings accounted against the ORIGINAL dtype (bf16
                # kernels are 2 bytes/elt, not 4)
                saved[0] += (
                    np.asarray(orig).nbytes - w_q.nbytes - s.nbytes
                )
                out = dict(node)
                out[names[0]] = w_q
                out[names[1]] = s
                del out["kernel"]
                return out
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(params), saved[0]


def dequantize_params(qparams, dtype=jnp.bfloat16):
    """Reconstruct an apply-compatible param tree from
    :func:`quantize_params` output: every (kernel_q, kernel_scale) pair
    becomes a dense ``kernel`` again. Use this to run a standard
    ``model.apply`` off a quantized checkpoint; serving stacks that
    call :func:`quantized_matmul` directly can keep the int8 leaves."""

    def walk(node):
        if isinstance(node, dict):
            if "kernel_q" in node:
                out = {k: v for k, v in node.items()
                       if k not in ("kernel_q", "kernel_scale")}
                out["kernel"] = (
                    jnp.asarray(node["kernel_q"], jnp.float32)
                    * jnp.asarray(node["kernel_scale"])[None, :]
                ).astype(dtype)
                return out
            if "kernel_q4" in node:
                out = {k: v for k, v in node.items()
                       if k not in ("kernel_q4", "kernel_scale4")}
                scales = jnp.asarray(node["kernel_scale4"])
                k_full = 2 * node["kernel_q4"].shape[0]
                group = k_full // scales.shape[0]
                out["kernel"] = _dequant_int4(
                    jnp.asarray(node["kernel_q4"]), scales, group
                ).astype(dtype)
                return out
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)


# ---------------------------------------------------------------------------
# int4 weight-only: two nibbles per int8 byte along K, GROUP-wise
# scales (finer than int8's per-column — int4's 15 levels need them).
# Quarter the weight bytes of bf16; decode is HBM-bound, so bytes are
# step time.
# ---------------------------------------------------------------------------


def quantize_int4(w, group=INT4_GROUP):
    """Group-wise symmetric int4 quantization of (K, N) →
    (packed int8 (K//2, N), scales fp32 (K//group, N)). Row 2i rides
    the LOW nibble of packed row i, row 2i+1 the HIGH nibble."""
    w = np.asarray(w, np.float32)
    k, n = w.shape
    if k % max(group, 2):
        raise ValueError(f"K={k} must be divisible by group={group} (and 2)")
    g = w.reshape(k // group, group, n)
    scales = np.abs(g).max(axis=1) / 7.0              # (K//group, N)
    scales = np.where(scales == 0.0, 1.0, scales).astype(np.float32)
    w_q = np.clip(np.round(g / scales[:, None, :]), -7, 7)
    w_q = w_q.reshape(k, n).astype(np.int8)
    low = w_q[0::2].astype(np.uint8) & 0x0F
    high = (w_q[1::2].astype(np.uint8) & 0x0F) << 4
    packed = (low | high).view(np.int8)               # (K//2, N)
    return packed, scales


def unpack_int4(packed):
    """(K//2, N) packed int8 → (K, N) int8 in [-7, 7] (sign-extended
    nibbles; jnp ops only, shared by the kernel and the XLA path)."""
    p = packed.astype(jnp.int8)
    low = jnp.right_shift(jnp.left_shift(p, 4), 4)    # sign-extend low
    high = jnp.right_shift(p, 4)                      # arithmetic
    kh, n = p.shape
    return jnp.stack([low, high], axis=1).reshape(2 * kh, n)


def _dequant_int4(packed, scales, group):
    w = unpack_int4(packed).astype(jnp.float32)
    return w * jnp.repeat(scales, group, axis=0)


def _q4mm_kernel(group, nk, k, bk, x_ref, wq_ref, scale_ref, o_ref,
                 acc_ref):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:].astype(jnp.float32)
    # on-the-fly group dequant of this K tile: unpack nibbles, apply
    # the (bk // group, bn) scale slice row-repeated to (bk, bn)
    w = _dequant_int4(wq_ref[:], scale_ref[:], group)
    if k % bk:
        # ragged final K tile: block padding past K may hold anything
        # (the padded fp32 scale rows in particular) — zero BOTH
        # operands so no garbage (or NaN) reaches the accumulator
        kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(kpos < k, x, 0.0)
        wpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, w.shape, 0)
        w = jnp.where(wpos < k, w, 0.0)
    acc_ref[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def quantized_matmul_int4_pallas(x, packed, scales, *, group=INT4_GROUP,
                                 block_m=128, block_n=128, block_k=512,
                                 interpret=False):
    """x (M, K) @ dequant(packed (K//2, N)) with (K//group, N) scales.

    K-blocked like the int8 kernel; the K tile is rounded to a multiple
    of the scale group (and of 2 for the nibble packing) so each grid
    step sees whole groups."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from sparkdl_tpu.ops._dispatch import block_for
    from sparkdl_tpu.utils.jax_compat import tpu_compiler_params

    m, k = x.shape
    kh, n = packed.shape
    assert k == 2 * kh, (x.shape, packed.shape)
    assert k == group * scales.shape[0], (k, group, scales.shape)
    bm = block_for(m, tile=block_m)
    bn = block_for(n, tile=block_n, floor=128)
    # whole groups per K tile: lcm(group, 2) ≤ bk ≤ k, group-aligned
    unit = group if group % 2 == 0 else 2 * group
    bk = max(unit, min(block_k, k) // unit * unit)
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn), pl.cdiv(k, bk))
    return pl.pallas_call(
        functools.partial(_q4mm_kernel, group, grid[2], k, bk),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, ki: (i, ki)),
            pl.BlockSpec((bk // 2, bn), lambda i, j, ki: (ki, j)),
            pl.BlockSpec((bk // group, bn), lambda i, j, ki: (ki, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, ki: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x, packed, scales)


def quantized_matmul_int4(x, packed, scales, *, group=INT4_GROUP,
                          interpret=None, mode=""):
    """Dispatch like :func:`quantized_matmul`, plus int4-specific
    support checks: a ``group`` that does not cover K with the given
    scale rows degrades loudly to the XLA lowering under the group the
    shapes imply (never a wrong answer), and raises when no consistent
    group exists."""
    k = x.shape[1]
    s_rows = scales.shape[0]
    if k != 2 * packed.shape[0]:
        raise ValueError(
            f"packed int4 weight has {packed.shape[0]} rows; K={k} "
            "activations need K//2")
    if interpret is not None:
        use_kernel, interp = True, bool(interpret)
    else:
        use_kernel, interp = _kernel_plan(mode)
    if group <= 0 or group * s_rows != k:
        if s_rows == 0 or k % s_rows:
            raise ValueError(
                f"int4 scales with {s_rows} rows cannot cover K={k} "
                f"under any group (requested group={group})")
        inferred = k // s_rows
        _fallback_warn(
            f"group={group} does not cover K={k} with {s_rows} scale "
            f"rows; using inferred group={inferred}")
        group, use_kernel = inferred, False
    if use_kernel and packed.dtype != jnp.int8:
        _fallback_warn(f"packed dtype {packed.dtype} is not int8")
        use_kernel = False
    if not use_kernel:
        w = _dequant_int4(packed, scales, group)
        return (x.astype(jnp.float32) @ w).astype(x.dtype)
    return quantized_matmul_int4_pallas(
        x, packed, scales, group=group, interpret=interp)
