"""Pallas TPU weight-only int8 matmul.

Serving-side kernel (pallas guide §Quantization): weights live in HBM
as int8 with per-output-channel fp32 scales — half/quarter the bytes of
bf16/fp32, which matters because decode-time matmuls are HBM-bandwidth
bound. Each grid cell streams an int8 weight tile into VMEM, converts
in-register, runs the MXU at fp32 accumulation, and applies the column
scales on the way out.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def quantize_int8(w):
    """Per-output-channel symmetric int8 quantization of a (K, N)
    weight matrix → (w_q int8 (K, N), scales fp32 (N,))."""
    w = np.asarray(w, np.float32)
    scales = np.abs(w).max(axis=0) / 127.0
    scales = np.where(scales == 0.0, 1.0, scales).astype(np.float32)
    w_q = np.clip(np.round(w / scales[None, :]), -127, 127).astype(np.int8)
    return w_q, scales


def _qmm_kernel(x_ref, wq_ref, scale_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    w = wq_ref[:].astype(jnp.float32)
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = (acc * scale_ref[:][None, :]).astype(o_ref.dtype)


def quantized_matmul_pallas(x, w_q, scales, *, block_m=128, block_n=128,
                            interpret=False):
    """x (M, K) @ dequant(w_q (K, N)) with per-column scales (N,)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    _, n = w_q.shape
    bm = min(block_m, m)
    bn = min(block_n, n)
    if m % bm or n % bn:
        raise ValueError(f"shape ({m},{n}) not divisible by ({bm},{bn})")
    grid = (m // bm, n // bn)
    return pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((bn,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(x, w_q, scales)


def quantized_matmul(x, w_q, scales, *, interpret=None):
    """Dispatch: pallas kernel on TPU (or interpret for tests), XLA
    dequant-matmul elsewhere. M and N are padded to tile multiples and
    sliced back."""
    from sparkdl_tpu.ops._dispatch import block_for, pad_to, use_pallas

    if interpret is None:
        if not use_pallas():
            w = w_q.astype(jnp.float32) * scales[None, :]
            return (x.astype(jnp.float32) @ w).astype(x.dtype)
        interpret = False
    m, n = x.shape[0], w_q.shape[1]
    bm, bn = block_for(m), block_for(n, floor=128)
    x, pad_m = pad_to(x, bm, 0)
    w_q, pad_n = pad_to(w_q, bn, 1)
    scales, _ = pad_to(scales, bn, 0)
    out = quantized_matmul_pallas(
        x, w_q, scales, block_m=bm, block_n=bn, interpret=interpret
    )
    return out[:m, :n] if (pad_m or pad_n) else out


# Dense layers quantized by default: every 2-D projection of the
# decoder family; embeddings stay dense (a lookup reads one row).
DEFAULT_QUANT_TARGETS = ("gate_proj", "up_proj", "down_proj",
                         "q_proj", "k_proj", "v_proj",
                         "o_proj", "lm_head")


def quantize_params(params, targets=DEFAULT_QUANT_TARGETS):
    """Quantize matching kernel leaves of a flax param tree →
    (new_params with int8 'kernel_q' + 'kernel_scale', bytes saved)."""

    saved = [0]

    def walk(node, name=""):
        if isinstance(node, dict):
            if ("kernel" in node and any(t in name for t in targets)
                    and getattr(node["kernel"], "ndim", 0) == 2):
                orig = node["kernel"]
                w_q, s = quantize_int8(np.asarray(orig, np.float32))
                # savings accounted against the ORIGINAL dtype (bf16
                # kernels are 2 bytes/elt, not 4)
                saved[0] += (
                    np.asarray(orig).nbytes - w_q.nbytes - s.nbytes
                )
                out = dict(node)
                out["kernel_q"] = w_q
                out["kernel_scale"] = s
                del out["kernel"]
                return out
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(params), saved[0]


def dequantize_params(qparams, dtype=jnp.bfloat16):
    """Reconstruct an apply-compatible param tree from
    :func:`quantize_params` output: every (kernel_q, kernel_scale) pair
    becomes a dense ``kernel`` again. Use this to run a standard
    ``model.apply`` off a quantized checkpoint; serving stacks that
    call :func:`quantized_matmul` directly can keep the int8 leaves."""

    def walk(node):
        if isinstance(node, dict):
            if "kernel_q" in node:
                out = {k: v for k, v in node.items()
                       if k not in ("kernel_q", "kernel_scale")}
                out["kernel"] = (
                    jnp.asarray(node["kernel_q"], jnp.float32)
                    * jnp.asarray(node["kernel_scale"])[None, :]
                ).astype(dtype)
                return out
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)
