"""Pallas TPU weight-only int8 matmul.

Serving-side kernel (pallas guide §Quantization): weights live in HBM
as int8 with per-output-channel fp32 scales — half/quarter the bytes of
bf16/fp32, which matters because decode-time matmuls are HBM-bandwidth
bound. Each grid cell streams an int8 weight tile into VMEM, converts
in-register, runs the MXU at fp32 accumulation, and applies the column
scales on the way out.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def quantize_int8(w):
    """Per-output-channel symmetric int8 quantization of a (K, N)
    weight matrix → (w_q int8 (K, N), scales fp32 (N,))."""
    w = np.asarray(w, np.float32)
    scales = np.abs(w).max(axis=0) / 127.0
    scales = np.where(scales == 0.0, 1.0, scales).astype(np.float32)
    w_q = np.clip(np.round(w / scales[None, :]), -127, 127).astype(np.int8)
    return w_q, scales


def _qmm_kernel(x_ref, wq_ref, scale_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    w = wq_ref[:].astype(jnp.float32)
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = (acc * scale_ref[:][None, :]).astype(o_ref.dtype)


def quantized_matmul_pallas(x, w_q, scales, *, block_m=128, block_n=128,
                            interpret=False):
    """x (M, K) @ dequant(w_q (K, N)) with per-column scales (N,)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    _, n = w_q.shape
    bm = min(block_m, m)
    bn = min(block_n, n)
    if m % bm or n % bn:
        raise ValueError(f"shape ({m},{n}) not divisible by ({bm},{bn})")
    grid = (m // bm, n // bn)
    return pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((bn,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(x, w_q, scales)


def quantized_matmul(x, w_q, scales, *, interpret=None):
    """Dispatch: pallas kernel on TPU (or interpret for tests), XLA
    dequant-matmul elsewhere."""
    if interpret is None:
        try:
            on_tpu = jax.default_backend() == "tpu"
        except RuntimeError:
            on_tpu = False
        if not on_tpu:
            w = w_q.astype(jnp.float32) * scales[None, :]
            return (x.astype(jnp.float32) @ w).astype(x.dtype)
        interpret = False
    # pad M to the tile if needed (N, K are weight-static)
    m = x.shape[0]
    bm = 128 if m >= 128 else max(8, m)
    pad = (-m) % bm
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    out = quantized_matmul_pallas(
        x, w_q, scales, block_m=bm, interpret=interpret
    )
    return out[:m] if pad else out


def quantize_params(params, targets=("gate_proj", "up_proj", "down_proj",
                                     "q_proj", "k_proj", "v_proj",
                                     "o_proj", "lm_head")):
    """Quantize matching kernel leaves of a flax param tree →
    (new_params with int8 'kernel_q' + 'kernel_scale', bytes saved)."""

    saved = [0]

    def walk(node, name=""):
        if isinstance(node, dict):
            if ("kernel" in node and any(t in name for t in targets)
                    and getattr(node["kernel"], "ndim", 0) == 2):
                w = np.asarray(node["kernel"], np.float32)
                w_q, s = quantize_int8(w)
                saved[0] += w.nbytes - w_q.nbytes - s.nbytes
                out = dict(node)
                out["kernel_q"] = w_q
                out["kernel_scale"] = s
                del out["kernel"]
                return out
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(params), saved[0]
