"""Pallas TPU weight-only int8 matmul.

Serving-side kernel (pallas guide §Quantization): weights live in HBM
as int8 with per-output-channel fp32 scales — half/quarter the bytes of
bf16/fp32, which matters because decode-time matmuls are HBM-bandwidth
bound. Each grid cell streams an int8 weight tile into VMEM, converts
in-register, runs the MXU at fp32 accumulation, and applies the column
scales on the way out.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

# int4 group size (rows per scale); defined up top because
# quantize_params defaults to it
INT4_GROUP = 64


def quantize_int8(w):
    """Per-output-channel symmetric int8 quantization of a (K, N)
    weight matrix → (w_q int8 (K, N), scales fp32 (N,))."""
    w = np.asarray(w, np.float32)
    scales = np.abs(w).max(axis=0) / 127.0
    scales = np.where(scales == 0.0, 1.0, scales).astype(np.float32)
    w_q = np.clip(np.round(w / scales[None, :]), -127, 127).astype(np.int8)
    return w_q, scales


def _qmm_kernel(x_ref, wq_ref, scale_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    w = wq_ref[:].astype(jnp.float32)
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = (acc * scale_ref[:][None, :]).astype(o_ref.dtype)


def quantized_matmul_pallas(x, w_q, scales, *, block_m=128, block_n=128,
                            interpret=False):
    """x (M, K) @ dequant(w_q (K, N)) with per-column scales (N,)."""
    from jax.experimental import pallas as pl
    from sparkdl_tpu.utils.jax_compat import tpu_compiler_params

    m, k = x.shape
    _, n = w_q.shape
    bm = min(block_m, m)
    bn = min(block_n, n)
    if m % bm or n % bn:
        raise ValueError(f"shape ({m},{n}) not divisible by ({bm},{bn})")
    grid = (m // bm, n // bn)
    return pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((bn,), lambda i, j: (j,)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(x, w_q, scales)


def quantized_matmul(x, w_q, scales, *, interpret=None):
    """Dispatch: pallas kernel on TPU (or interpret for tests), XLA
    dequant-matmul elsewhere. M and N are padded to tile multiples and
    sliced back."""
    from sparkdl_tpu.ops._dispatch import block_for, pad_to, use_pallas

    if interpret is None:
        if not use_pallas():
            w = w_q.astype(jnp.float32) * scales[None, :]
            return (x.astype(jnp.float32) @ w).astype(x.dtype)
        interpret = False
    m, n = x.shape[0], w_q.shape[1]
    bm, bn = block_for(m), block_for(n, floor=128)
    x, pad_m = pad_to(x, bm, 0)
    w_q, pad_n = pad_to(w_q, bn, 1)
    scales, _ = pad_to(scales, bn, 0)
    out = quantized_matmul_pallas(
        x, w_q, scales, block_m=bm, block_n=bn, interpret=interpret
    )
    return out[:m, :n] if (pad_m or pad_n) else out


# Dense layers quantized by default: every 2-D projection of the
# decoder family; embeddings stay dense (a lookup reads one row).
DEFAULT_QUANT_TARGETS = ("gate_proj", "up_proj", "down_proj",
                         "q_proj", "k_proj", "v_proj",
                         "o_proj", "lm_head")


def quantize_params(params, targets=DEFAULT_QUANT_TARGETS, bits=8,
                    group=INT4_GROUP):
    """Quantize matching kernel leaves of a flax param tree →
    (new_params, bytes saved). ``bits=8``: per-column int8
    ('kernel_q' + 'kernel_scale'). ``bits=4``: group-wise nibble-packed
    int4 ('kernel_q4' + 'kernel_scale4')."""
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    saved = [0]

    def walk(node, name=""):
        if isinstance(node, dict):
            if ("kernel" in node and any(t in name for t in targets)
                    and getattr(node["kernel"], "ndim", 0) == 2):
                orig = node["kernel"]
                if bits == 8:
                    w_q, s = quantize_int8(np.asarray(orig, np.float32))
                    names = ("kernel_q", "kernel_scale")
                else:
                    w_q, s = quantize_int4(
                        np.asarray(orig, np.float32), group=group)
                    names = ("kernel_q4", "kernel_scale4")
                # savings accounted against the ORIGINAL dtype (bf16
                # kernels are 2 bytes/elt, not 4)
                saved[0] += (
                    np.asarray(orig).nbytes - w_q.nbytes - s.nbytes
                )
                out = dict(node)
                out[names[0]] = w_q
                out[names[1]] = s
                del out["kernel"]
                return out
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(params), saved[0]


def dequantize_params(qparams, dtype=jnp.bfloat16):
    """Reconstruct an apply-compatible param tree from
    :func:`quantize_params` output: every (kernel_q, kernel_scale) pair
    becomes a dense ``kernel`` again. Use this to run a standard
    ``model.apply`` off a quantized checkpoint; serving stacks that
    call :func:`quantized_matmul` directly can keep the int8 leaves."""

    def walk(node):
        if isinstance(node, dict):
            if "kernel_q" in node:
                out = {k: v for k, v in node.items()
                       if k not in ("kernel_q", "kernel_scale")}
                out["kernel"] = (
                    jnp.asarray(node["kernel_q"], jnp.float32)
                    * jnp.asarray(node["kernel_scale"])[None, :]
                ).astype(dtype)
                return out
            if "kernel_q4" in node:
                out = {k: v for k, v in node.items()
                       if k not in ("kernel_q4", "kernel_scale4")}
                scales = jnp.asarray(node["kernel_scale4"])
                k_full = 2 * node["kernel_q4"].shape[0]
                group = k_full // scales.shape[0]
                out["kernel"] = _dequant_int4(
                    jnp.asarray(node["kernel_q4"]), scales, group
                ).astype(dtype)
                return out
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)


# ---------------------------------------------------------------------------
# int4 weight-only: two nibbles per int8 byte along K, GROUP-wise
# scales (finer than int8's per-column — int4's 15 levels need them).
# Quarter the weight bytes of bf16; decode is HBM-bound, so bytes are
# step time.
# ---------------------------------------------------------------------------


def quantize_int4(w, group=INT4_GROUP):
    """Group-wise symmetric int4 quantization of (K, N) →
    (packed int8 (K//2, N), scales fp32 (K//group, N)). Row 2i rides
    the LOW nibble of packed row i, row 2i+1 the HIGH nibble."""
    w = np.asarray(w, np.float32)
    k, n = w.shape
    if k % max(group, 2):
        raise ValueError(f"K={k} must be divisible by group={group} (and 2)")
    g = w.reshape(k // group, group, n)
    scales = np.abs(g).max(axis=1) / 7.0              # (K//group, N)
    scales = np.where(scales == 0.0, 1.0, scales).astype(np.float32)
    w_q = np.clip(np.round(g / scales[:, None, :]), -7, 7)
    w_q = w_q.reshape(k, n).astype(np.int8)
    low = w_q[0::2].astype(np.uint8) & 0x0F
    high = (w_q[1::2].astype(np.uint8) & 0x0F) << 4
    packed = (low | high).view(np.int8)               # (K//2, N)
    return packed, scales


def unpack_int4(packed):
    """(K//2, N) packed int8 → (K, N) int8 in [-7, 7] (sign-extended
    nibbles; jnp ops only, shared by the kernel and the XLA path)."""
    p = packed.astype(jnp.int8)
    low = jnp.right_shift(jnp.left_shift(p, 4), 4)    # sign-extend low
    high = jnp.right_shift(p, 4)                      # arithmetic
    kh, n = p.shape
    return jnp.stack([low, high], axis=1).reshape(2 * kh, n)


def _dequant_int4(packed, scales, group):
    w = unpack_int4(packed).astype(jnp.float32)
    return w * jnp.repeat(scales, group, axis=0)


def _q4mm_kernel(group, x_ref, wq_ref, scale_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    w = _dequant_int4(wq_ref[:], scale_ref[:], group)
    o_ref[:] = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def quantized_matmul_int4_pallas(x, packed, scales, *, group=INT4_GROUP,
                                 block_m=128, block_n=128,
                                 interpret=False):
    """x (M, K) @ dequant(packed (K//2, N)) with (K//group, N) scales."""
    from jax.experimental import pallas as pl
    from sparkdl_tpu.utils.jax_compat import tpu_compiler_params

    m, k = x.shape
    kh, n = packed.shape
    assert k == 2 * kh, (x.shape, packed.shape)
    bm = min(block_m, m)
    bn = min(block_n, n)
    if m % bm or n % bn:
        raise ValueError(f"shape ({m},{n}) not divisible by ({bm},{bn})")
    grid = (m // bm, n // bn)
    return pl.pallas_call(
        functools.partial(_q4mm_kernel, group),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((kh, bn), lambda i, j: (0, j)),
            pl.BlockSpec((k // group, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(x, packed, scales)


def quantized_matmul_int4(x, packed, scales, *, group=INT4_GROUP,
                          interpret=None):
    """Dispatch like :func:`quantized_matmul`: pallas on TPU (or
    interpret for tests), XLA dequant-matmul elsewhere."""
    from sparkdl_tpu.ops._dispatch import block_for, pad_to, use_pallas

    if interpret is None:
        if not use_pallas():
            w = _dequant_int4(packed, scales, group)
            return (x.astype(jnp.float32) @ w).astype(x.dtype)
        interpret = False
    m, n = x.shape[0], packed.shape[1]
    bm, bn = block_for(m), block_for(n, floor=128)
    x, pad_m = pad_to(x, bm, 0)
    packed, pad_n = pad_to(packed, bn, 1)
    scales, _ = pad_to(scales, bn, 1)
    out = quantized_matmul_int4_pallas(
        x, packed, scales, group=group, block_m=bm, block_n=bn,
        interpret=interpret,
    )
    return out[:m, :n] if (pad_m or pad_n) else out
