"""TPU kernels (pallas) and kernel-dispatching ops."""

from sparkdl_tpu.ops.attention import flash_attention  # noqa: F401
