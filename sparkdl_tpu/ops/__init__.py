"""TPU kernels (pallas) and kernel-dispatching ops."""

from sparkdl_tpu.ops.attention import flash_attention  # noqa: F401
from sparkdl_tpu.ops.pallas.quantized_matmul import (  # noqa: F401
    quantize_int8,
    quantize_params,
    quantized_matmul,
)
