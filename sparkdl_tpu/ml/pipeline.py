"""Pipeline and model-selection meta-algorithms.

The reference promises its estimators work "in PySpark Pipeline and
PySpark ML meta algorithms like CrossValidator/TrainValidationSplit"
(reference ``xgboost.py:167-169``). With pyspark installed the real
classes are used (our estimators subclass the real pyspark bases); this
module provides standalone equivalents for bare TPU hosts, operating on
pandas DataFrames with the same fit/transform contract.
"""

import numpy as np

from sparkdl_tpu.ml.base import Estimator, Model, Transformer
from sparkdl_tpu.ml.param import Params


class Pipeline(Estimator):
    """Sequential stages; fit() fits estimators in order, transforming
    the running dataset through each fitted model."""

    def __init__(self, stages=None):
        super().__init__()
        self._stages = list(stages or [])

    def getStages(self):
        return list(self._stages)

    def setStages(self, stages):
        self._stages = list(stages)
        return self

    def copy(self, extra=None):
        # Propagate an extra param map into the STAGES (pyspark
        # Pipeline.copy semantics) — this is what makes grid search
        # over pipeline-stage params work.
        that = super().copy(None)
        that._stages = [
            s.copy(extra) if extra is not None and isinstance(s, Params)
            else s
            for s in self._stages
        ]
        return that

    def _fit(self, dataset):
        fitted = []
        current = dataset
        for stage in self._stages:
            if isinstance(stage, Estimator):
                model = stage.fit(current)
                fitted.append(model)
                current = model.transform(current)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                current = stage.transform(current)
            else:
                raise TypeError(
                    f"Pipeline stage must be Estimator or Transformer, "
                    f"got {type(stage).__name__}"
                )
        return PipelineModel(fitted)


class PipelineModel(Model):
    def __init__(self, stages):
        super().__init__()
        self._stages = list(stages)

    def getStages(self):
        return list(self._stages)

    def _transform(self, dataset):
        current = dataset
        for stage in self._stages:
            current = stage.transform(current)
        return current


class ParamGridBuilder:
    """Cartesian parameter grids (pyspark.ml.tuning parity)."""

    def __init__(self):
        self._grid = {}

    def addGrid(self, param, values):
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args):
        for param, value in (
            args[0].items() if args and isinstance(args[0], dict)
            else args
        ):
            self._grid[param] = [value]
        return self

    def build(self):
        import itertools

        keys = list(self._grid)
        combos = []
        for values in itertools.product(*(self._grid[k] for k in keys)):
            combos.append(dict(zip(keys, values)))
        return combos or [{}]


def _eval_columns(estimator):
    """Label/prediction column names for evaluation: taken from the
    estimator when it exposes the params (plain estimators), defaults
    otherwise (e.g. a Pipeline, which has no column params itself)."""
    label, pred = "label", "prediction"
    if isinstance(estimator, Params):
        if estimator.hasParam("labelCol"):
            label = estimator.getOrDefault(estimator.getParam("labelCol"))
        if estimator.hasParam("predictionCol"):
            pred = estimator.getOrDefault(
                estimator.getParam("predictionCol")
            )
    return label, pred


def _fit_and_score(estimator, evaluator, param_map, train, valid):
    """One (param_map, split) evaluation. Param application goes
    through Estimator.fit(dataset, params) → copy(extra), which
    propagates into Pipeline stages."""
    if valid.empty:
        raise ValueError(
            "validation split is empty; use fewer folds or more data"
        )
    model = estimator.fit(train, params=param_map)
    out = model.transform(valid)
    label, pred = _eval_columns(estimator)
    return evaluator(out, label, pred)


class CrossValidator(Estimator):
    """K-fold cross validation over a param grid.

    :param evaluator: ``f(transformed_df, labelCol, predictionCol) ->
        float`` — higher is better (pass e.g.
        :func:`accuracy_evaluator` or :func:`neg_rmse_evaluator`).
    """

    def __init__(self, estimator=None, estimatorParamMaps=None,
                 evaluator=None, numFolds=3, seed=0):
        super().__init__()
        self._estimator = estimator
        self._grid = estimatorParamMaps or [{}]
        self._evaluator = evaluator
        self._num_folds = numFolds
        self._seed = seed

    def _fit(self, dataset):
        n = len(dataset)
        if n < self._num_folds:
            raise ValueError(
                f"{self._num_folds}-fold CV needs at least that many "
                f"rows; got {n}"
            )
        rng = np.random.RandomState(self._seed)
        # permutation-based assignment: folds are balanced, never empty
        fold_of = rng.permutation(n) % self._num_folds
        avg_metrics = []
        for param_map in self._grid:
            scores = [
                _fit_and_score(
                    self._estimator, self._evaluator, param_map,
                    dataset[fold_of != fold].reset_index(drop=True),
                    dataset[fold_of == fold].reset_index(drop=True),
                )
                for fold in range(self._num_folds)
            ]
            avg_metrics.append(float(np.mean(scores)))
        best_idx = int(np.argmax(avg_metrics))
        best_model = self._estimator.fit(
            dataset, params=self._grid[best_idx]
        )
        return CrossValidatorModel(best_model, avg_metrics, best_idx)


class CrossValidatorModel(Model):
    def __init__(self, bestModel, avgMetrics, bestIndex):
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics
        self.bestIndex = bestIndex

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)


class TrainValidationSplitModel(CrossValidatorModel):
    @property
    def validationMetrics(self):
        """pyspark.ml.tuning parity alias."""
        return self.avgMetrics


class TrainValidationSplit(Estimator):
    """Single random train/validation split over a param grid."""

    def __init__(self, estimator=None, estimatorParamMaps=None,
                 evaluator=None, trainRatio=0.75, seed=0):
        super().__init__()
        self._estimator = estimator
        self._grid = estimatorParamMaps or [{}]
        self._evaluator = evaluator
        self._ratio = trainRatio
        self._seed = seed

    def _fit(self, dataset):
        n = len(dataset)
        if n < 2:
            raise ValueError("TrainValidationSplit needs at least 2 rows")
        rng = np.random.RandomState(self._seed)
        perm = rng.permutation(n)
        n_val = min(max(1, int(round(n * (1 - self._ratio)))), n - 1)
        is_val = np.zeros(n, bool)
        is_val[perm[:n_val]] = True
        train = dataset[~is_val].reset_index(drop=True)
        valid = dataset[is_val].reset_index(drop=True)
        metrics = [
            _fit_and_score(
                self._estimator, self._evaluator, pm, train, valid
            )
            for pm in self._grid
        ]
        best_idx = int(np.argmax(metrics))
        return TrainValidationSplitModel(
            self._estimator.fit(dataset, params=self._grid[best_idx]),
            metrics, best_idx,
        )


# -- evaluator shorthands (delegate to sparkdl_tpu.ml.evaluation) -----------


def accuracy_evaluator(df, label_col, prediction_col):
    from sparkdl_tpu.ml.evaluation import MulticlassClassificationEvaluator

    return MulticlassClassificationEvaluator(
        labelCol=label_col, predictionCol=prediction_col,
        metricName="accuracy",
    ).evaluate(df)


def neg_rmse_evaluator(df, label_col, prediction_col):
    from sparkdl_tpu.ml.evaluation import RegressionEvaluator

    return -RegressionEvaluator(
        labelCol=label_col, predictionCol=prediction_col,
    ).evaluate(df)
