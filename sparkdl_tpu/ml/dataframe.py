"""DataFrame adapters: the estimators accept pandas DataFrames natively
and PySpark DataFrames when pyspark is present (reference input type,
``xgboost.py:225-234``)."""

import numpy as np


def is_spark_df(dataset):
    mod = type(dataset).__module__
    return mod.startswith("pyspark.")


def to_pandas(dataset):
    if is_spark_df(dataset):
        import pandas as pd  # noqa: F401

        pdf = dataset.toPandas()
        return pdf, dataset
    return dataset, None


def extract_matrix(pdf, col):
    """Column of vectors/lists (Spark Vector cells included) or a
    scalar column → (n, f) float32 matrix. Sparse vector semantics
    follow the reference contract: inactive slots mean 0, not missing
    (reference ``xgboost.py:44-47``)."""
    if col not in pdf.columns:
        raise ValueError(
            f"Column {col!r} not found in dataset columns {list(pdf.columns)}"
        )
    series = pdf[col]
    first = series.iloc[0]
    if np.isscalar(first):
        return series.to_numpy(np.float32).reshape(-1, 1)
    if hasattr(first, "toArray"):  # pyspark.ml.linalg.Vector
        return np.stack([v.toArray() for v in series]).astype(np.float32)
    return np.stack([np.asarray(v, np.float32) for v in series])


def to_output(pdf, spark_template):
    """Return the transformed frame in the caller's dialect."""
    if spark_template is not None:
        return spark_template.sparkSession.createDataFrame(pdf)
    return pdf
