"""Estimator/Model/Transformer bases mirroring ``pyspark.ml``
(reference ``xgboost.py:31``), operating on pandas DataFrames when
pyspark is absent."""

from sparkdl_tpu.ml.param import Params


class Transformer(Params):
    def transform(self, dataset, params=None):
        if params:
            return self.copy(params)._transform(dataset)
        return self._transform(dataset)

    def _transform(self, dataset):
        raise NotImplementedError


class Estimator(Params):
    def fit(self, dataset, params=None):
        if isinstance(params, (list, tuple)):
            return [self.fit(dataset, p) for p in params]
        if params:
            return self.copy(params)._fit(dataset)
        return self._fit(dataset)

    def _fit(self, dataset):
        raise NotImplementedError


class Model(Transformer):
    pass
