"""Evaluators with pyspark.ml.evaluation-style surface, usable both as
objects (``ev.evaluate(df)``) and as the callables the tuning
meta-algorithms accept."""

import numpy as np

from sparkdl_tpu.ml.param import Params


class _Evaluator(Params):
    def __init__(self, labelCol="label", predictionCol="prediction",
                 metricName=None):
        super().__init__()
        self.labelCol = labelCol
        self.predictionCol = predictionCol
        if metricName is not None:
            self.metricName = metricName

    def evaluate(self, dataset):
        return self._metric(
            dataset[self.labelCol].to_numpy(),
            dataset[self.predictionCol].to_numpy(),
        )

    # tuning-callable form: f(df, label_col, prediction_col) -> float,
    # higher is better
    def __call__(self, dataset, label_col=None, prediction_col=None):
        y = dataset[label_col or self.labelCol].to_numpy()
        p = dataset[prediction_col or self.predictionCol].to_numpy()
        v = self._metric(y, p)
        return v if self.isLargerBetter() else -v

    def isLargerBetter(self):
        return True


class MulticlassClassificationEvaluator(_Evaluator):
    # pyspark's default metric is "f1" (support-weighted)
    metricName = "f1"

    def _metric(self, y, p):
        if self.metricName == "accuracy":
            return float((y == p).mean())
        if self.metricName == "f1":
            # support-weighted F1 over label classes (pyspark semantics)
            classes, supports = np.unique(y, return_counts=True)
            f1s = []
            for c in classes:
                tp = float(((p == c) & (y == c)).sum())
                fp = float(((p == c) & (y != c)).sum())
                fn = float(((p != c) & (y == c)).sum())
                denom = 2 * tp + fp + fn
                f1s.append(2 * tp / denom if denom else 0.0)
            return float(np.average(f1s, weights=supports))
        raise ValueError(f"unknown metricName {self.metricName!r}")


def _average_ranks(scores):
    """Ranks 1..n with ties receiving their average rank."""
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores), np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    # average ranks within tie groups
    sorted_scores = scores[order]
    i = 0
    while i < len(scores):
        j = i
        while j + 1 < len(scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + 1 + j + 1) / 2.0
        i = j + 1
    return ranks


class BinaryClassificationEvaluator(_Evaluator):
    """areaUnderROC (default) or areaUnderPR over the rawPrediction
    margin, as in pyspark."""

    metricName = "areaUnderROC"

    def __init__(self, labelCol="label", rawPredictionCol="rawPrediction",
                 metricName=None):
        super().__init__(labelCol=labelCol, predictionCol=rawPredictionCol,
                         metricName=metricName)

    def __call__(self, dataset, label_col=None, prediction_col=None):
        # This evaluator is margin-based: IGNORE the tuning harness's
        # prediction-column override (it would hand us hard 0/1 labels
        # and degenerate the ranking metric).
        y = dataset[label_col or self.labelCol].to_numpy()
        raw = dataset[self.predictionCol].to_numpy()
        return self._metric(y, raw)

    def _metric(self, y, raw):
        if self.metricName not in ("areaUnderROC", "areaUnderPR"):
            raise ValueError(f"unknown metricName {self.metricName!r}")
        # raw column holds margin vectors [neg, pos]; use pos margin
        scores = np.asarray(
            [r[1] if np.ndim(r) else r for r in raw], np.float64
        )
        pos_mask = y == 1
        n_pos, n_neg = int(pos_mask.sum()), int((~pos_mask).sum())
        if n_pos == 0 or n_neg == 0:
            return 0.5
        if self.metricName == "areaUnderROC":
            ranks = _average_ranks(scores)  # tie-averaged Mann-Whitney
            r_pos = ranks[pos_mask].sum()
            return float(
                (r_pos - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
            )
        # areaUnderPR per pyspark's BinaryClassificationMetrics: the PR
        # curve has one point per DISTINCT threshold (ties grouped),
        # prepended with (recall=0, precision of the first point), and
        # the area is the trapezoidal (linear) integral — average
        # precision would diverge from pyspark on small/tied data.
        order = np.argsort(-scores, kind="mergesort")
        y_sorted = y[order]
        s_sorted = scores[order]
        tp = np.cumsum(y_sorted == 1)
        n = len(y)
        # last index of each tied-score group = the curve's points
        boundary = np.nonzero(
            np.append(s_sorted[1:] != s_sorted[:-1], True)
        )[0]
        tp_b = tp[boundary]
        recall = tp_b / n_pos
        precision = tp_b / (boundary + 1.0)
        recall = np.concatenate([[0.0], recall])
        precision = np.concatenate([[precision[0]], precision])
        trapezoid = getattr(np, "trapezoid", None) or np.trapz  # np<2.0
        return float(trapezoid(precision, recall))


class RegressionEvaluator(_Evaluator):
    metricName = "rmse"

    def _metric(self, y, p):
        err = p.astype(np.float64) - y.astype(np.float64)
        if self.metricName == "rmse":
            return float(np.sqrt(np.mean(err ** 2)))
        if self.metricName == "mae":
            return float(np.abs(err).mean())
        if self.metricName == "r2":
            ss_res = float((err ** 2).sum())
            ss_tot = float(((y - y.mean()) ** 2).sum())
            return 1.0 - ss_res / ss_tot if ss_tot else 0.0
        raise ValueError(f"unknown metricName {self.metricName!r}")

    def isLargerBetter(self):
        return self.metricName == "r2"
