"""OneVsRest meta-classifier (the third meta-algorithm the reference
names, ``xgboost.py:167-169``): fits one binary classifier per class
and predicts by the largest positive-class margin."""

import numpy as np

from sparkdl_tpu.ml.base import Estimator, Model


class OneVsRest(Estimator):
    def __init__(self, classifier=None, labelCol="label",
                 predictionCol="prediction"):
        super().__init__()
        self._classifier = classifier
        self._label_col = labelCol
        self._prediction_col = predictionCol

    def _fit(self, dataset):
        labels = np.sort(dataset[self._label_col].unique())
        models = []
        for cls in labels:
            binarized = dataset.copy()
            binarized[self._label_col] = (
                dataset[self._label_col] == cls
            ).astype(np.float32)
            sub = self._classifier.copy()
            # propagate column config into the sub-classifier (pyspark
            # OneVsRest semantics) — without this a non-default
            # labelCol would silently train on the wrong column
            if sub.hasParam("labelCol"):
                sub._set(labelCol=self._label_col)
            if sub.hasParam("predictionCol"):
                sub._set(predictionCol=self._prediction_col)
            models.append(sub.fit(binarized))
        return OneVsRestModel(
            models, labels, self._label_col, self._prediction_col
        )


class OneVsRestModel(Model):
    def __init__(self, models, labels, label_col, prediction_col):
        super().__init__()
        self.models = models
        self.labels = labels
        self._label_col = label_col
        self._prediction_col = prediction_col

    def _transform(self, dataset):
        out = dataset.copy()
        margins = []
        for model in self.models:
            scored = model.transform(dataset)
            raw_col = model.getOrDefault(model.getParam("rawPredictionCol"))
            # positive-class margin from each binary model
            margins.append(
                np.stack(scored[raw_col].to_numpy())[:, 1]
            )
        margins = np.stack(margins, axis=1)        # (n, n_classes)
        out[self._prediction_col] = self.labels[
            margins.argmax(axis=1)
        ].astype(np.float64)
        return out
