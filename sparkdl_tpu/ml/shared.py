"""Shared column-param mixins mirroring ``pyspark.ml.param.shared``
(the traits the reference's estimators mix in, reference
``xgboost.py:32-33``)."""

from sparkdl_tpu.ml.param import Param, Params, TypeConverters


class HasFeaturesCol(Params):
    featuresCol = Param(
        Params._dummy(), "featuresCol", "features column name.",
        typeConverter=TypeConverters.toString)

    def __init__(self):
        super().__init__()
        self._setDefault(featuresCol="features")

    def getFeaturesCol(self):
        return self.getOrDefault(self.featuresCol)


class HasLabelCol(Params):
    labelCol = Param(
        Params._dummy(), "labelCol", "label column name.",
        typeConverter=TypeConverters.toString)

    def __init__(self):
        super().__init__()
        self._setDefault(labelCol="label")

    def getLabelCol(self):
        return self.getOrDefault(self.labelCol)


class HasWeightCol(Params):
    weightCol = Param(
        Params._dummy(), "weightCol",
        "weight column name. If this is not set or empty, we treat all "
        "instance weights as 1.0.",
        typeConverter=TypeConverters.toString)

    def getWeightCol(self):
        return self.getOrDefault(self.weightCol)


class HasPredictionCol(Params):
    predictionCol = Param(
        Params._dummy(), "predictionCol", "prediction column name.",
        typeConverter=TypeConverters.toString)

    def __init__(self):
        super().__init__()
        self._setDefault(predictionCol="prediction")

    def getPredictionCol(self):
        return self.getOrDefault(self.predictionCol)


class HasProbabilityCol(Params):
    probabilityCol = Param(
        Params._dummy(), "probabilityCol",
        "Column name for predicted class conditional probabilities.",
        typeConverter=TypeConverters.toString)

    def __init__(self):
        super().__init__()
        self._setDefault(probabilityCol="probability")

    def getProbabilityCol(self):
        return self.getOrDefault(self.probabilityCol)


class HasRawPredictionCol(Params):
    rawPredictionCol = Param(
        Params._dummy(), "rawPredictionCol",
        "raw prediction (a.k.a. confidence) column name.",
        typeConverter=TypeConverters.toString)

    def __init__(self):
        super().__init__()
        self._setDefault(rawPredictionCol="rawPrediction")

    def getRawPredictionCol(self):
        return self.getOrDefault(self.rawPredictionCol)


class HasValidationIndicatorCol(Params):
    validationIndicatorCol = Param(
        Params._dummy(), "validationIndicatorCol",
        "name of the column that indicates whether each row is for "
        "training or for validation. False indicates training; true "
        "indicates validation.",
        typeConverter=TypeConverters.toString)

    def getValidationIndicatorCol(self):
        return self.getOrDefault(self.validationIndicatorCol)
