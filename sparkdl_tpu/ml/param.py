"""Param system mirroring ``pyspark.ml.param`` semantics.

Implements the two-tier config contract SURVEY.md §5.6 identifies:
typed ``Param`` descriptors with doc-carried semantics (reference
``xgboost.py:38-106``), default maps vs user-set maps, and param
discovery via the ``params`` property ("entries with `Param(parent=...`",
reference ``xgboost.py:304-305``).
"""

import copy
import uuid


class Param:
    def __init__(self, parent, name, doc, typeConverter=None):
        self.parent = parent.uid if isinstance(parent, Params) else parent
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter or (lambda v: v)

    def __repr__(self):
        return f"{self.parent}__{self.name}"

    def __hash__(self):
        return hash(repr(self))

    def __eq__(self, other):
        return isinstance(other, Param) and repr(self) == repr(other)


class TypeConverters:
    @staticmethod
    def toInt(value):
        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value!r} to int")
        return int(value)

    @staticmethod
    def toFloat(value):
        return float(value)

    @staticmethod
    def toBoolean(value):
        if isinstance(value, bool):
            return value
        raise TypeError(f"Boolean Param requires value of type bool, got {value!r}")

    @staticmethod
    def toString(value):
        return str(value)

    @staticmethod
    def toList(value):
        return list(value)

    @staticmethod
    def toListFloat(value):
        return [float(v) for v in value]

    @staticmethod
    def toListInt(value):
        return [int(v) for v in value]

    @staticmethod
    def toListString(value):
        return [str(v) for v in value]

    @staticmethod
    def identity(value):
        return value


class Params:
    """Mixin holding a param map + default map, pyspark-style."""

    def __init__(self):
        self._paramMap = {}
        self._defaultParamMap = {}
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._copy_class_params()

    @staticmethod
    def _dummy():
        d = object.__new__(Params)
        d.uid = "undefined"
        return d

    def _copy_class_params(self):
        """Rebind class-level Param descriptors to this instance (so
        ``est.maxDepth.parent == est.uid``, as in pyspark)."""
        for name in dir(type(self)):
            p = getattr(type(self), name, None)
            if isinstance(p, Param):
                inst = Param(self, p.name, p.doc, p.typeConverter)
                setattr(self, name, inst)

    @property
    def params(self):
        seen = {}
        for name in dir(self):
            if name == "params":
                continue
            p = self.__dict__.get(name)
            if isinstance(p, Param):
                seen[p.name] = p
        return [seen[k] for k in sorted(seen)]

    def getParam(self, paramName):
        p = getattr(self, paramName, None)
        if isinstance(p, Param):
            return p
        raise ValueError(f"Cannot find param with name: {paramName}")

    def hasParam(self, paramName):
        p = getattr(self, paramName, None)
        return isinstance(p, Param)

    def _resolveParam(self, param):
        return self.getParam(param) if isinstance(param, str) else param

    def isSet(self, param):
        return self._resolveParam(param) in self._paramMap

    def hasDefault(self, param):
        return self._resolveParam(param) in self._defaultParamMap

    def isDefined(self, param):
        return self.isSet(param) or self.hasDefault(param)

    def get(self, param):
        return self.getOrDefault(param)

    def getOrDefault(self, param):
        param = self._resolveParam(param)
        if param in self._paramMap:
            return self._paramMap[param]
        return self._defaultParamMap[param]

    def set(self, param, value):
        param = self._resolveParam(param)
        self._paramMap[param] = param.typeConverter(value)
        return self

    def _set(self, **kwargs):
        for name, value in kwargs.items():
            self.set(self.getParam(name), value)
        return self

    def _setDefault(self, **kwargs):
        for name, value in kwargs.items():
            p = self.getParam(name)
            self._defaultParamMap[p] = (
                value if value is None else p.typeConverter(value)
            )
        return self

    def clear(self, param):
        self._paramMap.pop(self._resolveParam(param), None)
        return self

    def extractParamMap(self, extra=None):
        pm = dict(self._defaultParamMap)
        pm.update(self._paramMap)
        if extra:
            pm.update(extra)
        return pm

    def explainParam(self, param):
        param = self._resolveParam(param)
        value = "undefined"
        if self.isDefined(param):
            value = self.getOrDefault(param)
        return f"{param.name}: {param.doc} (current: {value})"

    def explainParams(self):
        return "\n".join(self.explainParam(p) for p in self.params)

    def copy(self, extra=None):
        that = copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        if extra:
            for param, value in extra.items():
                name = param.name if isinstance(param, Param) else param
                # extra maps may carry params for OTHER instances (e.g.
                # a Pipeline distributing a grid to its stages): apply
                # only the ones this instance owns.
                if that.hasParam(name):
                    that._paramMap[that.getParam(name)] = value
        return that

    def _copyValues(self, to, extra=None):
        pm = self.extractParamMap(extra)
        for p, v in pm.items():
            if to.hasParam(p.name):
                to._paramMap[to.getParam(p.name)] = v
        return to
