"""Persistence mixins mirroring ``pyspark.ml.util`` (reference
``xgboost.py:35``): ``MLWritable.write().save(path)`` /
``MLReadable.read().load(path)``, plus the ``save``/``load``
conveniences. Param values are stored as JSON; non-JSON values
(callbacks) go through cloudpickle with the reference's caveat that
they "may fail to load with different versions of dependencies"
(reference ``xgboost.py:49-56``)."""

import base64
import json
import os
import shutil

from sparkdl_tpu.ml.param import Param


class MLWriter:
    def __init__(self, instance):
        self.instance = instance
        self._overwrite = False

    def overwrite(self):
        self._overwrite = True
        return self

    def save(self, path):
        if os.path.exists(path):
            if not self._overwrite:
                raise IOError(
                    f"Path {path} already exists; call .write().overwrite()"
                    ".save(path) to overwrite."
                )
            shutil.rmtree(path)
        os.makedirs(path)
        self.instance._save_impl(path)


class MLReader:
    def __init__(self, cls):
        self.cls = cls

    def load(self, path):
        return self.cls._load_impl(path)


class MLWritable:
    def write(self):
        return MLWriter(self)

    def save(self, path):
        self.write().save(path)


class MLReadable:
    @classmethod
    def read(cls):
        return MLReader(cls)

    @classmethod
    def load(cls, path):
        return cls.read().load(path)


def params_to_json(instance):
    """Serialize an instance's user-set + default params."""
    import cloudpickle

    def enc(v):
        try:
            json.dumps(v)
            return {"json": v}
        except (TypeError, ValueError):
            return {
                "pickle": base64.b64encode(cloudpickle.dumps(v)).decode()
            }

    return {
        "uid": instance.uid,
        "set": {
            p.name: enc(v) for p, v in instance._paramMap.items()
        },
        "default": {
            p.name: enc(v) for p, v in instance._defaultParamMap.items()
        },
    }


def params_from_json(instance, payload):
    import cloudpickle

    def dec(d):
        if "json" in d:
            return d["json"]
        return cloudpickle.loads(base64.b64decode(d["pickle"]))

    for name, v in payload.get("default", {}).items():
        if instance.hasParam(name):
            instance._defaultParamMap[instance.getParam(name)] = dec(v)
    for name, v in payload.get("set", {}).items():
        if instance.hasParam(name):
            instance._paramMap[instance.getParam(name)] = dec(v)
    return instance
