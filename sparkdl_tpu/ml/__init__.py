"""Minimal PySpark-ML-compatible layer.

The reference builds its estimators on ``pyspark.ml`` base classes
(reference ``xgboost.py:31-35``). pyspark is an *optional* dependency
here (matching the reference's zero-``install_requires`` packaging,
reference ``setup.py:41``), so this package re-exports the real
pyspark.ml classes when pyspark is importable and otherwise provides
API-compatible stand-ins that operate on pandas DataFrames — giving the
same Estimator/Model/Param/persistence surface on a bare TPU VM.
"""

try:  # pragma: no cover - exercised only on pyspark-equipped clusters
    from pyspark.ml import Estimator, Model, Transformer  # noqa: F401
    from pyspark.ml.param import (  # noqa: F401
        Param,
        Params,
        TypeConverters,
    )
    from pyspark.ml.param.shared import (  # noqa: F401
        HasFeaturesCol,
        HasLabelCol,
        HasPredictionCol,
        HasProbabilityCol,
        HasRawPredictionCol,
        HasValidationIndicatorCol,
        HasWeightCol,
    )
    from pyspark.ml.util import MLReadable, MLWritable  # noqa: F401

    HAVE_PYSPARK = True
except ImportError:
    from sparkdl_tpu.ml.base import (  # noqa: F401
        Estimator,
        Model,
        Transformer,
    )
    from sparkdl_tpu.ml.param import (  # noqa: F401
        Param,
        Params,
        TypeConverters,
    )
    from sparkdl_tpu.ml.shared import (  # noqa: F401
        HasFeaturesCol,
        HasLabelCol,
        HasPredictionCol,
        HasProbabilityCol,
        HasRawPredictionCol,
        HasValidationIndicatorCol,
        HasWeightCol,
    )
    from sparkdl_tpu.ml.util import MLReadable, MLWritable  # noqa: F401

    HAVE_PYSPARK = False
