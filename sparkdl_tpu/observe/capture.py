"""Worker-side perf-forensics capture service + manual trigger CLI.

The worker half of the perf-forensics round trip
(:mod:`sparkdl_tpu.observe.forensics` is the driver half): when the
driver sends ``MSG_PROFILE_REQ`` down this rank's control socket (a
perf alert fired, or an operator POSTed ``/capturez``), the service
captures a bounded evidence window into the job dir and answers
``MSG_PROFILE_DONE`` — the same framed-watchdog request/response
pattern as the hang-diagnosis ``MSG_DUMP_REQ`` stack dumps.

One capture window produces three artifacts:

- an xprof trace of the window (``xprof-rank-<N>-<seq>/``) via
  :class:`sparkdl_tpu.utils.jax_compat.profiler_trace` — best-effort,
  absent on processes that never imported jax;
- ``profile_report-rank-<N>-<seq>.json``: UNCAPPED per-step
  attribution rows for the window (the run-dir ``perf.json`` caps its
  tail at 200 rows; forensic evidence must not), plus a device-memory
  snapshot and the trigger metadata;
- a ``profile.capture.*`` instant pair in the timeline.

The window is bounded two ways: it ends after
``SPARKDL_TPU_PROFILE_STEPS`` instrumented train steps OR after a
wall-clock cap, whichever comes first — so a hung step cannot pin a
profiler session forever. At most ONE capture runs at a time per rank
(a flapping alert cannot stack profiler sessions; the driver enforces
its own per-rank in-flight latch on top).

Event collection taps the timeline's observer slot, CHAINING to the
flight recorder already installed there — it never drains the shared
timeline (the telemetry flusher owns draining). The same tap counts
train steps continuously, which is what implements the fixed-step A/B
trigger ``SPARKDL_TPU_PROFILE_AT_STEP`` without a second thread.

Zero-overhead contract: the service only exists inside
``worker_io``'s telemetry-latched block — telemetry-off runs construct
no object, read no knob, install no observer.

CLI (the manual trigger, third trigger path)::

    python -m sparkdl_tpu.observe.capture http://driver:8080 [rank]

POSTs ``/capturez`` on the driver's statusz endpoint and prints the
JSON response.
"""

import json
import os
import threading
import time

from sparkdl_tpu import observe
from sparkdl_tpu.utils import jax_compat, knobs

CAPTURE_SCHEMA = "sparkdl_tpu.observe.capture/1"

PROFILE_STEPS_ENV = "SPARKDL_TPU_PROFILE_STEPS"
PROFILE_AT_STEP_ENV = "SPARKDL_TPU_PROFILE_AT_STEP"
DEFAULT_PROFILE_STEPS = 20
# Wall-clock cap on one capture window: a wedged step must release the
# profiler session even though the step counter never advances (the
# hang detector owns diagnosing the wedge itself).
DEFAULT_MAX_WINDOW_S = 120.0


def report_name(rank, seq):
    return f"profile_report-rank-{rank}-{seq}.json"


def trace_dir_name(rank, seq):
    return f"xprof-rank-{rank}-{seq}"


class CaptureService:
    """Per-worker forensic capture: answers the driver's PROFILE_REQ
    frames (and the fixed-step self-trigger) with bounded evidence
    windows written into ``job_dir``."""

    def __init__(self, client, rank, job_dir, *,
                 steps=None, max_window_s=DEFAULT_MAX_WINDOW_S,
                 env=None):
        self._client = client
        self._rank = int(rank)
        self._job_dir = job_dir
        self._default_steps = (
            steps if steps is not None
            else knobs.read_int(PROFILE_STEPS_ENV,
                                DEFAULT_PROFILE_STEPS, env=env))
        self._at_step = knobs.read_int(PROFILE_AT_STEP_ENV, env=env)
        self._at_fired = False
        self._max_window_s = float(max_window_s)
        self._lock = threading.Lock()
        self._capturing = False
        self._thread = None
        self._seq = 0
        self._prev_observer = None
        self._installed = False
        # Live capture window state, touched by the tap (timeline
        # recording threads) and the capture thread. ``_buf`` doubles
        # as the capturing latch the tap reads: None = no window open.
        self._buf = None
        self._buf_steps = 0
        self._want_steps = 0
        self._steps_total = 0
        self._done = threading.Event()

    # -- lifecycle ----------------------------------------------------

    def start(self):
        """Install the timeline tap (chained over the flight-recorder
        mirror) and register for the driver's PROFILE_REQ frames."""
        tl = observe.timeline()
        self._prev_observer = tl.observer
        tl.observer = self._tap
        self._installed = True
        if self._client is not None:
            self._client.set_profile_handler(self._on_request)
        return self

    def stop(self, join_timeout=5.0):
        """Unregister, restore the previous observer, and release any
        in-flight capture window (it finalizes with whatever it has).
        Call BEFORE the flight recorder is torn down so the chain
        restores cleanly."""
        if self._client is not None:
            self._client.set_profile_handler(None)
        tl = observe.timeline()
        # == not `is`: each self._tap access builds a fresh bound
        # method, so identity never matches the one install() stored
        if self._installed and tl.observer == self._tap:
            tl.observer = self._prev_observer
        self._installed = False
        self._done.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=join_timeout)

    # -- the timeline tap (runs on recording threads) -----------------

    def _tap(self, ev):
        prev = self._prev_observer
        if prev is not None:
            try:
                prev(ev)
            except Exception:
                pass  # the chained mirror must never break the tap
        is_step = (
            ev.get("ph") == "X" and ev.get("cat") == "train"
            and (ev.get("args") or {}).get("phase") != "compile")
        if is_step:
            self._steps_total += 1
            if (self._at_step is not None and not self._at_fired
                    and self._steps_total >= self._at_step):
                self._at_fired = True
                self.trigger(reason="at_step")
        if self._buf is None:  # lock-free fast path: no window open
            return
        with self._lock:
            buf = self._buf
            if buf is None:  # closed while we raced for the lock
                return
            buf.append(ev)
            if is_step:
                self._buf_steps += 1
                if self._buf_steps >= self._want_steps:
                    # Quota reached: the TAP closes the window, not
                    # the capture thread — that thread can be stuck
                    # seconds inside jax.profiler.start_trace (slow
                    # first-use init), and evidence recorded past the
                    # quota would make the report size depend on
                    # profiler startup lag.
                    self._buf = None
                    self._done.set()

    # -- triggers -----------------------------------------------------

    def _on_request(self, req):
        """PROFILE_REQ handler — runs on the client watchdog thread,
        so it only spawns; the capture itself runs on its own thread."""
        if not isinstance(req, dict):
            req = {}
        self.trigger(reason=req.get("reason") or "alert",
                     rule=req.get("rule"), steps=req.get("steps"))

    def trigger(self, reason="manual", rule=None, steps=None):
        """Start one capture window unless one is already in flight
        (single-in-flight: a flapping trigger is dropped with an
        instant, never queued). Returns True when a capture started."""
        with self._lock:
            if self._capturing:
                observe.instant(
                    "profile.capture.skipped", cat="profile",
                    rank=self._rank, reason=reason,
                    **({"rule": rule} if rule else {}))
                return False
            self._capturing = True
            seq = self._seq
            self._seq += 1
        t = threading.Thread(
            target=self._capture, args=(reason, rule, steps, seq),
            name="sparkdl-tpu-profile-capture", daemon=True)
        self._thread = t
        t.start()
        return True

    # -- the capture window (its own thread) --------------------------

    def _capture(self, reason, rule, steps, seq):
        try:
            want = int(steps) if steps else self._default_steps
            want = max(1, want)
            rank = self._rank
            trace_name = trace_dir_name(rank, seq)
            observe.instant(
                "profile.capture.start", cat="profile", rank=rank,
                reason=reason, steps=want,
                **({"rule": rule} if rule else {}))
            t0 = time.time()
            buf = []
            self._done.clear()
            # The event window opens NOW, before the profiler session:
            # start_trace can spend seconds initializing on first use,
            # and the attribution evidence must cover the steps right
            # after the trigger, not whatever ran after the profiler
            # finally came up. The tap closes the window at the step
            # quota; the xprof trace is best-effort alongside.
            with self._lock:
                self._buf_steps = 0
                self._want_steps = want
                self._buf = buf
            traced = None
            try:
                with jax_compat.profiler_trace(
                        os.path.join(self._job_dir, trace_name)) as traced:
                    self._done.wait(self._max_window_s)
            finally:
                with self._lock:  # wall-cap / teardown close
                    self._buf = None
                    steps_captured = self._buf_steps
            window_s = time.time() - t0
            events = list(buf)
            from sparkdl_tpu.observe import perf

            report = {
                "schema": CAPTURE_SCHEMA,
                "rank": rank,
                "reason": reason,
                "rule": rule,
                "ts": t0,
                "window_s": window_s,
                "requested_steps": want,
                "steps_captured": steps_captured,
                # Uncapped: every step row of the window survives
                # (perf.json's 200-row cap does not apply to forensic
                # evidence).
                "attribution": perf.attribution_report(events),
                "device_memory": jax_compat.device_memory_stats(),
                "trace_dir": trace_name if traced else None,
            }
            fname = report_name(rank, seq)
            path = os.path.join(self._job_dir, fname)
            try:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(report, f, indent=2, sort_keys=True)
                os.replace(tmp, path)
            except OSError:
                fname = None  # unwritable dir: the DONE frame still goes
            observe.instant(
                "profile.capture.done", cat="profile", rank=rank,
                reason=reason, steps=steps_captured,
                window_s=round(window_s, 3),
                **({"rule": rule} if rule else {}))
            if self._client is not None:
                self._client.send_profile_done({
                    "rank": rank,
                    "reason": reason,
                    "rule": rule,
                    "report": fname,
                    "trace_dir": report["trace_dir"],
                    "steps_captured": steps_captured,
                    "window_s": window_s,
                })
        finally:
            with self._lock:
                self._capturing = False


def maybe_start_capture_service(client, rank, env=None):
    """The latched factory ``worker_io`` calls inside its telemetry
    block: a started :class:`CaptureService` when telemetry is on and
    this worker has a job dir to write evidence into, else None — no
    object, no observer, no knob read."""
    if client is None or not observe.enabled():
        return None
    env = os.environ if env is None else env
    job_dir = env.get("SPARKDL_TPU_JOB_DIR")
    if not job_dir:
        return None
    return CaptureService(client, rank, job_dir, env=env).start()


# -- manual trigger CLI -----------------------------------------------


def main(argv=None):
    import sys
    import urllib.error
    import urllib.request

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m sparkdl_tpu.observe.capture "
              "http://driver:port [rank]", file=sys.stderr)
        return 2
    url = argv[0].rstrip("/") + "/capturez"
    if len(argv) > 1:
        url += f"?rank={int(argv[1])}"
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = resp.read().decode("utf-8", "replace")
            code = resp.status
    except urllib.error.HTTPError as e:
        body = e.read().decode("utf-8", "replace")
        code = e.code
    except OSError as e:
        print(f"capture request failed: {e}", file=sys.stderr)
        return 1
    print(body)
    return 0 if 200 <= code < 300 else 1


if __name__ == "__main__":
    raise SystemExit(main())
