"""``python -m sparkdl_tpu.observe.top URL`` — a refresh-loop
terminal view of a live gang's ``/statusz`` endpoint.

The operator-facing half of the ISSUE 14 live tier: point it at the
statusz address the launcher logged (``statusz live at
http://127.0.0.1:PORT/statusz``) and watch the gang run — per-rank
step/progress/beat-age/HBM, the rolling attribution window, alert
firings, in-flight/completed profile captures, and the fleet replica
table when one is registered. Pure
stdlib (urllib + ANSI clear), artifact-free, jax-free: it runs on a
laptop against a port-forwarded driver.

``--once`` renders a single frame and exits (scripts, tests);
``--interval`` sets the refresh period. Exit code 0 on a clean
watch, 2 when the endpoint was never reachable.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch_status(url, timeout=5.0):
    """GET the /statusz JSON. Accepts a bare host:port, a server base
    URL, or the full /statusz URL."""
    if "://" not in url:
        url = f"http://{url}"
    if not url.rstrip("/").endswith("/statusz"):
        url = url.rstrip("/") + "/statusz"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def _fmt_bytes(n):
    if not isinstance(n, (int, float)):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}{unit}")
        n /= 1024.0
    return f"{n:.1f}TiB"


def _fmt(v, spec="{}"):
    return spec.format(v) if isinstance(v, (int, float)) else "-"


def render(doc):
    """One frame of the dashboard from a /statusz document. Pure
    string building — the unit the tests pin."""
    lines = []
    gang = doc.get("gang") or {}
    sup = doc.get("supervisor") or {}
    lines.append(
        "sparkdl-tpu gang status — "
        f"{gang.get('num_workers', '?')} worker(s), "
        f"attempt(s) {int(sup.get('attempts_total') or 0)}, "
        f"restart(s) {int(sup.get('restarts_total') or 0)}, "
        f"up {doc.get('uptime_s', 0):.0f}s")
    verdict = gang.get("hang_verdict")
    if verdict:
        lines.append(f"!! HANG VERDICT: {verdict}")

    ranks = doc.get("ranks") or {}
    perf = (doc.get("perf") or {}).get("per_rank") or {}
    window_s = (doc.get("perf") or {}).get("window_s")
    if ranks:
        lines.append("")
        lines.append(f"{'rank':>4} {'state':<12} {'step':>8} "
                     f"{'beat':>7} {'med step':>10} {'mfu':>7} "
                     f"{'hbm':>10}  last collective")
        for rank_s in sorted(ranks, key=lambda r: (len(r), r)):
            info = ranks[rank_s]
            p = perf.get(rank_s) or {}
            hbm = info.get("hbm") or {}
            used = hbm.get("in_use", hbm.get(
                "peak", hbm.get("live_buffers")))
            beat = info.get("beat_age_s")
            lines.append(
                f"{rank_s:>4} {info.get('state', '?'):<12} "
                f"{_fmt(info.get('step'), '{}'):>8} "
                f"{_fmt(beat, '{:.1f}s'):>7} "
                f"{_fmt(p.get('median_step_s'), '{:.4f}s'):>10} "
                f"{_fmt(p.get('mfu'), '{:.3f}'):>7} "
                f"{_fmt_bytes(used):>10}  "
                f"{info.get('collective') or '-'}")
    if perf and window_s is not None:
        effs = [p.get("overlap_efficiency") for p in perf.values()
                if isinstance(p.get("overlap_efficiency"),
                              (int, float))]
        if effs:
            lines.append(
                f"overlap efficiency (last {window_s:.0f}s window): "
                + ", ".join(f"{e * 100:.0f}%" for e in effs))

    alerts = doc.get("alerts") or {}
    fired = alerts.get("fired") or []
    if not alerts.get("enabled"):
        lines.append("")
        lines.append("alerts: disabled (set SPARKDL_TPU_ALERTS=1)")
    elif not fired:
        lines.append("")
        lines.append(
            f"alerts: none fired ({len(alerts.get('rules') or [])} "
            "rule(s) armed)")
    else:
        from sparkdl_tpu.observe.alerts import format_alert_line

        lines.append("")
        lines.append(f"alerts: {len(fired)} fired")
        for a in fired:
            lines.append("  " + format_alert_line(a))

    captures = doc.get("captures") or {}
    inflight = captures.get("in_flight") or []
    done = captures.get("completed") or []
    if inflight or done:
        lines.append("")
        head = (f"profile captures: {len(inflight)} in flight, "
                f"{len(done)} completed")
        if captures.get("on_alert"):
            head += (f" (on-alert armed, cooldown "
                     f"{_fmt(captures.get('cooldown_s'), '{:.0f}s')})")
        lines.append(head)
        for c in inflight:
            lines.append(
                f"  rank {c.get('rank')} capturing "
                f"[{c.get('rule') or c.get('reason')}] ...")
        for c in done:
            line = (f"  rank {c.get('rank')} "
                    f"[{c.get('rule') or c.get('reason')}]: "
                    f"{_fmt(c.get('steps_captured'), '{}')} step(s)")
            if c.get("report"):
                line += f" -> {c['report']}"
            if c.get("trace_dir"):
                line += f" + {c['trace_dir']}/"
            lines.append(line)

    for fleet in doc.get("fleet") or []:
        lines.append("")
        lines.append(
            f"fleet @ {':'.join(str(p) for p in fleet.get('address', []))}"
            f" — depth {fleet.get('queue_depth')}/"
            f"{fleet.get('max_queue')}, "
            f"{fleet.get('restarts', 0)} restart(s)")
        lines.append(f"{'replica':>8} {'alive':>6} {'queued':>7} "
                     f"{'inflight':>9}  restart cause")
        for rep in fleet.get("replicas", []):
            lines.append(
                f"{rep.get('replica'):>8} "
                f"{str(bool(rep.get('alive'))).lower():>6} "
                f"{_fmt(rep.get('queued'), '{}'):>7} "
                f"{_fmt(rep.get('inflight'), '{}'):>9}  "
                f"{rep.get('restart_cause') or '-'}")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.observe.top",
        description="Terminal refresh-loop view of a live gang's "
                    "/statusz endpoint.",
    )
    parser.add_argument("url", help="statusz address (host:port, base "
                        "URL, or the full /statusz URL)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds (default 2)")
    parser.add_argument("--once", action="store_true",
                        help="render one frame and exit")
    args = parser.parse_args(argv)

    seen_one = False
    try:
        while True:
            try:
                doc = fetch_status(args.url)
            except (urllib.error.URLError, OSError, ValueError) as e:
                if args.once or not seen_one:
                    print(f"observe.top: {args.url} unreachable ({e})",
                          file=sys.stderr)
                    return 2
                # a gang that finished mid-watch is a clean exit
                print("observe.top: endpoint gone (gang finished?)")
                return 0
            seen_one = True
            frame = render(doc)
            if args.once:
                print(frame)
                return 0
            # ANSI clear + home keeps the view in place like top(1)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
