"""Gang-wide memory accounting, leak detection support, and OOM
forensics (ISSUE 18).

The platform's remaining blind axis is memory: the heartbeat carries
one HBM gauge and one alert says "HBM high", but nothing says *what*
is using it, *which* category is growing, or *why* a run died at
RESOURCE_EXHAUSTED. This module composes the existing subsystems into
a memory-observability layer:

- **Categorized accounting** — long-lived trees are registered once by
  category (``params``, ``opt_state``, ``kv_pages``, ``compile_cache``,
  ``host_prefetch``); a low-rate sampler thread
  (``sparkdl-tpu-mem-sampler``) snapshots
  :func:`~sparkdl_tpu.utils.jax_compat.device_memory_stats` /
  :func:`~sparkdl_tpu.utils.jax_compat.live_buffer_bytes` plus host RSS
  into ``mem_bytes{category=}`` / ``host_rss_bytes`` gauges, aggregates
  the largest live buffers by (shape, dtype), and computes an
  ``unattributed`` residual (live − Σ categories) that surfaces leaks
  outside any registered tree.
- **Beacon + flight recorder** — every sample is folded into a compact
  dict (:func:`beacon_sample`) that rides the heartbeat into the
  driver's ``live_state`` (statusz panel, leak alert rules) and is
  emitted as a ``mem.sample`` timeline instant, which the worker's
  flight-recorder mirror persists so an OOM-killed rank's memory tail
  survives SIGKILL.
- **OOM forensics** — :func:`oom_guard` wraps step execution and engine
  admission; an allocation failure writes ``oom_report.json`` (sample
  tail, category table at death, largest buffers, measured peak vs the
  static ``memory_analysis`` budget, actionable hints) before the
  exception propagates.

Behind the PR 3 telemetry latch end to end: without
``SPARKDL_TPU_TELEMETRY_DIR`` there is no sampler thread, no per-step
work, and no report writing — :func:`maybe_start_sampler` is a single
boolean test. Host RSS is read from ``/proc/self/status`` (fallback
``resource.getrusage``) so the accounting works on CPU-only CI; device
stats go through the ``jax_compat`` shims, which never import jax.

Env knobs (registered in ``utils/knobs.py``):

- ``SPARKDL_TPU_MEM_SAMPLE_S`` — sampler period in seconds (default 2)
- ``SPARKDL_TPU_MEM_TOP_BUFFERS`` — rows kept in the largest-live-
  buffer table (default 8)
- ``SPARKDL_TPU_MEM_SAMPLES`` — rolling sample-tail length kept for the
  beacon and the OOM report (default 64)
"""

import collections
import contextlib
import json
import os
import sys
import threading
import time

SAMPLE_S_ENV = "SPARKDL_TPU_MEM_SAMPLE_S"
DEFAULT_SAMPLE_S = 2.0
TOP_BUFFERS_ENV = "SPARKDL_TPU_MEM_TOP_BUFFERS"
DEFAULT_TOP_BUFFERS = 8
SAMPLES_ENV = "SPARKDL_TPU_MEM_SAMPLES"
DEFAULT_SAMPLES = 64

#: The category vocabulary. register_tree accepts anything, but the
#: platform's own call sites stick to these so the doctor and the docs
#: can name them.
CATEGORIES = ("params", "opt_state", "kv_pages", "compile_cache",
              "host_prefetch")

OOM_REPORT_SCHEMA = "sparkdl_tpu.observe.mem/oom_report/1"

#: Substrings that identify an allocation failure across backends: XLA
#: raises RuntimeError/XlaRuntimeError with RESOURCE_EXHAUSTED, the
#: paged KV pool raises its own dead-end RuntimeError, and pure-host
#: paths raise MemoryError.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource_exhausted",
                "Out of memory", "out of memory", "OOM",
                "paged pool exhausted")

_lock = threading.Lock()
_trees = {}            # category -> int | callable() -> int
_samples = None        # deque of sample dicts (created on first use)
_latest = None         # last sample dict
_budgets = {}          # fn name -> memory_analysis dict (static budget)
_host_rss_high = 0     # high-water of sampled VmRSS
_sampler = None
_sampler_stop = None


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# -- host RSS ----------------------------------------------------------------


def host_rss_bytes():
    """Current resident set size of this process in bytes, or None
    when unreadable. ``/proc/self/status`` first (current RSS, Linux);
    ``getrusage`` high-water as the portable fallback."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        # ru_maxrss is KB on Linux: high-water, not current — still the
        # right order of magnitude for accounting without /proc.
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def host_rss_high_water_bytes():
    """High-water host RSS in bytes: the max of every sampled VmRSS and
    the kernel's own ``ru_maxrss`` accounting (which needs no sampler
    thread — benches call this once at the end of a run)."""
    high = _host_rss_high
    try:
        import resource

        high = max(high,
                   resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                   * 1024)
    except Exception:
        pass
    return high or None


def device_peak_bytes():
    """Peak device-memory use in bytes from the runtime's allocator
    stats, falling back to currently-live buffer bytes; None when the
    backend exposes neither (CPU)."""
    from sparkdl_tpu.utils import jax_compat

    stats = jax_compat.device_memory_stats()
    if stats and stats.get("peak_bytes_in_use") is not None:
        return int(stats["peak_bytes_in_use"])
    return jax_compat.live_buffer_bytes()


# -- categorized accounting --------------------------------------------------


def tree_nbytes(tree):
    """Σ leaf nbytes over a pytree without importing jax: uses
    ``jax.tree_util`` only when jax is already in the process, else
    duck-types nbytes on the object itself."""
    jax = sys.modules.get("jax")
    leaves = None
    if jax is not None:
        try:
            leaves = jax.tree_util.tree_leaves(tree)
        except Exception:
            leaves = None
    if leaves is None:
        leaves = [tree]
    total = 0
    for leaf in leaves:
        n = getattr(leaf, "nbytes", None)
        if isinstance(n, (int, float)):
            total += int(n)
    return total


def register_tree(category, tree):
    """Register a long-lived tree (params, opt state, ...) under a
    category. ``tree`` may be a pytree of arrays (sized once, now), an
    int byte count, or a zero-arg callable re-evaluated at every sample
    (for pools whose size moves, e.g. ``kv_pages``). Re-registering a
    category replaces it. Returns the current byte count (0 for
    callables until sampled). No-op (returns None) with telemetry
    off."""
    from sparkdl_tpu import observe

    if not observe.enabled():
        return None
    if callable(tree):
        sized = tree
        now = 0
    elif isinstance(tree, (int, float)):
        sized = int(tree)
        now = sized
    else:
        sized = tree_nbytes(tree)
        now = sized
    with _lock:
        _trees[str(category)] = sized
    return now


def set_category_bytes(category, nbytes):
    """Point update for a category whose size the owner tracks itself
    (the serving KV pool). No-op with telemetry off."""
    register_tree(category, int(nbytes))


def clear_category(category):
    with _lock:
        _trees.pop(str(category), None)


def category_bytes():
    """The category table right now: {category: bytes}. Callables are
    evaluated; a failing callable reports 0 rather than raising."""
    with _lock:
        items = list(_trees.items())
    table = {}
    for cat, sized in items:
        if callable(sized):
            try:
                table[cat] = int(sized() or 0)
            except Exception:
                table[cat] = 0
        else:
            table[cat] = int(sized)
    return table


def note_budget(name, analysis):
    """Record a compiled executable's static ``memory_analysis`` dict
    as the budget the OOM report sets measured peak against. Called by
    ``perf.register_step_cost`` (already behind the latch)."""
    if not analysis:
        return
    with _lock:
        _budgets[str(name)] = dict(analysis)


def static_budget_bytes():
    """Σ static peak over registered executables: arguments + outputs +
    temps (aliased pairs counted once is the shim's business); None
    when nothing was registered."""
    with _lock:
        budgets = list(_budgets.values())
    if not budgets:
        return None
    total = 0
    for b in budgets:
        for key in ("argument_size_in_bytes", "output_size_in_bytes",
                    "temp_size_in_bytes"):
            v = b.get(key)
            if v:
                total += int(v)
        alias = b.get("alias_size_in_bytes")
        if alias:
            total -= int(alias)
    return max(0, total)


def largest_buffers(top_n=None):
    """The largest live device buffers aggregated by (shape, dtype):
    ``[{"shape", "dtype", "count", "bytes"}, ...]`` sorted by bytes
    descending. Empty when jax is absent or exposes no live-array
    API — never raises, never imports jax."""
    if top_n is None:
        top_n = _env_int(TOP_BUFFERS_ENV, DEFAULT_TOP_BUFFERS)
    jax = sys.modules.get("jax")
    if jax is None or not hasattr(jax, "live_arrays"):
        return []
    agg = {}
    try:
        for arr in jax.live_arrays():
            n = getattr(arr, "nbytes", None)
            if not isinstance(n, (int, float)):
                continue
            key = (str(getattr(arr, "shape", "?")),
                   str(getattr(arr, "dtype", "?")))
            cnt, tot = agg.get(key, (0, 0))
            agg[key] = (cnt + 1, tot + int(n))
    except Exception:
        return []
    rows = [{"shape": shape, "dtype": dtype, "count": cnt, "bytes": tot}
            for (shape, dtype), (cnt, tot) in agg.items()]
    rows.sort(key=lambda r: r["bytes"], reverse=True)
    return rows[:top_n]


# -- sampling ----------------------------------------------------------------


def _samples_deque():
    global _samples
    if _samples is None:
        _samples = collections.deque(
            maxlen=max(4, _env_int(SAMPLES_ENV, DEFAULT_SAMPLES)))
    return _samples


def sample_now():
    """Take one sample: set the gauges, append to the rolling tail,
    emit the ``mem.sample`` instant (which the flight-recorder mirror
    persists), and return the sample dict. No-op (returns None) with
    telemetry off. This is what the sampler thread calls each tick;
    benches may call it synchronously."""
    global _latest, _host_rss_high
    from sparkdl_tpu import observe
    from sparkdl_tpu.utils import jax_compat

    if not observe.enabled():
        return None
    rss = host_rss_bytes()
    stats = jax_compat.device_memory_stats() or {}
    live = jax_compat.live_buffer_bytes()
    cats = category_bytes()
    attributed = sum(cats.values())
    unattributed = None
    if live is not None:
        unattributed = max(0, int(live) - attributed)
    sample = {
        "ts": time.time(),
        "rss": rss,
        "hbm": (int(stats["bytes_in_use"])
                if stats.get("bytes_in_use") is not None else live),
        "peak": (int(stats["peak_bytes_in_use"])
                 if stats.get("peak_bytes_in_use") is not None else None),
        "limit": (int(stats["bytes_limit"])
                  if stats.get("bytes_limit") is not None else None),
        "live": live,
        "categories": cats,
        "unattributed": unattributed,
    }
    with _lock:
        if rss:
            _host_rss_high = max(_host_rss_high, rss)
        _latest = sample
    _samples_deque().append(sample)
    if rss is not None:
        observe.set_gauge("host_rss_bytes", rss)
    for cat, nbytes in cats.items():
        observe.set_gauge("mem_bytes", nbytes, category=cat)
    if unattributed is not None:
        observe.set_gauge("mem_bytes", unattributed,
                          category="unattributed")
    observe.instant(
        "mem.sample", cat="mem", rss=rss, hbm=sample["hbm"],
        unattributed=unattributed)
    return sample


def beacon_sample():
    """The compact dict that rides the heartbeat: the latest sample
    minus the timestamp bulk. ``{}`` when no sample was taken yet (or
    telemetry is off) — the heartbeat payload stays small and the
    driver treats a missing field as 'no data'."""
    with _lock:
        sample = _latest
    if not sample:
        return {}
    out = {"rss": sample["rss"], "hbm": sample["hbm"],
           "unattributed": sample["unattributed"],
           "categories": sample["categories"]}
    return {k: v for k, v in out.items() if v is not None}


def sample_tail(n=16):
    return list(_samples_deque())[-n:]


def maybe_start_sampler(interval=None):
    """Start the low-rate sampler thread — behind the latch: without
    ``SPARKDL_TPU_TELEMETRY_DIR`` this returns None and NO thread
    exists (the zero-overhead contract, pinned by the thread-name-scan
    test). Idempotent. An interval <= 0 disables the thread (benches
    can still call :func:`sample_now` synchronously)."""
    global _sampler, _sampler_stop
    from sparkdl_tpu import observe

    if not observe.enabled():
        return None
    if _sampler is not None and _sampler.is_alive():
        return _sampler
    if interval is None:
        interval = _env_float(SAMPLE_S_ENV, DEFAULT_SAMPLE_S)
    if interval <= 0:
        return None
    _sampler_stop = stop = threading.Event()

    def loop():
        while not stop.wait(interval):
            try:
                sample_now()
            except Exception:
                # accounting must never take down the worker
                pass

    _sampler = threading.Thread(
        target=loop, name="sparkdl-tpu-mem-sampler", daemon=True)
    _sampler.start()
    # One synchronous sample so the first heartbeat after start already
    # carries a mem field instead of waiting a full period.
    try:
        sample_now()
    except Exception:
        pass
    return _sampler


def stop_sampler():
    global _sampler, _sampler_stop
    if _sampler_stop is not None:
        _sampler_stop.set()
    if _sampler is not None:
        _sampler.join(timeout=5.0)
    _sampler = None
    _sampler_stop = None


# -- OOM forensics -----------------------------------------------------------


def is_oom(exc):
    """True when ``exc`` looks like an allocation failure: MemoryError,
    or any exception whose text carries a known OOM marker
    (RESOURCE_EXHAUSTED from XLA, the paged-pool dead-end, ...)."""
    if isinstance(exc, MemoryError):
        return True
    text = f"{type(exc).__name__}: {exc}"
    return any(marker in text for marker in _OOM_MARKERS)


def _hints(phase, sample, budget):
    hints = []
    if phase == "admission":
        hints.append(
            "KV pool exhausted: raise PagedKVConfig.n_pages (or lower "
            "max_new_tokens / concurrent sequences); weight-only quant "
            "(SPARKDL_TPU_SERVE_QUANT=int8) frees HBM for more pages.")
    else:
        hints.append(
            "Undonated step buffers double params+opt_state at the "
            "peak: run `python -m sparkdl_tpu.analysis` donation "
            "checks and apply the fixer's donate_argnums patch.")
        hints.append(
            "Restore-time high-water: SPARKDL_TPU_RESHARD_GROUPED=1 "
            "bounds resharding to one parameter group at a time.")
    if budget is not None and sample and sample.get("peak") is not None \
            and sample["peak"] > budget:
        hints.append(
            f"Measured peak {sample['peak']} B exceeds the static "
            f"memory_analysis budget {budget} B — runtime allocations "
            "(collectives scratch, prefetch) are on top of the compiled "
            "program; leave headroom or shrink the step.")
    unattributed = (sample or {}).get("unattributed")
    attributed = sum(((sample or {}).get("categories") or {}).values())
    if unattributed and unattributed > max(attributed, 1):
        hints.append(
            "Most live bytes are unattributed (outside every registered "
            "tree) — a leak candidate; diff consecutive mem.sample "
            "instants / the largest-buffer table to find the grower.")
    return hints


def _report_dir(run_dir=None):
    from sparkdl_tpu import observe

    if run_dir:
        return run_dir
    return (os.environ.get("SPARKDL_TPU_JOB_DIR")
            or observe.telemetry_dir())


def oom_report_path(out_dir, rank=None):
    """``oom_report.json`` in ``out_dir``, rank-suffixed when two ranks
    share the dir and the plain name is taken."""
    base = os.path.join(out_dir, "oom_report.json")
    if rank is None or not os.path.exists(base):
        return base
    return os.path.join(out_dir, f"oom_report-rank-{rank}.json")


def write_oom_report(phase, error, run_dir=None, extra=None):
    """Write ``oom_report.json``: the forensic record of an allocation
    failure. Returns the path, or None when telemetry is off or no
    writable dir exists. Never raises — this runs inside an exception
    handler that must re-raise the real error."""
    from sparkdl_tpu import observe

    if not observe.enabled():
        return None
    out_dir = _report_dir(run_dir)
    if not out_dir:
        return None
    try:
        # a final sample so the table reflects the moment of death
        sample = sample_now() or (_latest or {})
    except Exception:
        sample = _latest or {}
    rank = os.environ.get("SPARKDL_TPU_RANK")
    budget = static_budget_bytes()
    report = {
        "schema": OOM_REPORT_SCHEMA,
        "ts": time.time(),
        "phase": phase,
        "rank": int(rank) if rank is not None else None,
        "error": str(error)[:4000],
        "host_rss_bytes": (sample or {}).get("rss"),
        "host_rss_high_water_bytes": host_rss_high_water_bytes(),
        "device": {k: (sample or {}).get(k)
                   for k in ("hbm", "peak", "limit", "live")},
        "categories": (sample or {}).get("categories") or category_bytes(),
        "unattributed": (sample or {}).get("unattributed"),
        "largest_buffers": largest_buffers(),
        "static_budget_bytes": budget,
        "sample_tail": sample_tail(),
        "hints": _hints(phase, sample, budget),
    }
    if extra:
        report["extra"] = extra
    try:
        os.makedirs(out_dir, exist_ok=True)
        path = oom_report_path(out_dir, rank=rank)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        return None
    try:
        observe.instant("mem.oom", cat="mem", phase=phase,
                        error=str(error)[:200])
        observe.inc("oom_reports_total", phase=phase)
        observe.flush()    # the process is probably about to die
    except Exception:
        pass
    return path


@contextlib.contextmanager
def oom_guard(phase="step", run_dir=None, extra=None):
    """Wrap an allocation-prone block (step execution, engine
    admission): an exception that looks like an allocation failure
    writes ``oom_report.json`` before propagating; every other
    exception passes through untouched. Zero work on the happy path
    and with telemetry off."""
    try:
        yield
    except BaseException as e:
        from sparkdl_tpu import observe

        if observe.enabled() and is_oom(e):
            write_oom_report(phase, e, run_dir=run_dir, extra=extra)
        raise


def _reset_for_tests():
    global _trees, _samples, _latest, _budgets, _host_rss_high
    stop_sampler()
    with _lock:
        _trees = {}
        _budgets = {}
        _latest = None
        _host_rss_high = 0
    _samples = None


__all__ = [
    "CATEGORIES", "OOM_REPORT_SCHEMA", "tree_nbytes",
    "register_tree", "set_category_bytes", "clear_category",
    "category_bytes", "largest_buffers",
    "note_budget", "static_budget_bytes",
    "host_rss_bytes", "host_rss_high_water_bytes", "device_peak_bytes",
    "sample_now", "beacon_sample", "sample_tail",
    "maybe_start_sampler", "stop_sampler",
    "is_oom", "oom_guard", "write_oom_report", "oom_report_path",
]
