"""Gang health: worker liveness beacons and driver-side hang detection.

The single worst failure mode of a real TPU gang is the *silent hang*:
one rank stuck in a collective the others already entered, a stalled
host callback, a wedged data loader. No rank dies, no EXC frame is
sent, the supervisor's transient/permanent classifier never fires —
the driver just waits. This module makes a hung gang diagnose itself:

- **Worker side**: a :class:`HeartbeatSender` thread ships a liveness
  beacon over the control plane every ``SPARKDL_TPU_HEARTBEAT_S``
  (default 10s): the rank's current step, a monotonic *progress*
  counter (bumped by :func:`note_step` from ``instrument_step`` and by
  :func:`note_collective` on every collective entry/exit), the
  last-entered collective op, and device memory gauges
  (:func:`sparkdl_tpu.utils.jax_compat.device_memory_stats`, exported
  as ``device_hbm_bytes{kind=...}`` plus a ``worker_step`` gauge in
  the process registry).
- **Driver side**: a :class:`HangDetector` tracks per-rank last-beat
  and last-progress. A rank whose beats continue but whose progress
  counter hasn't moved for ``SPARKDL_TPU_STALL_S`` (default 300s) is
  declared *stalled*; a rank whose beats stop while its process lives
  is *silent*; when every rank is stalled-or-silent the gang is
  declared *hung* with a ``straggler`` (ranks at different steps —
  one laggard dragged the rest into a collective wait) or
  ``deadlock`` (everyone wedged at the same point) verdict. Verdicts
  land as ``health.*`` timeline instants and
  ``gang_stalls_total{verdict=...}`` counters; the launcher then
  requests stack dumps from the stalled ranks and fails the gang with
  ``kind="hang"`` so the supervisor relaunches it under the HANG
  cause (docs/fault_tolerance.rst).

Zero-overhead contract: everything here is inert unless telemetry is
opted in (``SPARKDL_TPU_TELEMETRY_DIR``). ``note_step`` /
``note_collective`` are only reached behind the callers' cached
``observe.enabled()`` check, the sender thread is only started by the
worker bootstrap when telemetry is on, and the detector is only
constructed by the launcher alongside :class:`GangTelemetry`.

False-positive guard: a rank is only eligible for a *stall* verdict
once it has reported progress at least once — an uninstrumented main
(no ``instrument_step``, no ``hvd`` collectives) never moves the
counter and must never be killed as "hung". Size ``STALL_S`` above
your worst-case XLA compile: progress bumps at step *entry*, so a
long first-step compile only pins the counter for one compile, but a
stall window shorter than that compile would still misfire.
"""

import os
import threading
import time

HEARTBEAT_S_ENV = "SPARKDL_TPU_HEARTBEAT_S"
STALL_S_ENV = "SPARKDL_TPU_STALL_S"
DEFAULT_HEARTBEAT_S = 10.0
DEFAULT_STALL_S = 300.0

# Gang-level hang verdicts (the doctor reproduces these from artifacts
# alone, so the strings are contract).
VERDICT_STALL = "stall"
VERDICT_SILENT = "silent"
VERDICT_STRAGGLER = "straggler"
VERDICT_DEADLOCK = "deadlock"


def heartbeat_interval():
    return float(os.environ.get(HEARTBEAT_S_ENV, DEFAULT_HEARTBEAT_S))


def stall_seconds():
    return float(os.environ.get(STALL_S_ENV, DEFAULT_STALL_S))


# -- worker-side progress state ---------------------------------------------
#
# One tiny shared struct per process; writers are the training thread
# (note_step / note_collective, behind the callers' enabled() latch)
# and the reader is the heartbeat thread. A plain lock is fine — these
# fire at step/collective rate, not per-element.

_state_lock = threading.Lock()
_state = {"step": None, "progress": 0, "collective": None}


def note_step(step):
    """Training-loop progress marker (``instrument_step`` calls this
    at step entry). Bumps the monotonic progress counter."""
    with _state_lock:
        _state["step"] = int(step)
        _state["progress"] += 1


def note_collective(op, done=False):
    """Collective entry/exit marker (the ``hvd`` engine calls this
    around every public op). Entering an op IS progress — a rank
    wedged inside its first allreduce must still be stall-eligible —
    and the entry records the op name the postmortem will show as
    "last entered <op>"."""
    with _state_lock:
        if not done:
            _state["collective"] = str(op)
        _state["progress"] += 1


def progress_snapshot():
    with _state_lock:
        return dict(_state)


def export_device_memory(registry):
    """Set ``device_hbm_bytes{kind=...}`` gauges on ``registry`` from
    the jax_compat shims and return the raw dict (``{}`` when nothing
    is readable — CPU rigs without memory_stats report live-buffer
    bytes instead)."""
    from sparkdl_tpu.utils import jax_compat

    out = {}
    stats = jax_compat.device_memory_stats()
    if stats:
        kinds = {"bytes_in_use": "in_use", "peak_bytes_in_use": "peak",
                 "bytes_limit": "limit"}
        for key, kind in kinds.items():
            if key in stats:
                out[kind] = stats[key]
    else:
        live = jax_compat.live_buffer_bytes()
        if live is not None:
            out["live_buffers"] = live
    for kind, value in out.items():
        registry.gauge("device_hbm_bytes", kind=kind).set(value)
    return out


def heartbeat_payload(rank):
    """One liveness beacon: progress state + device memory, with the
    ``worker_step`` / ``device_hbm_bytes`` gauges refreshed in the
    process registry so the next telemetry flush exports them."""
    from sparkdl_tpu import observe

    from sparkdl_tpu.observe import mem as _mem

    snap = progress_snapshot()
    registry = observe.metrics()
    if snap["step"] is not None:
        registry.gauge("worker_step").set(snap["step"])
    registry.gauge("worker_progress").set(snap["progress"])
    hbm = export_device_memory(registry)
    return {
        "rank": int(rank),
        "step": snap["step"],
        "progress": snap["progress"],
        "collective": snap["collective"],
        "hbm": hbm,
        # categorized accounting (ISSUE 18): the latest mem sample
        # rides the guaranteed beacon so the driver's live_state /
        # statusz / leak rules see per-category bytes without any
        # extra transport. {} until the sampler takes its first sample.
        "mem": _mem.beacon_sample(),
        "ts": time.time(),
    }


class HeartbeatSender:
    """Worker-side beacon thread: ships :func:`heartbeat_payload` over
    the control plane every ``interval`` seconds (first beat
    immediately, so the driver learns this rank's baseline before the
    first stall window can elapse). The chaos harness can mute it
    (``SPARKDL_TPU_CHAOS_MUTE_HEARTBEAT`` — beats stop, process
    alive) to exercise the detector's *silent* verdict."""

    def __init__(self, client, rank, interval=None):
        self._client = client
        self._rank = int(rank)
        self._interval = (heartbeat_interval() if interval is None
                          else float(interval))
        self._stop = threading.Event()
        self._thread = None

    def beat(self):
        from sparkdl_tpu.utils.chaos import heartbeat_muted

        if heartbeat_muted(self._rank):
            return False
        try:
            self._client.send_heartbeat(heartbeat_payload(self._rank))
        except Exception:
            # A beat must never take down the worker; the control-plane
            # client already swallows socket errors, this guards the
            # payload assembly (e.g. an exotic device backend).
            return False
        return True

    def start(self):
        if self._interval <= 0:
            return None
        if self._thread is not None and self._thread.is_alive():
            return self._thread

        def loop():
            self.beat()
            while not self._stop.wait(self._interval):
                self.beat()

        self._thread = threading.Thread(
            target=loop, name="sparkdl-tpu-heartbeat", daemon=True
        )
        self._thread.start()
        return self._thread

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._thread = None


# -- driver-side detection ---------------------------------------------------


class HangDetector:
    """Tracks per-rank liveness from HEARTBEAT frames and declares
    stall / silent / hang verdicts.

    ``observe_beat`` is called from control-plane connection threads;
    ``poll`` from the launcher's monitor loop (throttled internally to
    ``check_every``). Verdict instants and counters are emitted HERE
    (the detector only exists when telemetry is on), while the caller
    acts on the returned report: request stack dumps for newly stalled
    ranks, fail the gang on a hang verdict.
    """

    def __init__(self, num_workers, stall_s=None, clock=time.monotonic,
                 check_every=1.0):
        self.num_workers = int(num_workers)
        self.stall_s = stall_seconds() if stall_s is None else float(stall_s)
        self._clock = clock
        self._check_every = float(check_every)
        self._lock = threading.Lock()
        self._ranks = {}       # rank -> beat/progress bookkeeping
        self._stalled = set()  # ranks with an emitted stall verdict
        self._silent = set()
        self._hang_verdict = None
        self._next_check = 0.0
        self._t0 = None        # first poll (gang considered running)

    def observe_beat(self, rank, payload):
        from sparkdl_tpu import observe

        now = self._clock()
        rank = int(rank)
        progress = payload.get("progress")
        recovered = False
        with self._lock:
            info = self._ranks.get(rank)
            if info is None:
                info = self._ranks[rank] = {
                    "progress": None, "progress_t": now,
                    "ever_progressed": False,
                }
            info["last_beat"] = now
            info["step"] = payload.get("step")
            info["collective"] = payload.get("collective")
            info["hbm"] = payload.get("hbm") or {}
            info["mem"] = payload.get("mem") or {}
            if isinstance(progress, (int, float)):
                if info["progress"] is None or progress > info["progress"]:
                    if info["progress"] is not None and rank in self._stalled:
                        # Progress resumed after a stall verdict (the
                        # window was undersized, or the wedge cleared):
                        # revoke it, or one long-ago transient stall
                        # would let a later hang verdict condemn a
                        # rank that is demonstrably training.
                        self._stalled.discard(rank)
                        recovered = True
                    info["progress"] = progress
                    info["progress_t"] = now
                if progress > 0:
                    info["ever_progressed"] = True
            if rank in self._silent:
                # Beats resumed (e.g. a transient network blip): the
                # rank is observable again.
                self._silent.discard(rank)
        if recovered:
            observe.instant("health.recovered", cat="health", rank=rank,
                            progress=progress)

    # -- verdict machinery ---------------------------------------------------

    def _classify_locked(self, now):
        """(newly_stalled, newly_silent, hang_verdict_or_None)."""
        new_stalled, new_silent = [], []
        # Judge every EXPECTED rank, not just the observed ones: a
        # rank whose beacon never arrived at all (muted from boot, a
        # dead heartbeat thread, dropped frames) must become *silent*
        # once the gang has been running a full window — otherwise it
        # would both escape its own verdict and veto the gang's.
        expected = set(range(self.num_workers)) | set(self._ranks)
        for rank in expected:
            info = self._ranks.get(rank)
            if info is None:
                if (self._t0 is not None
                        and now - self._t0 > self.stall_s
                        and rank not in self._silent):
                    new_silent.append(rank)
                continue
            beat_age = now - info["last_beat"]
            if beat_age > self.stall_s:
                if rank not in self._silent:
                    new_silent.append(rank)
                continue
            # Beats continue: stall = no progress movement for the
            # whole window, on a rank that has proven it CAN progress
            # (uninstrumented mains never become stall-eligible).
            if (info["ever_progressed"]
                    and now - info["progress_t"] > self.stall_s
                    and rank not in self._stalled):
                new_stalled.append(rank)
        # Gang hang: every expected rank is beating-but-stalled or
        # silent (and at least one is genuinely stalled — an all-silent
        # gang is a dead control plane, not a hang).
        hang = None
        if self._hang_verdict is None and expected:
            stalled_after = self._stalled | set(new_stalled)
            silent_after = self._silent | set(new_silent)
            covered = stalled_after | silent_after
            if stalled_after and all(r in covered for r in expected):
                steps = {
                    self._ranks[r].get("step") for r in stalled_after
                }
                hang = (VERDICT_DEADLOCK if len(steps) <= 1
                        else VERDICT_STRAGGLER)
        return new_stalled, new_silent, hang

    def poll(self):
        """Run one detection pass. Returns ``{"new_stalled": [...],
        "new_silent": [...], "hang": verdict-or-None}`` — empty/None
        between check intervals and after the hang has been declared
        (one hang per gang attempt)."""
        from sparkdl_tpu import observe

        now = self._clock()
        report = {"new_stalled": [], "new_silent": [], "hang": None}
        if now < self._next_check:
            return report
        self._next_check = now + self._check_every
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            new_stalled, new_silent, hang = self._classify_locked(now)
            self._stalled.update(new_stalled)
            self._silent.update(new_silent)
            if hang is not None:
                self._hang_verdict = hang
            stalled_info = {
                r: dict(self._ranks[r]) for r in new_stalled
            }
        for rank in sorted(new_stalled):
            info = stalled_info[rank]
            observe.instant(
                "health.stall", cat="health", rank=rank,
                verdict=VERDICT_STALL, step=info.get("step"),
                progress=info.get("progress"),
                collective=info.get("collective"),
                stalled_for_s=round(now - info["progress_t"], 1),
            )
            observe.inc("gang_stalls_total", verdict=VERDICT_STALL)
        for rank in sorted(new_silent):
            observe.instant(
                "health.silent", cat="health", rank=rank,
                verdict=VERDICT_SILENT,
            )
            observe.inc("gang_stalls_total", verdict=VERDICT_SILENT)
        if hang is not None:
            observe.instant(
                "health.hang", cat="health", verdict=hang,
                stalled=sorted(self._stalled),
                silent=sorted(self._silent),
            )
            observe.inc("gang_stalls_total", verdict=hang)
        report["new_stalled"] = sorted(new_stalled)
        report["new_silent"] = sorted(new_silent)
        report["hang"] = hang
        return report

    def note_stack_dump(self, rank):
        """A requested stack dump arrived (called by the control
        plane): mark the moment on the timeline so the postmortem can
        order detection → dump → relaunch."""
        from sparkdl_tpu import observe

        observe.instant("health.stack_dump", cat="health", rank=int(rank))

    @property
    def stalled_ranks(self):
        with self._lock:
            return sorted(self._stalled)

    @property
    def hang_verdict(self):
        with self._lock:
            return self._hang_verdict

    def describe(self):
        """One human line per rank — the evidence block of a
        ``kind="hang"`` GangFailure message (and of the doctor's
        report, which re-reads it from ``health.json``)."""
        with self._lock:
            lines = []
            for rank in sorted(self._ranks):
                info = self._ranks[rank]
                state = ("stalled" if rank in self._stalled
                         else "silent" if rank in self._silent
                         else "progressing")
                coll = info.get("collective")
                lines.append(
                    f"rank {rank}: {state} @ step {info.get('step')}"
                    + (f", last entered {coll}" if coll else "")
                    + f", progress counter {info.get('progress')}"
                )
            return "\n".join(lines)

    def live_state(self):
        """Per-rank liveness as it stands NOW — the ``/statusz``
        ``ranks`` table and the alert engine's heartbeat-gap input.
        One dict per EXPECTED rank (a rank that never beat shows up as
        ``state="unseen"``, beat_age None), with the detector's own
        stall/silent classification and the age of the last beat on
        this detector's clock."""
        now = self._clock()
        out = {}
        with self._lock:
            for rank in sorted(set(range(self.num_workers))
                               | set(self._ranks)):
                info = self._ranks.get(rank)
                if info is None:
                    out[rank] = {
                        "state": ("silent" if rank in self._silent
                                  else "unseen"),
                        "step": None, "progress": None,
                        "collective": None, "hbm": {}, "mem": {},
                        "beat_age_s": None,
                    }
                    continue
                state = ("stalled" if rank in self._stalled
                         else "silent" if rank in self._silent
                         else "progressing")
                out[rank] = {
                    "state": state,
                    "step": info.get("step"),
                    "progress": info.get("progress"),
                    "collective": info.get("collective"),
                    "hbm": dict(info.get("hbm") or {}),
                    "mem": dict(info.get("mem") or {}),
                    "beat_age_s": round(now - info["last_beat"], 3),
                }
        return out

    def summary(self):
        """JSON-able detector state for ``health.json`` in the merged
        run dir (what ``observe.doctor`` diagnoses from)."""
        with self._lock:
            return {
                "num_workers": self.num_workers,
                "stall_s": self.stall_s,
                "hang_verdict": self._hang_verdict,
                "stalled": sorted(self._stalled),
                "silent": sorted(self._silent),
                "ranks": {
                    str(r): {
                        "step": info.get("step"),
                        "progress": info.get("progress"),
                        "collective": info.get("collective"),
                        "hbm": info.get("hbm") or {},
                        "mem": info.get("mem") or {},
                    }
                    for r, info in self._ranks.items()
                },
            }


def _reset_for_tests():
    with _state_lock:
        _state.update({"step": None, "progress": 0, "collective": None})


def dump_all_threads():
    """faulthandler all-thread stack dump as text — what a worker
    answers a driver dump request with. faulthandler needs a real
    fd, so spool through an unlinked temp file."""
    import faulthandler
    import tempfile

    with tempfile.TemporaryFile(mode="w+") as f:
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.seek(0)
        return f.read()


__all__ = [
    "HeartbeatSender", "HangDetector", "heartbeat_payload",
    "note_step", "note_collective", "progress_snapshot",
    "export_device_memory", "dump_all_threads",
    "heartbeat_interval", "stall_seconds",
    "VERDICT_STALL", "VERDICT_SILENT", "VERDICT_STRAGGLER",
    "VERDICT_DEADLOCK",
]
