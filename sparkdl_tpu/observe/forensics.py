"""Driver-side perf-forensics manager: alert-triggered (and manual)
on-demand profiling with differential step attribution.

The driver half of the forensics round trip
(:mod:`sparkdl_tpu.observe.capture` is the worker half). The platform
already tells an operator *that* a perf regression happened
(``step_time_regression`` et al. in ``alerts.json``); this module
makes the firing produce its own evidence, the way a hang produces
stack dumps and an OOM produces ``oom_report.json``:

- **why** — ``perf.diff_attribution`` between the alert rule's own
  calibration window (the healthy past the baseline was computed
  from, stashed by the alert engine) and the regressed window that
  fired, written into the run dir as ``regression_report.json``
  beside ``alerts.json``;
- **what it looked like** — a ``MSG_PROFILE_REQ`` frame down the
  offending rank's control socket (the ``MSG_DUMP_REQ`` pattern)
  tells that rank's capture service to profile the next N steps:
  xprof trace + uncapped attribution rows + device-memory snapshot,
  recovered into the run dir at write time.

Trigger paths sharing this machinery:

- the alert-engine hook — ``launch_gang``'s monitor loop hands each
  poll's firings to :meth:`ForensicsManager.on_alerts`, gated behind
  ``SPARKDL_TPU_PROFILE_ON_ALERT`` (default off);
- the manual ``POST /capturez?rank=N`` statusz endpoint (and the
  ``python -m sparkdl_tpu.observe.capture URL`` CLI) via
  :meth:`request_capture`;
- the worker-side fixed-step knob ``SPARKDL_TPU_PROFILE_AT_STEP``
  (A/B capture — no driver involvement at all).

Flap control: a per-(rule, rank) cooldown
(``SPARKDL_TPU_PROFILE_COOLDOWN_S``) on the alert path, plus at most
one capture in flight per rank on every path (cleared when the
worker's ``MSG_PROFILE_DONE`` lands) — a flapping alert can never
stack profiler sessions on a struggling rank.

Zero-overhead contract: :func:`maybe_make_forensics` returns None
without live gang telemetry — no object, no knob read, no callback.
The manager spans supervised attempts like the alert engine;
:meth:`bind_server` rebinds it to each attempt's control plane.
"""

import threading
import time

from sparkdl_tpu.utils import knobs

PROFILE_ON_ALERT_ENV = "SPARKDL_TPU_PROFILE_ON_ALERT"
PROFILE_COOLDOWN_ENV = "SPARKDL_TPU_PROFILE_COOLDOWN_S"
DEFAULT_COOLDOWN_S = 300.0

# The alert rules whose firings are *perf* regressions — the ones a
# profile window can explain. Liveness/memory rules have their own
# forensic artifacts (stack dumps, oom/leak reports).
PERF_RULES = ("step_time_regression", "mfu_drop", "overlap_drop")


def maybe_make_forensics(telemetry, alert_engine=None, env=None):
    """The latch: a :class:`ForensicsManager` only when gang telemetry
    is live; None otherwise — no object, no knob read. The ON_ALERT
    knob gates only the alert hook, not construction: the manual
    ``/capturez`` path works on any telemetry-on gang."""
    if telemetry is None:
        return None
    return ForensicsManager(telemetry, alert_engine=alert_engine,
                            env=env)


class ForensicsManager:
    """Driver-side capture orchestration + regression-report builder.

    Thread-safety: ``on_alerts`` runs on the monitor loop,
    ``request_capture`` on statusz handler threads, and the
    PROFILE_DONE callback on control-plane connection threads — one
    lock covers the in-flight/cooldown/report state."""

    def __init__(self, telemetry, alert_engine=None, env=None,
                 clock=time.monotonic, wall=time.time):
        self._telemetry = telemetry
        self._engine = alert_engine
        self._clock = clock
        self._wall = wall
        self.on_alert_enabled = knobs.read_bool(
            PROFILE_ON_ALERT_ENV, env=env)
        self.cooldown_s = float(
            knobs.read(PROFILE_COOLDOWN_ENV, env=env)
            or DEFAULT_COOLDOWN_S)
        self._lock = threading.Lock()
        self._server = None
        self._inflight = {}    # rank -> trigger info
        self._cooldowns = {}   # (rule, rank) -> monotonic ok-after
        self._completed = []   # PROFILE_DONE metas, arrival order
        self._entries = {}     # rank -> newest regression entry

    # -- attempt wiring -----------------------------------------------

    def bind_server(self, server):
        """Rebind to this attempt's control plane: PROFILE_REQ frames
        go out through it, and its PROFILE_DONE callback clears the
        per-rank in-flight latch. An attempt's workers dying with a
        capture outstanding also clears it (the dead rank can never
        answer; the next attempt's rank N must be capturable)."""
        with self._lock:
            self._server = server
            self._inflight.clear()
        if server is not None:
            server.on_profile_done = self._on_profile_done

    # -- trigger paths ------------------------------------------------

    def on_alerts(self, records):
        """The monitor-loop hook: fired alert records from one
        ``AlertEngine.poll`` pass. Perf-rule firings on a concrete
        rank request a capture (cooldown + in-flight gated) and write
        a regression entry; everything else is ignored. Inert unless
        ``SPARKDL_TPU_PROFILE_ON_ALERT`` is set. Returns the (rule,
        rank) pairs that started a capture."""
        if not self.on_alert_enabled:
            return []
        started = []
        for rec in records or ():
            rule = rec.get("rule")
            rank = rec.get("rank")
            if rule not in PERF_RULES or not isinstance(rank, int):
                continue
            if self._trigger(rank, "alert", rule, alert=rec):
                started.append((rule, rank))
        return started

    def request_capture(self, rank, reason="manual", rule=None):
        """The manual path (``POST /capturez``): request a capture on
        ``rank`` now. In-flight gated but cooldown-exempt — an
        operator asking twice means it. Returns (ok, why)."""
        try:
            rank = int(rank)
        except (TypeError, ValueError):
            return False, "bad rank"
        with self._lock:
            if rank in self._inflight:
                return False, f"capture already in flight on rank {rank}"
            if self._server is None:
                return False, "no control plane bound"
        ok = self._trigger(rank, reason, rule)
        return (ok, "requested" if ok
                else f"rank {rank} has no control connection")

    def _trigger(self, rank, reason, rule, alert=None):
        now = self._clock()
        with self._lock:
            server = self._server
            if server is None or rank in self._inflight:
                return False
            if alert is not None:
                key = (rule, rank)
                if now < self._cooldowns.get(key, 0.0):
                    return False
                self._cooldowns[key] = now + self.cooldown_s
            self._inflight[rank] = {
                "rank": rank, "reason": reason, "rule": rule,
                "ts": self._wall(),
            }
        entry = self._build_entry(rank, reason, rule, alert)
        if entry is not None:
            with self._lock:
                self._entries[rank] = entry
            self._telemetry.add_regression_report(entry)
        ok = server.request_profile(rank, reason=reason, rule=rule)
        if not ok:
            # No control connection for the rank (already dead, or a
            # pre-READY attempt): release the latch so a later trigger
            # can retry. The regression entry stays — the driver-side
            # diff is evidence even without a worker capture.
            with self._lock:
                self._inflight.pop(rank, None)
        return ok

    # -- the differential report --------------------------------------

    def _build_entry(self, rank, reason, rule, alert):
        """One ``regression_report.json`` entry: the per-component
        diff between the rank's calibration window and its current
        (regressed) window, plus the trigger metadata. ``diff`` is
        None when either window is unattributable (env/ledger
        baselines carry no event window) — the entry still records
        the firing and, later, the capture artifact names."""
        from sparkdl_tpu.observe import perf

        engine = self._engine
        baseline = (engine.baseline_window(rank)
                    if engine is not None else [])
        window_s = engine.window_s if engine is not None else 60.0
        regressed = (self._telemetry.recent_events(window_s)
                     or {}).get(rank) or []
        diff = None
        if baseline and regressed:
            try:
                diff = perf.diff_attribution(baseline, regressed)
            except Exception:
                diff = None
        if alert is None and diff is None:
            # A manual capture with nothing to diff produces only the
            # worker-side artifacts; no empty entry.
            return None
        return {
            "rule": rule,
            "rank": rank,
            "reason": reason,
            "ts": self._wall(),
            "severity": (alert or {}).get("severity"),
            "alert_detail": dict((alert or {}).get("detail") or {})
            or None,
            "diff": diff,
            "capture": None,
        }

    # -- worker answers -----------------------------------------------

    def _on_profile_done(self, rank, meta):
        """PROFILE_DONE landed (control-plane connection thread):
        clear the rank's in-flight latch, record the capture, and
        attach its artifact names to the rank's regression entry."""
        meta = dict(meta) if isinstance(meta, dict) else {}
        with self._lock:
            self._inflight.pop(rank, None)
            info = {
                "rank": rank,
                "reason": meta.get("reason"),
                "rule": meta.get("rule"),
                "report": meta.get("report"),
                "trace_dir": meta.get("trace_dir"),
                "steps_captured": meta.get("steps_captured"),
                "window_s": meta.get("window_s"),
                "ts": self._wall(),
            }
            self._completed.append(info)
            entry = self._entries.get(rank)
            if entry is not None and entry.get("capture") is None:
                entry["capture"] = {
                    k: info[k] for k in
                    ("report", "trace_dir", "steps_captured",
                     "window_s")
                }

    # -- status surface (statusz `captures` block) --------------------

    def captures_status(self):
        """The statusz ``captures`` block: live in-flight and
        completed captures plus the trigger config — what
        ``observe.top`` renders."""
        with self._lock:
            return {
                "on_alert": self.on_alert_enabled,
                "cooldown_s": self.cooldown_s,
                "in_flight": [dict(self._inflight[r])
                              for r in sorted(self._inflight)],
                "completed": [dict(c) for c in self._completed],
            }


__all__ = [
    "ForensicsManager", "maybe_make_forensics", "PERF_RULES",
]
