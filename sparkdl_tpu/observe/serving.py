"""Request-level serving telemetry: per-request SLO tracing through
the continuous-batching engine, plus the run-dir artifacts a serving
box leaves behind.

Training gangs got their observability in PRs 3 and 5 (metrics
registry, merged timeline, flight recorder, doctor); this module gives
the SERVING path the same treatment. One :class:`ServingTelemetry`
instance rides a :class:`~sparkdl_tpu.models.server.ServingFrontend`
and instruments the full request lifecycle::

    do_POST -> queue wait -> engine admission -> prefill ->
    per-chunk decode -> first token -> completion

**Opt-in latch (the PR-3 contract):** the frontend constructs a
ServingTelemetry only when ``SPARKDL_TPU_TELEMETRY_DIR`` is set.
Without it, ``frontend.request_telemetry`` and ``engine.telemetry``
stay ``None`` and the hot path performs ZERO observe work per token —
every hook site is one ``is not None`` test (pinned by test the same
way PR 5 pinned heartbeat thread names).

**Span tree** (Chrome trace, ``cat="serving"``), keyed by request id —
each request renders as its own track (``tid = rid``) so the tree
reads per-request in Perfetto, and the instants are ordered
``request.submit <= request.admit <= request.first_token <=
request.done``::

    request                  X  arrival -> generation done
      request.queue_wait     X  arrival -> slot admission
      request.submit         i  handed to engine.submit
      request.admit          i  engine started prefilling it
      request.first_token    i  first generated token (args: ttft_s)
      request.done           i  finished (args: code, tokens,
                                tokens_per_sec)
    request.reject           i  refused before admission (args: code,
                                reason; no rid — it never got one)

**SLO metrics** (recorded into the frontend's own always-on registry,
so they ride the existing ``GET /metrics``):

- ``server_ttft_seconds`` — arrival -> first token;
- ``server_inter_token_seconds`` — gap between consecutive tokens of
  one request (the streaming jitter SLO);
- ``server_queue_wait_seconds`` — arrival -> the engine starting
  admission (prefill) for the request;
- ``server_tokens_per_sec`` — per-request decode rate histogram;
- ``server_generated_tokens_total`` — aggregate token counter;
- ``server_admission_rejections_total{reason=...}`` — requests
  refused before admission (``invalid_request``, ``engine_refused``).

**Engine-internal gauges** (why latency moved — fed by the engine's
``telemetry`` hooks in :mod:`sparkdl_tpu.models.serving`):

- ``engine_batch_utilization`` — active slots / n_slots, observed once
  per decode chunk (its ``_sum/_count`` is the time-average the
  latency-under-load bench reports);
- ``engine_active_slots`` / ``engine_slot_occupancy`` — slots busy at
  the last chunk (count and fraction);
- ``engine_kv_page_occupancy`` — used pages / pool (paged cache only);
- ``engine_kv_page_occupancy_high_water`` — the worst occupancy any
  chunk has seen (paged cache only) — pool sizing reads this, not the
  instantaneous gauge;
- ``engine_request_kv_pages`` — per-request worst-case KV-page
  footprint histogram, observed at admission (paged cache only);
- ``engine_decode_chunks_total`` / ``engine_decode_tokens_total`` —
  decode chunks and tokens dispatched (dispatched minus accepted
  ``server_generated_tokens_total`` = host-discarded overshoot);
- ``engine_admission_deferrals_total{reason=...}`` — admissions
  capacity-deferred (``pool_exhausted``), requeued not refused.

**Run artifacts:** :meth:`write` leaves the SAME artifact set a
training gang's launcher writes — ``timeline.json`` (one "server"
lane plus one track per request), ``metrics.prom`` / ``metrics.json``
(series labeled ``rank="server"``) — under a fresh
``SPARKDL_TPU_TELEMETRY_DIR/run-<pid>-<n>/`` dir, and mirrors every
event into a PR-5 flight-recorder ring in that dir, so a SIGKILLed
server's request tail is recoverable post-mortem
(``observe.doctor`` reads the ring when ``timeline.json`` never got
written).
"""

import json
import os
import socket
import threading
import time

from sparkdl_tpu.observe.metrics import render_json, render_prometheus
from sparkdl_tpu.observe.timeline import chrome_trace

SERVER_LABEL = "server"

# Periodic artifact writes for long-running servers (seconds; <= 0
# disables the writer thread — close() still writes once).
WRITE_S_ENV = "SPARKDL_TPU_SERVING_WRITE_S"
DEFAULT_WRITE_S = 30.0

# Retained-trace cap: a serving box runs indefinitely (unlike a gang
# launch), so the re-rendered timeline keeps the NEWEST N events and
# counts what it dropped (the metrics registry is cumulative and never
# drops anything).
MAX_EVENTS_ENV = "SPARKDL_TPU_SERVING_TRACE_EVENTS"
DEFAULT_MAX_EVENTS = 100_000

# Per-request decode rates span tiny CPU rigs (a few tok/s) through
# batched TPU serving (thousands) — the latency DEFAULT_BUCKETS would
# dump every sample in +Inf.
RATE_BUCKETS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                500.0, 1000.0, 2500.0, 5000.0, 10000.0)

# Utilization lives in [0, 1]; sixteenths resolve a slot at n_slots<=16
# and the _sum/_count average is exact regardless of layout.
UTIL_BUCKETS = tuple(i / 16 for i in range(1, 17))

# Per-request KV-page footprints: power-of-two buckets span a one-page
# toy prompt through a long-context pool-filler.
PAGE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                512.0)


class ServingTelemetry:
    """One serving run's request instrumentation + artifact writer.

    ``registry`` is the frontend's own (always-on) metrics registry —
    SLO series land next to the request-class counters on the same
    ``GET /metrics``. The timeline is this instance's OWN
    :class:`~sparkdl_tpu.observe.timeline.Timeline` (not the
    process-global one): a frontend hosted inside a telemetry-enabled
    gang worker must not steal events the worker's flusher would ship
    to the driver, and two frontends in one process must not drain
    each other — the serving run dir owns exactly its own story. The
    flight-recorder mirror hangs off this private timeline's
    ``observer`` hook, so no process-global observer is touched
    either.

    Threading: per-request state (``_req``) is touched only on the
    engine thread (submit/admit/token/done all run there — the
    frontend's ``_poll_queue`` and result loop included); arrival and
    rejection hooks run on handler threads but touch only the
    thread-safe registry/timeline and the request's own mailbox.
    """

    def __init__(self, registry, run_dir=None, max_events=None):
        from sparkdl_tpu import observe
        from sparkdl_tpu.observe.flightrec import FlightRecorder, ring_path
        from sparkdl_tpu.observe.timeline import Timeline

        self.registry = registry
        self.timeline = Timeline()
        self.run_dir = run_dir or observe.new_run_dir()
        self._events = []        # drained-but-retained (rewrites render all)
        self._closed = False
        try:
            self.max_events = int(
                max_events if max_events is not None
                else os.environ.get(MAX_EVENTS_ENV, DEFAULT_MAX_EVENTS))
        except ValueError:
            self.max_events = DEFAULT_MAX_EVENTS
        self._dropped = 0
        self._pool_high_water = 0.0   # worst KV-page occupancy seen
        self._write_lock = threading.Lock()  # writer thread vs close()
        self._writer = None
        self._writer_stop = None
        # Crash story: mirror every event into an mmap ring in the run
        # dir — a SIGKILLed server never reaches write(), but the
        # kernel writes the MAP_SHARED pages back anyway and the
        # doctor recovers the request tail from the ring alone. The
        # mirror rides THIS timeline's observer hook (private, never
        # the global observe.set_flight_recorder — a gang worker's own
        # ring must stay untouched).
        self._flight = FlightRecorder(ring_path(self.run_dir, 0))
        self.timeline.observer = self._flight.record
        self._req = {}           # rid -> lifecycle state

    # -- frontend hooks (HTTP side) -----------------------------------

    def request_arrived(self, box, prompt_len, max_new, stream):
        """Stamp the mailbox with the request's wall-clock arrival
        (its ``t0`` perf stamp already exists) — queue wait and TTFT
        measure from here, 400s included."""
        box.obs_wall0 = time.time()
        box.obs_meta = (int(prompt_len), int(max_new), bool(stream))

    def request_rejected(self, code, reason):
        """Refused before admission (validation 400, engine-specific
        submit refusal): no rid, no span tree — one instant + the
        rejection counter the doctor breaks down by reason."""
        self.registry.counter(
            "server_admission_rejections_total", reason=reason).inc()
        self.timeline.instant("request.reject", cat="serving",
                              code=int(code), reason=reason)

    def request_submitted(self, rid, box):
        """The engine thread handed the request to ``engine.submit``
        — the span tree's root opens here (engine thread only)."""
        wall0 = getattr(box, "obs_wall0", None) or time.time()
        meta = getattr(box, "obs_meta", (0, 0, False))
        self._req[rid] = {
            "wall0": wall0, "perf0": box.t0,
            "prompt_len": meta[0], "max_new": meta[1],
            "stream": meta[2],
            "admit_wall": None, "admit_perf": None,
            "first_perf": None, "last_perf": None, "tokens": 0,
        }
        self.timeline.instant("request.submit", cat="serving", tid=rid,
                              rid=rid, prompt_len=meta[0],
                              max_new=meta[1])

    def token(self, rid):
        """One generated token reached the frontend: first token
        observes TTFT, every later one the inter-token gap."""
        st = self._req.get(rid)
        if st is None:
            return
        now = time.perf_counter()
        st["tokens"] += 1
        self.registry.counter("server_generated_tokens_total").inc()
        if st["first_perf"] is None:
            st["first_perf"] = now
            ttft = now - st["perf0"]
            self.registry.histogram("server_ttft_seconds").observe(ttft)
            self.timeline.instant("request.first_token", cat="serving",
                                  tid=rid, rid=rid,
                                  ttft_s=round(ttft, 6))
        else:
            self.registry.histogram(
                "server_inter_token_seconds"
            ).observe(now - st["last_perf"])
        st["last_perf"] = now

    def request_done(self, rid, code=200):
        """Generation finished (or the request was failed): close the
        span tree and observe the per-request rate."""
        st = self._req.pop(rid, None)
        if st is None:
            return
        now_perf = time.perf_counter()
        total_s = now_perf - st["perf0"]
        ttft = (st["first_perf"] - st["perf0"]
                if st["first_perf"] is not None else None)
        queue_wait = (st["admit_perf"] - st["perf0"]
                      if st["admit_perf"] is not None else None)
        # Decode rate over the request's whole residency (admission
        # included): tokens / (arrival -> done). Failed requests that
        # never produced a token observe nothing.
        tps = None
        if st["tokens"] and total_s > 0:
            tps = st["tokens"] / total_s
            self.registry.histogram(
                "server_tokens_per_sec", buckets=RATE_BUCKETS
            ).observe(tps)
        self.timeline.instant(
            "request.done", cat="serving", tid=rid, rid=rid,
            code=int(code), tokens=st["tokens"],
            tokens_per_sec=round(tps, 3) if tps else None,
        )
        if queue_wait is not None:
            self.timeline.complete(
                "request.queue_wait", st["wall0"], queue_wait,
                cat="serving", tid=rid, rid=rid,
            )
        self.timeline.complete(
            "request", st["wall0"], total_s, cat="serving", tid=rid,
            rid=rid, code=int(code), tokens=st["tokens"],
            ttft_s=round(ttft, 6) if ttft is not None else None,
            queue_wait_s=(round(queue_wait, 6)
                          if queue_wait is not None else None),
            tokens_per_sec=round(tps, 3) if tps else None,
            stream=st["stream"], prompt_len=st["prompt_len"],
        )

    # -- engine hooks (models/serving.py, behind `telemetry is not
    # -- None` on the engine side) ------------------------------------

    def request_admitted(self, rid):
        """The engine pulled the request off its queue and is starting
        its prefill — queue wait ends here."""
        st = self._req.get(rid)
        if st is None:
            return
        st["admit_wall"] = time.time()
        st["admit_perf"] = time.perf_counter()
        self.registry.histogram("server_queue_wait_seconds").observe(
            st["admit_perf"] - st["perf0"])
        self.timeline.instant("request.admit", cat="serving", tid=rid,
                              rid=rid)

    def decode_chunk(self, active, n_slots, n_tokens,
                     free_pages=None, n_pages=None):
        """Once per decode chunk (or speculation round): the batch
        shape that explains WHY latency moved."""
        util = active / max(1, n_slots)
        self.registry.histogram(
            "engine_batch_utilization", buckets=UTIL_BUCKETS
        ).observe(util)
        self.registry.gauge("engine_active_slots").set(active)
        self.registry.gauge("engine_slot_occupancy").set(util)
        self.registry.counter("engine_decode_chunks_total").inc()
        # tokens DISPATCHED (active slots x chunk steps) vs the
        # accepted server_generated_tokens_total: the delta is
        # host-discarded overshoot (mid-chunk eos/budget) — decode
        # compute the chunk granularity wastes
        self.registry.counter("engine_decode_tokens_total").inc(
            active * n_tokens)
        if n_pages:
            # page 0 is the reserved junk dump, never allocatable
            pool = max(1, n_pages - 1)
            occupancy = (pool - free_pages) / pool
            self.registry.gauge("engine_kv_page_occupancy").set(
                occupancy)
            if occupancy > self._pool_high_water:
                self._pool_high_water = occupancy
                self.registry.gauge(
                    "engine_kv_page_occupancy_high_water"
                ).set(occupancy)

    def request_pages(self, rid, pages):
        """Admission computed this request's worst-case KV-page
        footprint (prompt + max_new, shared prefix pages excluded) —
        the per-request memory cost distribution pool sizing is done
        against."""
        self.registry.histogram(
            "engine_request_kv_pages", buckets=PAGE_BUCKETS
        ).observe(pages)

    def admission_deferred(self, reason):
        """Capacity admission control kicked in (request left queued,
        not refused) — e.g. the paged pool can't cover the queue
        head's worst case yet."""
        self.registry.counter(
            "engine_admission_deferrals_total", reason=reason).inc()

    # -- artifacts -----------------------------------------------------

    def write(self):
        """Write the run-dir artifacts (same shapes as a training
        gang's: ``timeline.json`` + ``metrics.prom`` +
        ``metrics.json``), atomically. Idempotent — a later write
        re-renders everything retained so far. A serving box runs
        indefinitely, so the retained trace is BOUNDED: beyond
        ``max_events`` the oldest events are dropped and counted in
        the trace's ``dropped_events`` (metrics are cumulative and
        lose nothing). Returns the paths."""
        with self._write_lock:
            self._events.extend(self.timeline.drain())
            if len(self._events) > self.max_events:
                drop = len(self._events) - self.max_events
                del self._events[:drop]
                self._dropped += drop
            host = socket.gethostname()
            trace = chrome_trace(
                [(0, f"{SERVER_LABEL} @ {host}", self._events)])
            if self._dropped:
                trace["dropped_events"] = self._dropped
            labeled = [({"rank": SERVER_LABEL}, self.registry.snapshot())]
            files = [
                ("timeline.json", json.dumps(trace)),
                ("metrics.prom", render_prometheus(labeled)),
                ("metrics.json", render_json(labeled, indent=2)),
            ]
            paths = {}
            for name, text in files:
                path = os.path.join(self.run_dir, name)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(text)
                os.replace(tmp, path)
                paths[name] = path
            self._flight.flush()
            return paths

    def start_writer(self, interval=None):
        """Periodic :meth:`write` on a daemon thread: a long-running
        server keeps its run dir current (the artifacts are readable
        mid-run, not only after close) and its in-memory event buffer
        drained. Idempotent; ``interval <= 0`` disables (returns
        None) — the close-time write still happens."""
        if self._writer is not None and self._writer.is_alive():
            return self._writer
        if interval is None:
            try:
                interval = float(
                    os.environ.get(WRITE_S_ENV, DEFAULT_WRITE_S))
            except ValueError:
                interval = DEFAULT_WRITE_S
        if interval <= 0:
            return None
        self._writer_stop = stop = threading.Event()

        def loop():
            while not stop.wait(interval):
                try:
                    self.write()
                except Exception:
                    pass  # telemetry never takes down the server

        self._writer = threading.Thread(
            target=loop, name="sparkdl-serving-telemetry-write",
            daemon=True)
        self._writer.start()
        return self._writer

    def stop_writer(self):
        if self._writer_stop is not None:
            self._writer_stop.set()
        if self._writer is not None:
            self._writer.join(timeout=5.0)
        self._writer = None
        self._writer_stop = None

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.stop_writer()
        self.timeline.observer = None
        self._flight.close()
