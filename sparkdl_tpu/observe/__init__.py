"""``sparkdl_tpu.observe``: gang-wide structured metrics + a merged
event timeline riding the control plane.

The package's observability layer (ROADMAP: production scale needs a
signal you can alert on, not log lines). Three pieces:

- :mod:`~sparkdl_tpu.observe.metrics` — per-process registry of
  counters/gauges/histograms with Prometheus-text and JSON exporters;
- :mod:`~sparkdl_tpu.observe.timeline` — typed spans/instants exported
  as Chrome trace-event JSON (opens in Perfetto);
- :mod:`~sparkdl_tpu.observe.aggregate` — driver-side merge of worker
  telemetry into one gang-wide view under ``SPARKDL_TPU_TELEMETRY_DIR``.

This module is the instrumentation facade the rest of the package
calls. **Off by default**: unless ``SPARKDL_TPU_TELEMETRY_DIR`` is set
(latched at first use, like the chaos harness), every helper here is a
no-op behind one cached boolean — production gangs that didn't opt in
pay a single ``if`` per call site and allocate nothing. The
:class:`~sparkdl_tpu.observe.metrics.Registry` class itself is always
live when instantiated explicitly (the serving frontend's ``/metrics``
endpoint owns one; its request metrics are part of its API, not
gang telemetry).

Worker→driver transport: inside a gang worker, the worker bootstrap
registers the control-plane client as the telemetry *sink*
(:func:`set_sink`) and starts a background flusher
(:func:`start_flusher`) that ships cumulative metric snapshots plus
drained timeline events as ``TELEMETRY`` frames every
``SPARKDL_TPU_TELEMETRY_FLUSH_S`` seconds (default 5) and once more at
exit — low-rate batches on the guaranteed control socket, same
backpressure posture as ``log_to_driver``. The chaos harness calls
:func:`flush` synchronously before an injected kill so the fault
instant reaches the driver even though the process dies by SIGKILL.

See ``docs/observability.rst`` for the metric catalog and env knobs.
"""

import itertools
import os
import socket
import threading

from sparkdl_tpu.observe.metrics import Registry
from sparkdl_tpu.observe.timeline import Timeline

TELEMETRY_DIR_ENV = "SPARKDL_TPU_TELEMETRY_DIR"
FLUSH_S_ENV = "SPARKDL_TPU_TELEMETRY_FLUSH_S"
DEFAULT_FLUSH_S = 5.0

__all__ = [
    "enabled", "telemetry_dir", "metrics", "timeline",
    "inc", "set_gauge", "observe_value", "span", "host_span",
    "instant", "complete",
    "set_sink", "flush", "start_flusher", "stop_flusher",
    "snapshot_payload", "new_run_dir", "Registry", "Timeline",
    "set_flight_recorder",
]

# Latched like the chaos harness: gangs ship env at spawn, so one
# check at first call suffices and the disabled path stays a single
# boolean test forever after.
_enabled = None

_registry = Registry()
_timeline = Timeline()
_sink = None                       # callable(payload_dict) or None
_sink_lock = threading.Lock()      # serializes flush() payloads
_flusher = None
_flusher_stop = None
_run_seq = itertools.count()


def enabled():
    """True when telemetry was opted in (``SPARKDL_TPU_TELEMETRY_DIR``
    set). Cached; tests reset via :func:`_reset_for_tests`."""
    global _enabled
    if _enabled is None:
        _enabled = bool(os.environ.get(TELEMETRY_DIR_ENV))
    return _enabled


def telemetry_dir():
    return os.environ.get(TELEMETRY_DIR_ENV) or None


def new_run_dir():
    """A fresh per-launch artifact directory under the telemetry root
    (``run-<driverpid>-<n>``): one gang launch — across all its
    supervised attempts — writes one merged view."""
    d = os.path.join(
        telemetry_dir(), f"run-{os.getpid()}-{next(_run_seq)}"
    )
    os.makedirs(d, exist_ok=True)
    return d


def metrics():
    """This process's global registry (driver or worker side)."""
    return _registry


def timeline():
    """This process's global timeline."""
    return _timeline


# -- recording helpers (no-ops when telemetry is off) -----------------------


def inc(name, value=1, **labels):
    if enabled():
        _registry.counter(name, **labels).inc(value)


def set_gauge(name, value, **labels):
    if enabled():
        _registry.gauge(name, **labels).set(value)


def observe_value(name, value, buckets=None, **labels):
    if enabled():
        _registry.histogram(name, buckets=buckets, **labels).observe(value)


class _NoopSpan:
    """Shared do-nothing context manager: the disabled `span()` path
    allocates nothing (the zero-overhead contract's visible half)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


def span(name, cat="", **args):
    if not enabled():
        return _NOOP_SPAN
    return _timeline.span(name, cat=cat, **args)


def host_span(name, **args):
    """A ``cat="host"`` span: host-side work done on behalf of the
    device program (io_callback/debug-callback bodies, checkpoint
    host snapshots). This is the built-in emitter feeding the
    ``host_callback`` component of the ``observe.perf`` step
    attribution — wrap the Python body of a callback (or any host
    detour inside the step window) and the time lands there instead
    of being misread as compute. No-op (shared singleton) with
    telemetry off, like :func:`span`."""
    return span(name, cat="host", **args)


def instant(name, cat="", **args):
    if enabled():
        _timeline.instant(name, cat=cat, **args)


def complete(name, start, dur, cat="", tid=None, **args):
    """Record a complete event with explicit wall-clock start and
    duration (seconds) — for blocks whose endpoints the caller already
    timed (the collective wrappers measure with ``perf_counter`` and
    report here once)."""
    if enabled():
        _timeline.complete(name, start, dur, cat=cat, tid=tid, **args)


# -- worker flush machinery --------------------------------------------------


def set_sink(sink):
    """Register where :func:`flush` ships payloads (a gang worker
    passes ``client.send_telemetry``); ``None`` unregisters."""
    global _sink
    _sink = sink


def set_flight_recorder(rec):
    """Mirror every timeline event into ``rec`` (a
    :class:`~sparkdl_tpu.observe.flightrec.FlightRecorder`) so the
    tail of the story survives a SIGKILL between flushes. ``None``
    unregisters (and closes nothing — the caller owns the recorder's
    lifecycle)."""
    _timeline.observer = rec.record if rec is not None else None


def snapshot_payload():
    """One flush unit: host/pid attribution, the cumulative metric
    snapshot, and the timeline events drained since the last flush."""
    return {
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "metrics": _registry.snapshot(),
        "events": _timeline.drain(),
    }


def flush(lock_timeout=5.0):
    """Ship a telemetry payload to the registered sink now. Safe to
    call from any thread (payload assembly + send are serialized so a
    periodic flush and a chaos pre-kill flush cannot interleave);
    no-op without a sink or with telemetry off. The lock acquire is
    BOUNDED: if another flush is wedged mid-send (driver stopped
    draining), give up rather than hang — the worker-exit path calls
    this right after ``stop_flusher``'s join also timed out on that
    same wedged thread, and BYE must still go out."""
    sink = _sink
    if sink is None or not enabled():
        return False
    if not _sink_lock.acquire(timeout=lock_timeout):
        return False
    try:
        payload = snapshot_payload()
        try:
            sink(payload)
        except Exception:
            # Telemetry must never take down the instrumented process;
            # the control-plane client already swallows socket errors,
            # this guards custom sinks.
            return False
    finally:
        _sink_lock.release()
    return True


def start_flusher(interval=None):
    """Background periodic flush (worker side). Idempotent. An
    interval <= 0 disables the periodic flusher entirely (returns
    None) — the exit-time and chaos flushes still fire — rather than
    letting ``wait(0)`` busy-spin TELEMETRY frames at the driver."""
    global _flusher, _flusher_stop
    if _flusher is not None and _flusher.is_alive():
        return _flusher
    if interval is None:
        interval = float(os.environ.get(FLUSH_S_ENV, DEFAULT_FLUSH_S))
    if interval <= 0:
        return None
    _flusher_stop = stop = threading.Event()

    def loop():
        while not stop.wait(interval):
            flush()

    _flusher = threading.Thread(
        target=loop, name="sparkdl-tpu-telemetry-flush", daemon=True
    )
    _flusher.start()
    return _flusher


def stop_flusher():
    global _flusher, _flusher_stop
    if _flusher_stop is not None:
        _flusher_stop.set()
    if _flusher is not None:
        _flusher.join(timeout=5.0)
    _flusher = None
    _flusher_stop = None


def _reset_for_tests():
    """Fresh state: re-latch the enabled flag, empty registry and
    timeline (dropping any flight-recorder mirror), no sink/flusher,
    health counters zeroed."""
    global _enabled, _registry, _timeline, _sink
    stop_flusher()
    _enabled = None
    _registry = Registry()
    _timeline = Timeline()
    _sink = None
    from sparkdl_tpu.observe import health, mem, perf

    health._reset_for_tests()
    perf._reset_for_tests()
    mem._reset_for_tests()
