"""``observe.statusz``: the live tier of gang observability — a
driver-side HTTP status server for a RUNNING gang.

The reference's one observable surface is ``log_to_driver``
(``runner_base.py`` docstrings); PR 3/5/7 made the *post-hoc* story
excellent (run-dir artifacts, post-mortems, attribution), but a live
gang was a black box between launch and ``GangTelemetry.write``. This
server closes the gap by exposing, over plain HTTP on the driver, the
telemetry that ALREADY arrives every flush interval (the per-rank
``MSG_TELEMETRY`` cumulative snapshots and ``MSG_HEARTBEAT`` payloads
today just wait for ``write()``):

``GET /metrics``
    Live gang-merged Prometheus text: :func:`render_prometheus` over
    :meth:`GangTelemetry.live_labeled` — the newest cumulative
    snapshot per rank incarnation, merged exactly as the run-dir
    ``metrics.prom`` will be, plus the driver's own delta and the
    ``build_info{git_sha,jax_version,device_kind}`` stamp. Point a
    Prometheus scraper here and the run-dir artifact becomes the
    scrape's final sample, not the only one.
``GET /statusz``
    One JSON document for humans and ``observe.top``: per-rank
    step / progress / last-collective / HBM / beat-age from the PR 5
    heartbeat state, supervisor attempt counters, a rolling PR 7
    attribution window (component fractions, median step time,
    overlap efficiency, MFU) per rank, the alert engine's rule
    catalog + firings, and — when a
    :class:`~sparkdl_tpu.models.fleet.FleetFrontend` has registered
    itself via :func:`register_fleet` — a per-replica
    depth/in-flight/restarts table.
``GET /events``
    Server-sent-events tail of the live merged timeline: each journal
    event as one ``data:`` line with its sequence as the SSE ``id``,
    so ``curl -N .../events`` watches the gang's step spans, health
    verdicts and chaos instants stream by in real time.
``POST /capturez?rank=N``
    Manual perf-forensics trigger (ISSUE 20): asks the forensics
    manager to send a ``PROFILE_REQ`` frame down rank N's control
    socket — the worker captures an xprof trace + uncapped
    attribution window into its job dir. The ``captures`` block of
    ``/statusz`` reports in-flight and completed captures.

Zero-overhead contract (the PR 3 latch, extended): everything here is
inert unless ``SPARKDL_TPU_STATUSZ_PORT`` is set — no thread, no
socket, no object (:func:`maybe_start_statusz` returns None). With
the env set the server runs on daemon threads named
``sparkdl-tpu-statusz*`` and costs the gang nothing between requests;
handlers only READ (journal snapshots, merged metric renders) — they
never mutate gang state, so a scrape cannot perturb the run.
"""

import json
import os
import threading
import time
import weakref

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

STATUSZ_PORT_ENV = "SPARKDL_TPU_STATUSZ_PORT"

STATUSZ_SCHEMA = "sparkdl_tpu.observe.statusz/1"

# Rolling window the /statusz perf section is computed over (shares
# the alert engine's window knob so the two live views agree), and
# the rule catalog the /statusz alerts section names.
from sparkdl_tpu.observe.alerts import (  # noqa: E402  (constant import)
    DEFAULT_WINDOW_S,
    RULES as ALERT_RULES,
    WINDOW_S_ENV,
    _env_float,
)

# -- fleet registration -------------------------------------------------------
#
# A FleetFrontend lives in the serving process, not inside the gang
# machinery; when one starts it registers itself here (weakly — the
# status server must never keep a closed fleet alive) so any statusz
# server in the same process can render its per-replica table.

_fleets = []
_fleets_lock = threading.Lock()


def register_fleet(frontend):
    """Called by :meth:`FleetFrontend.start`; idempotent (a restarted
    frontend never duplicates its row), and a dead ref is pruned on
    the next read."""
    with _fleets_lock:
        if not any(ref() is frontend for ref in _fleets):
            _fleets.append(weakref.ref(frontend))


def unregister_fleet(frontend):
    """Called by :meth:`FleetFrontend.close`: a CLOSED fleet must
    leave the table immediately — the weakref only dies when the
    object is collected, and callers routinely keep the variable
    around after close(), which would render a dead fleet's replica
    rows indistinguishable from a crashed live one."""
    with _fleets_lock:
        _fleets[:] = [ref for ref in _fleets
                      if ref() is not None and ref() is not frontend]


def fleet_status():
    """Per-replica state of every live registered fleet, or None when
    none registered (the /statusz key is absent rather than empty —
    gang-only runs have no fleet section at all)."""
    out = []
    # Snapshot the live fleets under the registry lock, then build
    # the rows OUTSIDE it: replica_states()/queue_depth() take each
    # fleet's own locks, and holding the module registry lock across
    # foreign lock acquisitions couples every statusz reader to every
    # fleet's internals (lock-order hygiene; see analysis.concur).
    for fleet in live_fleets():
        try:
            out.append({
                "address": list(fleet.address),
                "replicas": fleet.replica_states(),
                "restarts": fleet._restarts,
                "max_queue": fleet.max_queue,
                "queue_depth": fleet.queue_depth(),
            })
        except Exception:
            continue
    return out or None


def live_fleets():
    """The live registered :class:`FleetFrontend` OBJECTS (not status
    rows) — what the elastic chip-budget arbiter scales and the
    ``server_ttft`` alert rule reads histograms from. Prunes dead
    refs like :func:`fleet_status`; returns a (possibly empty) list."""
    out = []
    with _fleets_lock:
        live = []
        for ref in _fleets:
            fleet = ref()
            if fleet is None:
                continue
            live.append(ref)
            out.append(fleet)
        _fleets[:] = live
    return out


def _reset_fleets_for_tests():
    with _fleets_lock:
        _fleets.clear()


# -- the server ---------------------------------------------------------------


def statusz_port(env=None):
    """The configured port, or None when the latch is closed. ``0``
    is a valid (ephemeral) port — the bound port is on the returned
    server's ``port`` attribute."""
    env = os.environ if env is None else env
    raw = env.get(STATUSZ_PORT_ENV)
    if raw in (None, ""):
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{STATUSZ_PORT_ENV}={raw!r} is not a port number") from None


def maybe_start_statusz(telemetry, detector=None, num_workers=None,
                        alerts=None, elastic=None, forensics=None,
                        env=None):
    """The latch: a running :class:`StatuszServer` when
    ``SPARKDL_TPU_STATUSZ_PORT`` is set and telemetry is live, None
    otherwise — no thread, no socket, no allocation on the default
    path. A bind failure (port already taken by another gang) logs
    and returns None rather than failing the launch: the gang matters
    more than its dashboard."""
    port = statusz_port(env)
    if port is None or telemetry is None:
        return None
    try:
        return StatuszServer(
            telemetry, detector=detector, num_workers=num_workers,
            alerts=alerts, elastic=elastic, forensics=forensics,
            port=port, env=env,
        ).start()
    except OSError as e:
        import logging

        logging.getLogger("HorovodRunner").warning(
            "statusz server failed to bind port %s: %s — continuing "
            "without the live endpoint", port, e)
        return None


class StatuszServer:
    """The driver-side HTTP server. Construction binds the socket;
    :meth:`start` begins serving on a daemon thread; :meth:`close` is
    idempotent and joins the serve thread."""

    def __init__(self, telemetry, detector=None, num_workers=None,
                 alerts=None, elastic=None, forensics=None,
                 host="127.0.0.1", port=0, env=None):
        env = os.environ if env is None else env
        self._telemetry = telemetry
        self._detector = detector
        self._alerts = alerts
        self._elastic = elastic
        self._forensics = forensics
        self.num_workers = num_workers
        self._t0 = time.time()
        self._closed = threading.Event()
        # same knob as the alert engine, same env mapping, same
        # knob-naming parse error, so the two live views always
        # describe the same window
        self.window_s = _env_float(env, WINDOW_S_ENV,
                                   DEFAULT_WINDOW_S)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):    # scrapes stay out of stderr
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    server._serve_metrics(self)
                elif path == "/statusz":
                    server._serve_statusz(self)
                elif path == "/events":
                    server._serve_events(self)
                elif path == "/healthz":
                    server._send(self, 200, b"ok\n", "text/plain")
                else:
                    self.send_error(404)

            def do_POST(self):
                path, _, query = self.path.partition("?")
                if path == "/capturez":
                    server._serve_capturez(self, query)
                else:
                    self.send_error(404)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.address = f"{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="sparkdl-tpu-statusz", daemon=True)

    def start(self):
        self._thread.start()
        from sparkdl_tpu import observe

        observe.instant("statusz.start", cat="statusz",
                        address=self.address)
        return self

    def close(self):
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)

    # -- handlers ------------------------------------------------------------

    @staticmethod
    def _send(handler, code, body, content_type):
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _serve_metrics(self, handler):
        from sparkdl_tpu.observe.metrics import render_prometheus

        body = render_prometheus(self._telemetry.live_labeled()).encode()
        self._send(handler, 200, body,
                   "text/plain; version=0.0.4; charset=utf-8")

    def status_doc(self):
        """The /statusz JSON document (also what ``observe.top``
        renders). Pure reads — safe at any moment of the run."""
        doc = {
            "schema": STATUSZ_SCHEMA,
            "ts": time.time(),
            "uptime_s": round(time.time() - self._t0, 1),
            "gang": {"num_workers": self.num_workers},
            "ranks": {},
            "supervisor": self._supervisor_state(),
            "perf": self._perf_window(),
        }
        if self._detector is not None:
            doc["ranks"] = {
                str(r): info
                for r, info in self._detector.live_state().items()
            }
            doc["gang"]["stall_s"] = self._detector.stall_s
            doc["gang"]["hang_verdict"] = self._detector.hang_verdict
            # Per-rank memory panel (ISSUE 18): the beacon mem samples
            # lifted into their own top-level table so mission control
            # reads categories/RSS without digging through ranks.
            memory = {}
            for r, info in doc["ranks"].items():
                mem = info.get("mem") or {}
                if mem:
                    memory[r] = {
                        "rss_bytes": mem.get("rss"),
                        "hbm_bytes": mem.get("hbm"),
                        "unattributed_bytes": mem.get("unattributed"),
                        "categories": mem.get("categories") or {},
                    }
            if memory:
                doc["memory"] = memory
        if self._alerts is not None:
            doc["alerts"] = {
                "enabled": True,
                "fired": self._alerts.records(),
                "rules": [r for r, _s, _m, _d in ALERT_RULES],
            }
        else:
            doc["alerts"] = {"enabled": False, "fired": []}
        if self._forensics is not None:
            try:
                doc["captures"] = self._forensics.captures_status()
            except Exception:
                pass
        fleet = fleet_status()
        if fleet is not None:
            doc["fleet"] = fleet
        if self._elastic is not None:
            try:
                doc["elastic"] = self._elastic.status()
            except Exception:
                pass
        return doc

    def _serve_statusz(self, handler):
        body = (json.dumps(self.status_doc(), indent=2, sort_keys=True)
                + "\n").encode()
        self._send(handler, 200, body, "application/json")

    def _supervisor_state(self):
        """Driver-side supervision counters as they stand: attempts,
        restarts, classified failures (the supervisor already counts
        them on the driver registry; reading a counter that was never
        written returns 0) — plus the per-attempt world sizes the
        launcher records, so an elastically shrunken gang is visible
        in mission control (current attempt's world vs the previous
        attempt's)."""
        from sparkdl_tpu import observe
        from sparkdl_tpu.horovod.supervisor import (
            attempt_chip_hours,
            attempt_world_sizes,
        )

        reg = observe.metrics()
        worlds = attempt_world_sizes()
        chip_hours = attempt_chip_hours()
        out = {
            "attempts_total": reg.counter("gang_attempts_total").value,
            "restarts_total": reg.counter("gang_restarts_total").value,
            "world_size": worlds[-1] if worlds else self.num_workers,
            "previous_world_size":
                worlds[-2] if len(worlds) > 1 else None,
            "world_sizes": worlds,
        }
        if chip_hours:
            out["chip_hours"] = chip_hours
            known = [e["chip_hours"] for e in chip_hours
                     if e.get("chip_hours") is not None]
            if known:
                out["chip_hours_total"] = round(sum(known), 6)
        return out

    def _perf_window(self):
        """Rolling attribution over the journal window, per rank:
        median step time, component fractions, overlap efficiency —
        plus the live MFU gauges from the merged snapshots."""
        from sparkdl_tpu.observe.alerts import _median
        from sparkdl_tpu.observe.perf import attribution_report

        events = self._telemetry.recent_events(self.window_s)
        per_rank = {}
        for rank, evs in sorted(events.items()):
            rep = attribution_report(evs)
            if not rep.get("steps"):
                continue
            median = _median(
                [r["dur_s"] for r in rep.get("per_step", ())])
            per_rank[str(rank)] = {
                "steps": rep["steps"],
                "median_step_s": round(median, 6),
                "fractions": rep.get("fractions"),
                "overlap_efficiency": rep.get("overlap_efficiency"),
            }
        # live MFU: newest mfu gauge per rank from the merged view
        try:
            for extra, snap in self._telemetry.live_labeled():
                rank = extra.get("rank")
                if rank in per_rank:
                    for g in snap.get("gauges", ()):
                        if g["name"] == "mfu":
                            per_rank[rank]["mfu"] = g["value"]
                            break
        except Exception:
            pass
        return {"window_s": self.window_s, "per_rank": per_rank}

    def _serve_capturez(self, handler, query):
        """``POST /capturez?rank=N`` — the one deliberate exception to
        the handlers-only-read rule: the manual perf-forensics trigger
        (``python -m sparkdl_tpu.observe.capture URL`` posts here).
        The capture itself runs on the target worker; this only asks
        the forensics manager to send the PROFILE_REQ frame. Omitting
        ``rank`` targets rank 0."""
        from urllib.parse import parse_qs

        if self._forensics is None:
            self._send(handler, 503,
                       b'{"ok": false, "detail": '
                       b'"perf forensics unavailable"}\n',
                       "application/json")
            return
        rank = (parse_qs(query).get("rank") or ["0"])[0]
        ok, why = self._forensics.request_capture(rank, reason="manual")
        body = (json.dumps(
            {"ok": ok, "detail": why, "rank": rank},
            sort_keys=True) + "\n").encode()
        self._send(handler, 200 if ok else 409, body,
                   "application/json")

    def _serve_events(self, handler):
        """SSE tail of the live journal. Streams until the client
        disconnects or the server closes; polls the journal at the
        telemetry flush cadence (new events only arrive on flushes)."""
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.end_headers()
        seq = 0
        try:
            # Resume support: Last-Event-ID picks up where a dropped
            # client left off (the journal ring bounds how far back).
            last = handler.headers.get("Last-Event-ID")
            if last:
                seq = int(last)
        except (TypeError, ValueError):
            seq = 0
        try:
            while not self._closed.is_set():
                newest, batch = self._telemetry.events_since(
                    seq, limit=256)
                # advance past what was SENT, not past the journal's
                # newest — a limit-truncated batch must not skip the
                # remainder on the next poll
                seq = batch[-1][0] if batch else newest
                for ev_seq, rank, event in batch:
                    payload = json.dumps(
                        {"rank": rank, "event": event},
                        sort_keys=True)
                    handler.wfile.write(
                        f"id: {ev_seq}\ndata: {payload}\n\n".encode())
                if not batch:
                    # comment line = keepalive; also how a dead client
                    # is detected between event batches
                    handler.wfile.write(b": keepalive\n\n")
                handler.wfile.flush()
                self._closed.wait(0.5)
        except (BrokenPipeError, ConnectionResetError, OSError):
            return


__all__ = [
    "StatuszServer", "maybe_start_statusz", "statusz_port",
    "register_fleet", "fleet_status", "live_fleets",
    "STATUSZ_PORT_ENV", "STATUSZ_SCHEMA",
]
