"""Per-process metrics registry: counters, gauges, histograms.

Zero-dependency and thread-safe by construction — instrumentation
points live on gang hot paths (collectives, train steps, the serving
request loop), so every mutation is one short critical section over
plain Python numbers, and the registry itself never imports jax,
numpy, or anything that could initialize a backend.

Export formats:

- :meth:`Registry.to_prometheus` — Prometheus text exposition format
  (``# TYPE`` headers, ``_bucket``/``_sum``/``_count`` series for
  histograms), the format the ``ServingFrontend`` ``GET /metrics``
  endpoint serves and the gang aggregator writes to
  ``SPARKDL_TPU_TELEMETRY_DIR/metrics.prom``.
- :meth:`Registry.to_json` — the same data as one JSON document for
  programmatic consumers (the CI artifact check, dashboards that
  don't scrape).

Cross-process semantics: workers ship cumulative :meth:`Registry.
snapshot` dicts to the driver over the control plane; the driver
merges them per rank with :func:`merge_snapshots` (counters and
histogram buckets sum across a rank's process incarnations — a
supervised relaunch restarts the counters — gauges take the newest
snapshot's value) and renders the gang-wide view with
:func:`render_prometheus` / :func:`render_json`, one ``rank`` label
per series.
"""

import bisect
import json
import sys
import threading
import time

# Latency-shaped default buckets (seconds): sub-millisecond collective
# dispatches through minute-long checkpoint writes.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, value=1):
        if value < 0:
            raise ValueError(f"counters only go up (inc({value}))")
        with self._lock:
            self._value += value

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (Prometheus ``gauge``)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value):
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Cumulative-bucket histogram (Prometheus ``histogram``): one
    count per upper bound plus the implicit ``+Inf`` catch-all, a
    running sum, and a total count."""

    __slots__ = ("_lock", "_uppers", "_counts", "_sum", "_count")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self._lock = threading.Lock()
        self._uppers = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self._uppers) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        value = float(value)
        idx = bisect.bisect_left(self._uppers, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def buckets(self):
        return self._uppers

    @property
    def counts(self):
        with self._lock:
            return list(self._counts)

    @property
    def sum(self):
        with self._lock:
            return self._sum

    @property
    def count(self):
        with self._lock:
            return self._count


def _label_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Registry:
    """Get-or-create store of named metrics, keyed by (name, labels).

    A name is bound to ONE metric kind; asking for the same name as a
    different kind raises instead of silently shadowing (the exporter
    could not render both under one ``# TYPE`` header anyway).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}   # (name, label_key) -> metric object
        self._kinds = {}     # name -> "counter" | "gauge" | "histogram"
        self._hist_buckets = {}  # name -> upper bounds (pinned at first use)

    def _get(self, kind, name, labels, factory):
        key = (name, _label_key(labels))
        with self._lock:
            seen = self._kinds.get(name)
            if seen is not None and seen != kind:
                raise ValueError(
                    f"metric {name!r} already registered as a {seen}, "
                    f"cannot re-register as a {kind}"
                )
            metric = self._metrics.get(key)
            if metric is None:
                metric = factory()
                self._metrics[key] = metric
                self._kinds[name] = kind
            return metric

    def counter(self, name, **labels):
        return self._get("counter", name, labels, Counter)

    def gauge(self, name, **labels):
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name, buckets=None, **labels):
        # Bucket layout is pinned per name so every labeled series of
        # one histogram aggregates (and renders) on the same bounds.
        with self._lock:
            bounds = self._hist_buckets.setdefault(
                name,
                tuple(sorted(float(b) for b in buckets))
                if buckets is not None else DEFAULT_BUCKETS,
            )
        return self._get(
            "histogram", name, labels, lambda: Histogram(bounds)
        )

    # -- export --------------------------------------------------------------

    def snapshot(self):
        """Cumulative JSON-able dump of every series — the unit that
        crosses the control plane (one snapshot supersedes the
        previous one from the same process)."""
        with self._lock:
            items = list(self._metrics.items())
            kinds = dict(self._kinds)
        snap = {"ts": time.time(), "counters": [], "gauges": [],
                "histograms": []}
        for (name, label_key), metric in items:
            labels = dict(label_key)
            kind = kinds[name]
            if kind == "counter":
                snap["counters"].append(
                    {"name": name, "labels": labels, "value": metric.value}
                )
            elif kind == "gauge":
                snap["gauges"].append(
                    {"name": name, "labels": labels, "value": metric.value}
                )
            else:
                snap["histograms"].append({
                    "name": name, "labels": labels,
                    "buckets": list(metric.buckets),
                    "counts": metric.counts,
                    "sum": metric.sum, "count": metric.count,
                })
        for k in ("counters", "gauges", "histograms"):
            snap[k].sort(key=lambda s: (s["name"], sorted(s["labels"].items())))
        return snap

    def to_prometheus(self):
        return render_prometheus([({}, self.snapshot())])

    def to_json(self, indent=None):
        return render_json([({}, self.snapshot())], indent=indent)


# -- build-info correlation ---------------------------------------------------
#
# Scrapes and ledger lines must join on the same git sha without
# guessing, so every *export surface* (serving /metrics, the gang
# statusz /metrics, the run-dir metrics.prom) stamps a constant
# ``build_info{git_sha,jax_version,device_kind} 1`` gauge onto its
# registry before rendering. Injection is explicit per surface — not
# inside ``snapshot()`` — so raw Registries stay exactly what their
# callers put in them (golden exports, unit tests), while every wire
# endpoint carries the correlation labels.

_build_info_labels = None
_build_info_lock = threading.Lock()


def build_info_labels():
    """Process-lifetime constant labels: short git sha of this
    checkout (``none`` outside one), the jax version WITHOUT importing
    jax (``sys.modules`` when already imported, package metadata
    otherwise — a metrics export must never be the thing that
    initializes a backend), and the probed device kind."""
    global _build_info_labels
    with _build_info_lock:
        if _build_info_labels is None:
            from sparkdl_tpu.observe import perf

            jax = sys.modules.get("jax")
            if jax is not None:
                jax_version = getattr(jax, "__version__", "unknown")
            else:
                try:
                    from importlib import metadata

                    jax_version = metadata.version("jax")
                except Exception:
                    jax_version = "uninstalled"
            _build_info_labels = {
                "git_sha": perf.git_sha() or "none",
                "jax_version": jax_version,
                "device_kind": perf.device_kind() or "none",
            }
        return dict(_build_info_labels)


def ensure_build_info(registry):
    """Stamp the ``build_info`` gauge (value 1, labels from
    :func:`build_info_labels`) onto ``registry``. Idempotent and
    cheap after the first call (labels are cached); returns the
    labels so callers can reuse them in their own records."""
    labels = build_info_labels()
    registry.gauge("build_info", **labels).set(1)
    return labels


def _reset_build_info_for_tests():
    global _build_info_labels
    with _build_info_lock:
        _build_info_labels = None


# -- snapshot merging and rendering (driver-side gang view) -----------------


def merge_snapshots(snaps):
    """Merge cumulative snapshots from successive incarnations of ONE
    logical process (e.g. a rank across supervised relaunches):
    counters and histogram bucket counts sum; gauges take the value
    from the newest snapshot BY ITS ``ts`` STAMP, not by position in
    ``snaps`` — callers recover incarnation files in directory-listing
    order, so a restarted rank whose first attempt flushed last must
    still lose to the newer attempt's gauge (ties go to the later
    argument). A gauge is a statement about "now"; only the newest
    "now" survives the merge."""
    out = {"ts": 0.0, "counters": [], "gauges": [], "histograms": []}
    counters = {}
    gauges = {}   # key -> (ts, value)
    hists = {}
    for snap in snaps:
        ts = snap.get("ts", 0.0)
        out["ts"] = max(out["ts"], ts)
        for c in snap.get("counters", ()):
            key = (c["name"], _label_key(c["labels"]))
            counters[key] = counters.get(key, 0.0) + c["value"]
        for g in snap.get("gauges", ()):
            key = (g["name"], _label_key(g["labels"]))
            if key not in gauges or ts >= gauges[key][0]:
                gauges[key] = (ts, g["value"])
        for h in snap.get("histograms", ()):
            key = (h["name"], _label_key(h["labels"]))
            prev = hists.get(key)
            if prev is None or list(prev["buckets"]) != list(h["buckets"]):
                # First sight (or a bucket-layout change across a code
                # rollout mid-job: keep the newer layout rather than
                # summing incompatible bins).
                hists[key] = {
                    "name": h["name"], "labels": dict(h["labels"]),
                    "buckets": list(h["buckets"]),
                    "counts": list(h["counts"]),
                    "sum": h["sum"], "count": h["count"],
                }
            else:
                prev["counts"] = [
                    a + b for a, b in zip(prev["counts"], h["counts"])
                ]
                prev["sum"] += h["sum"]
                prev["count"] += h["count"]
    for (name, lk), v in sorted(counters.items()):
        out["counters"].append(
            {"name": name, "labels": dict(lk), "value": v})
    for (name, lk), (_, v) in sorted(gauges.items()):
        out["gauges"].append({"name": name, "labels": dict(lk), "value": v})
    for key in sorted(hists):
        out["histograms"].append(hists[key])
    return out


def snapshot_delta(base, cur):
    """``cur`` minus ``base`` for the monotonic series — the per-RUN
    view of a registry that outlives runs (the driver's global
    registry spans every launch in the process; each launch's
    artifacts must report only its own counts). Counters subtract by
    value; histograms subtract bucket counts/sum/count (a bucket-
    layout change falls back to ``cur``); gauges are point-in-time
    and pass through. Series that did not move this run are dropped."""
    out = {"ts": cur.get("ts", 0.0), "counters": [],
           "gauges": [dict(g) for g in cur.get("gauges", ())],
           "histograms": []}
    base_c = {(c["name"], _label_key(c["labels"])): c["value"]
              for c in base.get("counters", ())}
    for c in cur.get("counters", ()):
        v = c["value"] - base_c.get(
            (c["name"], _label_key(c["labels"])), 0.0)
        if v > 0:
            out["counters"].append(
                {"name": c["name"], "labels": dict(c["labels"]),
                 "value": v})
    base_h = {(h["name"], _label_key(h["labels"])): h
              for h in base.get("histograms", ())}
    for h in cur.get("histograms", ()):
        prev = base_h.get((h["name"], _label_key(h["labels"])))
        if prev is None or list(prev["buckets"]) != list(h["buckets"]):
            d = {k: (list(h[k]) if isinstance(h[k], list) else h[k])
                 for k in ("name", "buckets", "counts", "sum", "count")}
            d["labels"] = dict(h["labels"])
        else:
            d = {"name": h["name"], "labels": dict(h["labels"]),
                 "buckets": list(h["buckets"]),
                 "counts": [a - b for a, b in
                            zip(h["counts"], prev["counts"])],
                 "sum": h["sum"] - prev["sum"],
                 "count": h["count"] - prev["count"]}
        if d["count"] > 0:
            out["histograms"].append(d)
    return out


def _esc(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_str(labels):
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_num(v):
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(labeled_snapshots):
    """Prometheus text format over ``[(extra_labels, snapshot), ...]``
    — one ``# TYPE`` header per metric name, every series carrying its
    own labels plus the extras (the gang aggregator passes
    ``{"rank": ...}``). Deterministic ordering so exports are
    golden-testable."""
    # name -> (kind, [(series_sort_key, [lines in emit order])])
    # Series sort by their labels; a histogram's bucket lines keep
    # ascending-``le`` order inside their series (the exposition
    # format expects cumulative buckets in increasing order).
    by_name = {}
    for extra, snap in labeled_snapshots:
        for kind, key in (("counter", "counters"), ("gauge", "gauges")):
            for s in snap.get(key, ()):
                labels = {**s["labels"], **extra}
                by_name.setdefault(s["name"], (kind, []))[1].append((
                    _label_key(labels),
                    [f"{s['name']}{_labels_str(labels)} "
                     f"{_fmt_num(s['value'])}"],
                ))
        for h in snap.get("histograms", ()):
            labels = {**h["labels"], **extra}
            lines = []
            cum = 0
            for upper, n in zip(h["buckets"], h["counts"]):
                cum += n
                lines.append(
                    f"{h['name']}_bucket"
                    f"{_labels_str({**labels, 'le': _fmt_num(upper)})} "
                    f"{cum}"
                )
            cum += h["counts"][len(h["buckets"])]
            lines.append(
                f"{h['name']}_bucket"
                f"{_labels_str({**labels, 'le': '+Inf'})} {cum}"
            )
            lines.append(
                f"{h['name']}_sum{_labels_str(labels)} "
                f"{_fmt_num(h['sum'])}"
            )
            lines.append(
                f"{h['name']}_count{_labels_str(labels)} {h['count']}"
            )
            by_name.setdefault(h["name"], ("histogram", []))[1].append(
                (_label_key(labels), lines)
            )
    out = []
    for name in sorted(by_name):
        kind, series = by_name[name]
        out.append(f"# TYPE {name} {kind}")
        for _, lines in sorted(series, key=lambda s: s[0]):
            out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")


def render_json(labeled_snapshots, indent=None):
    doc = {
        "generated_at": time.time(),
        "series": [
            {"labels": dict(extra), **snap}
            for extra, snap in labeled_snapshots
        ],
    }
    return json.dumps(doc, indent=indent, sort_keys=True)
