"""``observe.compare``: noise-aware perf diff + the regression gate.

``python -m sparkdl_tpu.observe.compare BASE CAND`` compares two
performance records and **exits non-zero when a regression is found**
— the CI perf gate is this exit code, so every PR's perf delta is
enforced, not eyeballed (ROADMAP item 3/4). Either side may be:

- a **bench JSON** file (the one-line record ``bench.py`` /
  ``benchmarks/*_bench.py`` print: ``{"metric": ..., "value": ...}``);
- the committed **BASELINE.json** (its ``published`` map);
- a **history ledger** (``benchmarks/results/history.jsonl``, one
  :func:`~sparkdl_tpu.observe.perf.history_record` per line). Default:
  the newest entry; ``history.jsonl@-2`` selects by index;
- a **telemetry run dir** (``run-*`` under
  ``SPARKDL_TPU_TELEMETRY_DIR``): per-rank ``train_step_per_second``
  gauges and the mean of the execute-phase ``train_step_seconds``
  histogram become the compared metrics.

Noise-aware thresholds: when a metric carries rep ``samples``, the
two sides are compared by their sample **medians** (a headline
``value`` is often one timed invocation — two runs of identical code
on a shared CPU differ >10% on it while their medians agree to <1%),
and a metric regresses only when the relative delta is worse than
``max(--floor, --iqr-k × rel-IQR)`` where rel-IQR is the
interquartile range over the samples divided by their median
(whichever side is noisier wins). A noisy-but-flat metric — wide IQR,
unchanged median — therefore passes; a genuine 20% cliff on a quiet
metric fails the default 5% floor. Lower-is-better metrics
(``*_seconds`` / ``*_ms`` / latency shapes) invert automatically.

Cross-host honesty: ledger records carry a host fingerprint; when the
two sides were measured on different hosts the numbers are
apples-to-oranges, so regressions are reported but the exit code stays
0 unless ``--strict-host`` — the committed baseline enforces on the
machine that recorded it and degrades to advisory anywhere else.

``--format json`` is the machine contract (the autotuner and CI
consume the same judge the humans read): per-metric rows carry the
compared medians, delta, threshold, noise and direction, and the top
level names the gate's own ``decision`` (``ok`` | ``regression`` |
``regression-advisory`` | ``no-overlap``) plus the ``exit_code`` it
implies, so a consumer never re-derives the cross-host/no-overlap
rules.

``--explain`` answers the next question a failing gate raises — *why*
is the candidate slower: when the verdict is not ``ok`` and both
sides are telemetry run dirs, the per-rank differential step
attribution (:func:`sparkdl_tpu.observe.perf.diff_attribution`, the
same core the alert-triggered forensics report uses) is appended —
per-component deltas, overlap-efficiency/MFU movement and the
top-growing span names, from each side's timeline (or the capped
``perf.json`` rows when the timeline is gone).
"""

import argparse
import json
import os
import sys


def _quantile(samples, p):
    xs = sorted(float(s) for s in samples)
    i = p * (len(xs) - 1)
    lo = int(i)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (i - lo)


def _rel_iqr(samples):
    if not samples or len(samples) < 4:
        return 0.0
    med = _quantile(samples, 0.5)
    if med == 0:
        return 0.0
    return abs((_quantile(samples, 0.75) - _quantile(samples, 0.25))
               / med)


def _effective_value(m):
    """The number a side is compared BY: the median of its rep
    samples when it has enough of them, else the raw value. A bench's
    headline ``value`` is often one timed invocation — on a shared
    CPU two back-to-back runs of identical code differ by >10% on
    that number while their medians agree to <1%, so the gate
    compares the robust center the IQR threshold already describes.
    """
    samples = m.get("samples")
    if isinstance(samples, (list, tuple)) and len(samples) >= 3:
        return _quantile(samples, 0.5), f"median[{len(samples)}]"
    return m["value"], "value"


_LOWER_IS_BETTER_HINTS = ("_seconds", "_ms", "latency", "ttft",
                          "_wait", "_s_mean")


def _higher_is_better(name, explicit=None):
    if explicit is not None:
        return bool(explicit)
    n = name.lower()
    return not any(h in n for h in _LOWER_IS_BETTER_HINTS)


# -- record loading ----------------------------------------------------------


def _from_bench_json(doc):
    metrics = {}
    if not isinstance(doc, dict):
        return {"kind": "bench", "host": None, "metrics": metrics}
    name = doc.get("metric")
    if name and isinstance(doc.get("value"), (int, float)):
        metrics[name] = {
            "value": float(doc["value"]),
            "unit": doc.get("unit"),
            "samples": doc.get("rate_samples") or doc.get("samples"),
        }
    # steps_per_sec_p50/p99 are NOT extracted as their own metrics:
    # they are the same throughput the headline value + rate_samples
    # already compare (scaled by batch*seq), but as bare numbers they
    # would bypass the median/IQR protection and make the gate flaky
    # on a noisy runner.
    return {"kind": "bench", "host": doc.get("host"), "metrics": metrics}


def _from_baseline(doc):
    metrics = {}
    for name, v in (doc.get("published") or {}).items():
        if name.startswith("_") or not isinstance(v, (int, float)):
            continue
        metrics[name] = {"value": float(v)}
    # the committed baseline records WHO measured it so the gate
    # enforces on that machine and degrades to advisory anywhere else
    return {"kind": "baseline", "host": doc.get("host_fingerprint"),
            "metrics": metrics}


def _from_history_entry(entry):
    metrics = {}
    for name, m in (entry.get("metrics") or {}).items():
        if not isinstance(m, dict):
            m = {"value": m}
        if isinstance(m.get("value"), (int, float)):
            metrics[name] = dict(m)
    return {
        "kind": "history",
        "host": entry.get("host"),
        "git_sha": entry.get("git_sha"),
        "ts": entry.get("ts"),
        "metrics": metrics,
    }


def _from_run_dir(path):
    try:
        with open(os.path.join(path, "metrics.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(
            f"compare: {path} has no readable metrics.json ({e})")
    metrics = {}
    for series in doc.get("series", ()):
        rank = series.get("labels", {}).get("rank")
        if rank is None or rank == "driver":
            continue
        for g in series.get("gauges", ()):
            if g.get("name") == "train_step_per_second" and isinstance(
                    g.get("value"), (int, float)):
                metrics[f"train_step_per_second[rank={rank}]"] = {
                    "value": float(g["value"])}
        for h in series.get("histograms", ()):
            if (h.get("name") == "train_step_seconds"
                    and h.get("labels", {}).get("phase") == "execute"
                    and h.get("count")):
                metrics[f"train_step_seconds_mean[rank={rank}]"] = {
                    "value": h["sum"] / h["count"],
                    "higher_is_better": False,
                }
    return {"kind": "run-dir", "host": None, "metrics": metrics}


def load_record(spec):
    """Load one comparison side from a path spec (file, ``file@IDX``
    for history ledgers, or a run dir)."""
    path, idx = spec, None
    if "@" in spec and not os.path.exists(spec):
        path, _, idx_s = spec.rpartition("@")
        try:
            idx = int(idx_s)
        except ValueError:
            path, idx = spec, None
    if os.path.isdir(path):
        return _from_run_dir(path)
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise SystemExit(f"compare: cannot read {path}: {e}")
    doc = None
    if not path.endswith(".jsonl"):
        # A pretty-printed single document also contains newlines, so
        # "one JSON value" is decided by the parser, not a heuristic.
        try:
            doc = json.loads(text)
        except ValueError:
            doc = None
    if doc is None:
        entries = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except ValueError:
                continue
        if not entries:
            raise SystemExit(f"compare: no parsable entries in {path}")
        try:
            entry = entries[idx if idx is not None else -1]
        except IndexError:
            raise SystemExit(
                f"compare: index {idx} out of range for {path} "
                f"({len(entries)} entries)")
        if isinstance(entry, dict) and "metrics" in entry:
            return _from_history_entry(entry)
        return _from_bench_json(entry)
    if "published" in doc:
        return _from_baseline(doc)
    if "metrics" in doc and "schema" in doc:
        return _from_history_entry(doc)
    return _from_bench_json(doc)


# -- the --explain diff ------------------------------------------------------


def _explain_windows(path):
    """rank -> diffable window for one run-dir side: the raw timeline
    events by lane when ``timeline.json`` survived (lane ``rank + 1``
    is rank ``r``, span names available — full-fidelity diff), else
    the capped per-step rows out of ``perf.json`` (component deltas
    still work; grown spans cannot be named)."""
    try:
        with open(os.path.join(path, "timeline.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = None
    out = {}
    for e in (doc or {}).get("traceEvents", ()):
        pid = e.get("pid") if isinstance(e, dict) else None
        if isinstance(pid, int) and pid >= 1 and e.get("ph") != "M":
            out.setdefault(str(pid - 1), []).append(e)
    if out:
        return out
    try:
        with open(os.path.join(path, "perf.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    for rank_s, rep in ((doc or {}).get("ranks") or {}).items():
        rows = (rep or {}).get("per_step")
        if rows:
            out[str(rank_s)] = list(rows)
    return out


def explain_run_dirs(base_path, cand_path):
    """The ``--explain`` core: per-rank
    :func:`~sparkdl_tpu.observe.perf.diff_attribution` between two run
    dirs (base = the healthy run, candidate = the regressed one) —
    the SAME differential the alert-triggered forensics report writes,
    so the gate's "why" and the live incident's "why" read alike.
    Ranks with no attributable window on either side are skipped."""
    from sparkdl_tpu.observe import perf

    base_w = _explain_windows(base_path)
    cand_w = _explain_windows(cand_path)
    out = {}
    for rank_s in sorted(set(base_w) & set(cand_w),
                         key=lambda r: (len(r), r)):
        diff = perf.diff_attribution(base_w[rank_s], cand_w[rank_s])
        if diff is not None:
            out[rank_s] = diff
    return out


# -- comparison --------------------------------------------------------------


def compare_records(base, cand, *, floor=0.05, iqr_k=1.0, only=None):
    """Metric-by-metric verdicts over the intersection of the two
    sides. Returns ``{"metrics": [...], "regressions": n,
    "improvements": n, "cross_host": bool}``."""
    bm, cm = base["metrics"], cand["metrics"]
    names = sorted(set(bm) & set(cm))
    if only:
        names = [n for n in names if n in only]
    rows = []
    regressions = improvements = 0
    for name in names:
        b, c = bm[name], cm[name]
        bv, basis_b = _effective_value(b)
        cv, basis_c = _effective_value(c)
        hib = _higher_is_better(
            name, b.get("higher_is_better", c.get("higher_is_better")))
        if bv == 0:
            continue
        delta = (cv - bv) / abs(bv)
        if not hib:
            delta = -delta
        noise = max(_rel_iqr(b.get("samples")), _rel_iqr(c.get("samples")))
        thr = max(floor, iqr_k * noise)
        status = ("regression" if delta < -thr
                  else "improved" if delta > thr else "ok")
        if status == "regression":
            regressions += 1
        elif status == "improved":
            improvements += 1
        rows.append({
            "metric": name,
            "base": bv,
            "candidate": cv,
            "basis": (basis_b if basis_b == basis_c
                      else f"{basis_b}/{basis_c}"),
            "delta": delta,
            "threshold": thr,
            "noise": noise,
            "higher_is_better": hib,
            "status": status,
        })
    cross = bool(base.get("host") and cand.get("host")
                 and base["host"] != cand["host"])
    return {
        "metrics": rows,
        "regressions": regressions,
        "improvements": improvements,
        "cross_host": cross,
        "base_host": base.get("host"),
        "candidate_host": cand.get("host"),
    }


def render_text(report):
    lines = []
    for r in report["metrics"]:
        arrow = {"regression": "REGRESSION", "improved": "improved",
                 "ok": "ok"}[r["status"]]
        noise_note = (", rel-IQR %.1f%%" % (r["noise"] * 100)
                      if r["noise"] > 0 else "")
        lines.append(
            "%-52s %14.4g -> %-14.4g %+7.2f%% (thr %.1f%%%s) %s"
            % (r["metric"], r["base"], r["candidate"],
               r["delta"] * 100, r["threshold"] * 100, noise_note,
               arrow))
    if not report["metrics"]:
        lines.append("compare: no common metrics between the two records")
    if report["cross_host"]:
        lines.append(
            f"NOTE: cross-host comparison ({report['base_host']} vs "
            f"{report['candidate_host']}) — verdicts are advisory "
            "unless --strict-host")
    lines.append(
        f"summary: {len(report['metrics'])} compared, "
        f"{report['regressions']} regression(s), "
        f"{report['improvements']} improvement(s)")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.observe.compare",
        description="Noise-aware perf comparison; exits 1 on "
                    "regression, 2 when nothing was comparable.",
    )
    parser.add_argument("base", help="baseline: bench JSON, "
                        "BASELINE.json, history.jsonl[@IDX], or run dir")
    parser.add_argument("candidate", help="candidate record (same forms)")
    parser.add_argument("--metric", action="append", default=None,
                        help="restrict to this metric (repeatable)")
    parser.add_argument("--floor", type=float, default=0.05,
                        help="minimum relative regression threshold "
                        "(default 0.05 = 5%%)")
    parser.add_argument("--iqr-k", type=float, default=1.0,
                        help="noise multiplier over rel-IQR of rep "
                        "samples (default 1.0)")
    parser.add_argument("--strict-host", action="store_true",
                        help="enforce regressions even across "
                        "different host fingerprints")
    parser.add_argument("--explain", action="store_true",
                        help="on a failing verdict between two run "
                        "dirs, append the per-rank differential step "
                        "attribution (why the candidate is slower)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    args = parser.parse_args(argv)

    base = load_record(args.base)
    cand = load_record(args.candidate)
    report = compare_records(
        base, cand, floor=args.floor, iqr_k=args.iqr_k,
        only=set(args.metric) if args.metric else None,
    )
    # ONE machine-readable verdict (the autotuner and CI consume the
    # same judge the humans read): per-metric rows already carry
    # base/candidate medians, delta, threshold, direction and status;
    # the top level names the gate's own decision and the exit code it
    # implies, so a JSON consumer never re-derives the cross-host /
    # no-overlap rules from the numbers.
    if not report["metrics"]:
        decision, rc = "no-overlap", 2
    elif report["regressions"] == 0:
        decision, rc = "ok", 0
    elif report["cross_host"] and not args.strict_host:
        decision, rc = "regression-advisory", 0
    else:
        decision, rc = "regression", 1
    report.update({"decision": decision, "exit_code": rc,
                   "floor": args.floor, "iqr_k": args.iqr_k,
                   "strict_host": bool(args.strict_host)})
    explain = None
    if (args.explain and decision != "ok"
            and os.path.isdir(args.base)
            and os.path.isdir(args.candidate)):
        explain = explain_run_dirs(args.base, args.candidate)
        report["explain"] = explain
    if args.format == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        text = render_text(report)
        if explain:
            from sparkdl_tpu.observe.perf import render_diff_lines

            lines = ["why (differential step attribution, base -> "
                     "candidate):"]
            for rank_s, diff in explain.items():
                lines.append(f"  rank {rank_s}:")
                lines.extend(render_diff_lines(diff, indent="    "))
            text += "\n" + "\n".join(lines)
        elif explain is not None:
            text += ("\nwhy: no attributable step windows on both "
                     "sides — nothing to diff")
        print(text)
    return rc


if __name__ == "__main__":
    sys.exit(main())
