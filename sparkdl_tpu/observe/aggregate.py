"""Driver-side gang telemetry aggregation.

Workers flush ``TELEMETRY`` control-plane frames (cumulative metric
snapshots + drained timeline events, see
:meth:`sparkdl_tpu.horovod.control_plane.ControlPlaneClient.
send_telemetry`); the :class:`ControlPlaneServer` hands each decoded
payload to :meth:`GangTelemetry.ingest`. At the end of a supervised
launch — success, exhaustion, or permanent failure — the launcher
calls :meth:`GangTelemetry.write`, which folds in the DRIVER's own
registry/timeline (supervisor attempts, backoff, slot claims,
rendezvous) and writes one merged view:

- ``timeline.json`` — Chrome trace-event JSON: lane 0 is the driver,
  lane ``rank+1`` is each worker rank (labeled with host), so a chaos
  run reads as one story in Perfetto: kill at step N → classified
  transient → backoff → resume from checkpoint.
- ``metrics.prom`` — Prometheus text format, every series labeled
  ``rank="N"`` (driver series ``rank="driver"``). Counters and
  histograms sum across a rank's process incarnations (supervised
  relaunches reset in-process values); gauges take the newest.
- ``metrics.json`` — the same series as one JSON document.

Ingest is called from control-plane connection threads (one per
worker) while ``write`` runs on the driver main thread after the gang
drained — one lock covers both.
"""

import collections
import json
import os
import threading

from sparkdl_tpu.observe.metrics import (
    ensure_build_info,
    merge_snapshots,
    render_json,
    render_prometheus,
    snapshot_delta,
)
from sparkdl_tpu.observe.timeline import chrome_trace

TIMELINE_FILE = "timeline.json"
PROM_FILE = "metrics.prom"
JSON_FILE = "metrics.json"
HEALTH_FILE = "health.json"
PERF_FILE = "perf.json"
COMMS_FILE = "comms_report.json"
FIXIT_FILE = "fixit_report.json"
ALERTS_FILE = "alerts.json"
ELASTIC_FILE = "elastic.json"
REGRESSION_FILE = "regression_report.json"

# Live event journal bound: the statusz SSE tail and the alert
# engine's rolling windows only ever need the recent past, so the
# journal is a ring — old events fall off, the write()-time artifacts
# (which keep everything) are unaffected.
JOURNAL_CAP = 8192

# perf.json keeps the newest per-step attribution rows up to this cap
# (the aggregate components cover the whole run either way) so a
# week-long job's artifact stays readable.
PERF_MAX_STEP_ROWS = 200

DRIVER_LABEL = "driver"


class GangTelemetry:
    """Accumulates one gang launch's telemetry (all attempts)."""

    def __init__(self):
        from sparkdl_tpu import observe

        self._lock = threading.Lock()
        self._snaps = {}    # (rank, pid) -> latest cumulative snapshot
        self._events = {}   # rank -> [event, ...]
        self._hosts = {}    # rank -> host
        self._stack_dumps = {}      # rank -> [(reason, dump), ...]
        self._job_dirs = []         # one per attempt (flight-rec scan)
        self._health_summaries = [] # one HangDetector summary/attempt
        self._comms_reports = []    # static comms budgets (pre-flight)
        self._fixit_reports = []    # verified fixit reports (pre-flight)
        self._alert_reports = []    # one alert-engine report per attempt
        self._elastic_reports = []  # elastic-controller decision logs
        self._regression_reports = []  # perf-forensics diff entries
        # Live journal: every ingested worker event, in arrival order,
        # with a monotonically increasing seq — the feed behind the
        # statusz `/events` SSE tail and the alert engine's rolling
        # step-time window. Ring-bounded; write()'s artifacts read the
        # full per-rank event lists, not this.
        self._journal = collections.deque(maxlen=JOURNAL_CAP)
        self._journal_seq = 0
        # The driver's global registry outlives launches (a notebook
        # driver runs many); baseline it NOW so write() reports only
        # THIS launch's driver-side movement. Worker snapshots need no
        # baseline — every launch spawns fresh processes.
        self._driver_base = observe.metrics().snapshot()

    def ingest(self, rank, payload):
        """Absorb one worker flush (thread-safe; latest snapshot from
        a given (rank, pid) supersedes its previous one — snapshots
        are cumulative — while events only ever append)."""
        rank = int(rank)
        metrics = payload.get("metrics")
        if metrics:
            self._validate_snapshot(metrics)
        events = payload.get("events") or ()
        with self._lock:
            if metrics:
                self._snaps[(rank, payload.get("pid"))] = metrics
            if events:
                fresh = [e for e in events if isinstance(e, dict)]
                self._events.setdefault(rank, []).extend(fresh)
                for e in fresh:
                    self._journal_seq += 1
                    self._journal.append((self._journal_seq, rank, e))
            host = payload.get("host")
            if host:
                self._hosts[rank] = str(host)

    def add_stack_dump(self, rank, dump, reason=None):
        """A worker answered a hang-diagnosis dump request: keep the
        text for the run dir (``stack-rank-<r>.txt``) — the evidence
        ``observe.doctor`` names the stalled frame from."""
        with self._lock:
            self._stack_dumps.setdefault(int(rank), []).append(
                (str(reason or "requested"), str(dump))
            )

    def note_job_dir(self, job_dir):
        """Register one attempt's job dir so ``write`` can recover
        flight-recorder tails from it — including from ranks that were
        SIGKILLed before their final telemetry flush."""
        with self._lock:
            if job_dir and job_dir not in self._job_dirs:
                self._job_dirs.append(job_dir)

    def add_health_summary(self, summary):
        """One attempt's :meth:`HangDetector.summary` (written to
        ``health.json`` — what the doctor reproduces verdicts from)."""
        if summary:
            with self._lock:
                self._health_summaries.append(summary)

    def add_comms_reports(self, reports):
        """Static comms budgets the launcher pre-flight priced
        (:func:`sparkdl_tpu.analysis.comms.comms_report`) — written to
        ``comms_report.json`` so ``observe.doctor`` can set predicted
        bytes-on-the-wire against the measured
        ``collective_bytes_total`` counters."""
        with self._lock:
            self._comms_reports.extend(
                r for r in reports if isinstance(r, dict)
            )

    def add_fixit_reports(self, reports):
        """Fixit reports the launcher pre-flight produced
        (:func:`sparkdl_tpu.analysis.fixes.fix_program` with
        ``SPARKDL_TPU_PREFLIGHT_FIX=1``) — written to
        ``fixit_report.json`` so ``observe.doctor`` can render the
        suggested/applied fixes (and their four proofs) for the run."""
        with self._lock:
            self._fixit_reports.extend(
                r for r in reports if isinstance(r, dict)
            )

    def add_alert_report(self, report):
        """One alert-engine report per supervised attempt (each
        attempt constructs its own engine). Reports ACCUMULATE like
        health summaries — a regression that fired on attempt 1 must
        survive a clean attempt 2 into ``alerts.json`` — and write()
        merges them: newest config, every attempt's firings. Written
        even when no rule fired, so a clean run's artifact proves the
        rules were evaluated and found nothing (the false-positive
        guard is auditable, not just absent)."""
        if isinstance(report, dict):
            with self._lock:
                self._alert_reports.append(report)

    def add_elastic_report(self, report):
        """The elastic controller's decision log (ISSUE 16) — one
        report per supervised launch (the controller spans attempts),
        written to ``elastic.json`` so every grow/yield/reclaim
        decision is auditable from the run dir and ``observe.doctor``
        can render the decision history post-hoc."""
        if isinstance(report, dict):
            with self._lock:
                self._elastic_reports.append(report)

    def add_regression_report(self, entry):
        """One perf-forensics entry from the driver-side forensics
        manager (:mod:`sparkdl_tpu.observe.forensics`): the
        ``diff_attribution`` document for a fired perf alert plus the
        trigger/capture metadata. Entries accumulate across attempts
        like alert reports and are written to
        ``regression_report.json`` beside ``alerts.json``."""
        if isinstance(entry, dict):
            with self._lock:
                self._regression_reports.append(entry)

    # -- live views (statusz / alert engine) ---------------------------------

    def events_since(self, seq=0, limit=None):
        """Journal entries newer than ``seq``: ``(newest_seq,
        [(seq, rank, event), ...])`` — the statusz SSE tail's poll
        unit. ``limit`` caps one batch so a slow client never makes
        the handler build an 8k-event payload. Seqs increase with
        deque order, so the scan walks from the RIGHT and stops at
        the first already-seen entry — an idle poll (the common case,
        2x/sec per SSE client) is O(1) under the same lock every
        worker telemetry flush needs."""
        out = []
        with self._lock:
            newest = self._journal_seq
            for entry in reversed(self._journal):
                if entry[0] <= seq:
                    break
                out.append(entry)
        out.reverse()
        if limit is not None:
            out = out[:int(limit)]
        return newest, out

    def recent_events(self, window_s, now=None):
        """``{rank: [event, ...]}`` for journal events whose wall-clock
        ``ts`` falls inside the trailing ``window_s`` seconds — the
        rolling window the live attribution (statusz) and the
        step-time regression rule (alerts) are computed over."""
        import time as _time

        now = _time.time() if now is None else now
        cutoff = (now - float(window_s)) * 1e6
        out = {}
        with self._lock:
            entries = list(self._journal)
        for _seq, rank, e in entries:
            ts = e.get("ts")
            if isinstance(ts, (int, float)) and ts >= cutoff:
                out.setdefault(rank, []).append(e)
        return out

    def live_labeled(self):
        """The labeled merged snapshots as they stand NOW — the same
        shape ``write`` renders, driver series included (delta'd
        against the construction baseline), but non-destructive: no
        timeline drain, no file writes. The statusz ``GET /metrics``
        body is ``render_prometheus(live_labeled())``."""
        from sparkdl_tpu import observe

        registry = observe.metrics()
        ensure_build_info(registry)
        driver_snap = snapshot_delta(
            self._driver_base, registry.snapshot())
        return self._merged(driver_snap)

    @staticmethod
    def _validate_snapshot(snap):
        # Frames come off the wire: shape-check EVERYTHING the merge
        # and render math will touch, before any of it is stored — a
        # malformed frame must cost one frame (control plane logs and
        # drops it), never detonate later in write() and cost every
        # rank's artifacts.
        num = (int, float)
        for key in ("counters", "gauges", "histograms"):
            for s in snap.get(key, ()):
                if not isinstance(s.get("name"), str) or not isinstance(
                    s.get("labels", {}), dict
                ):
                    raise ValueError(f"malformed metric series: {s!r}")
                if key != "histograms":
                    if not isinstance(s.get("value"), num):
                        raise ValueError(
                            f"malformed metric series: {s!r}")
                    continue
                buckets, counts = s.get("buckets"), s.get("counts")
                if (
                    not isinstance(buckets, list)
                    or not isinstance(counts, list)
                    or len(counts) != len(buckets) + 1
                    or not all(isinstance(b, num) for b in buckets)
                    or not all(isinstance(c, num) for c in counts)
                    or not isinstance(s.get("sum"), num)
                    or not isinstance(s.get("count"), num)
                ):
                    raise ValueError(f"malformed histogram: {s!r}")

    # -- merged views --------------------------------------------------------

    def _merged(self, driver_snapshot=None):
        """``[(extra_labels, merged_snapshot), ...]`` — one entry per
        rank plus the driver's."""
        with self._lock:
            by_rank = {}
            for (rank, _pid), snap in sorted(self._snaps.items()):
                by_rank.setdefault(rank, []).append(snap)
        out = []
        if driver_snapshot is not None:
            out.append(({"rank": DRIVER_LABEL}, driver_snapshot))
        for rank in sorted(by_rank):
            out.append(
                ({"rank": str(rank)}, merge_snapshots(by_rank[rank]))
            )
        return out

    def chrome(self, driver_events=()):
        with self._lock:
            ranks = sorted(self._events)
            groups = [(0, DRIVER_LABEL, list(driver_events))] + [
                (
                    rank + 1,
                    f"rank {rank}"
                    + (f" @ {self._hosts[rank]}"
                       if rank in self._hosts else ""),
                    list(self._events[rank]),
                )
                for rank in ranks
            ]
        return chrome_trace(groups)

    def write(self, out_dir, driver_registry=None, driver_timeline=None):
        """Write the merged artifacts. Defaults to the process-global
        driver registry/timeline (draining the timeline). Writes are
        atomic (tmp + rename) so a watcher — or the CI artifact check
        — never reads a half-written file. Returns the paths."""
        from sparkdl_tpu import observe

        if driver_registry is None:
            # The baseline only describes the process-global registry;
            # an explicitly passed registry is the caller's own and is
            # reported as-is. The build-info stamp rides the driver
            # series so run-dir scrape joins on git sha even when no
            # worker snapshot carried one.
            registry = observe.metrics()
            ensure_build_info(registry)
            driver_snap = snapshot_delta(
                self._driver_base, registry.snapshot()
            )
        else:
            driver_snap = driver_registry.snapshot()
        if driver_timeline is None:
            driver_timeline = observe.timeline()
        os.makedirs(out_dir, exist_ok=True)
        labeled = self._merged(driver_snap)
        trace = self.chrome(driver_timeline.drain())
        files = [
            (TIMELINE_FILE, json.dumps(trace)),
            (PROM_FILE, render_prometheus(labeled)),
            (JSON_FILE, render_json(labeled, indent=2)),
        ]
        # Per-rank step-time attribution (observe.perf): where each
        # rank's step wall time went — compute vs collective vs host
        # vs data wait vs checkpoint — plus overlap efficiency.
        # Written only when at least one rank recorded step spans
        # (serving run dirs have none).
        from sparkdl_tpu.observe import perf as _perf

        with self._lock:
            rank_events = {r: list(evs)
                           for r, evs in self._events.items()}
        perf_ranks = {}
        for rank in sorted(rank_events):
            report = _perf.attribution_report(rank_events[rank])
            if not report.get("steps"):
                continue
            per_step = report.get("per_step") or []
            if len(per_step) > PERF_MAX_STEP_ROWS:
                report["per_step"] = per_step[-PERF_MAX_STEP_ROWS:]
                report["per_step_truncated"] = (
                    len(per_step) - PERF_MAX_STEP_ROWS)
            perf_ranks[str(rank)] = report
        if perf_ranks:
            files.append((PERF_FILE, json.dumps(
                {"schema": _perf.BREAKDOWN_SCHEMA, "ranks": perf_ranks},
                indent=2)))
        with self._lock:
            dumps = {r: list(d) for r, d in self._stack_dumps.items()}
            job_dirs = list(self._job_dirs)
            health = list(self._health_summaries)
            comms = list(self._comms_reports)
            fixit = list(self._fixit_reports)
            alert_reports = list(self._alert_reports)
            elastic_reports = list(self._elastic_reports)
            regression_reports = list(self._regression_reports)
        if elastic_reports:
            # Same merge shape as alerts: newest config/state wins,
            # decisions concatenate across reports.
            merged = dict(elastic_reports[-1])
            merged["decisions"] = [d for rep in elastic_reports
                                   for d in rep.get("decisions", ())]
            merged["reports"] = len(elastic_reports)
            files.append((ELASTIC_FILE, json.dumps(merged, indent=2)))
        if alert_reports:
            # Merge across attempts: newest report's config (rules,
            # window — they only change with env, but the last attempt
            # is the authoritative run state), CONCATENATED firings.
            merged = dict(alert_reports[-1])
            merged["alerts"] = [a for rep in alert_reports
                                for a in rep.get("alerts", ())]
            merged["attempts"] = len(alert_reports)
            files.append((ALERTS_FILE, json.dumps(merged, indent=2)))
        if regression_reports:
            files.append((REGRESSION_FILE, json.dumps(
                {"schema": _perf.REGRESSION_SCHEMA,
                 "reports": regression_reports}, indent=2)))
        if comms:
            files.append((COMMS_FILE, json.dumps(
                {"reports": comms}, indent=2)))
        if fixit:
            files.append((FIXIT_FILE, json.dumps(
                {"reports": fixit}, indent=2)))
        # Stack dumps from hang diagnosis: one text file per rank (a
        # rank dumped more than once — e.g. stall then hang — keeps
        # every dump, separated).
        for rank in sorted(dumps):
            text = "\n".join(
                f"==== stack dump (reason: {reason}) ====\n{dump}"
                for reason, dump in dumps[rank]
            )
            files.append((f"stack-rank-{rank}.txt", text))
        # Flight-recorder tails: recovered from every attempt's job
        # dir — this is the only record of a rank SIGKILLed between
        # telemetry flushes (chaos kills, the launcher reaping a hung
        # gang). Recovery failures are skipped, never fatal: the main
        # artifacts must still land.
        from sparkdl_tpu.observe.flightrec import recover_job_dir

        tails = {}
        for job_dir in job_dirs:
            for rank, events in recover_job_dir(job_dir).items():
                tails.setdefault(rank, []).extend(events)
        for rank in sorted(tails):
            files.append((
                f"flightrec-rank-{rank}.json",
                json.dumps({"rank": rank, "events": tails[rank]}),
            ))
        # OOM reports: workers write oom_report*.json into their job
        # dir (the only directory a gang worker is guaranteed to own);
        # copy them into the merged run dir where the doctor looks.
        # Same never-fatal stance as flight-ring recovery.
        import glob as _glob

        for job_dir in job_dirs:
            try:
                reports = _glob.glob(os.path.join(job_dir, "oom_report*.json"))
            except Exception:
                continue
            for src in sorted(reports):
                try:
                    with open(src) as f:
                        files.append((os.path.basename(src), f.read()))
                except Exception:
                    continue
        # Perf-forensics evidence: capture services write
        # profile_report-rank-*.json (uncapped attribution windows)
        # and xprof-rank-*/ trace dirs into their job dir; recover
        # both into the merged run dir where the doctor (and an
        # operator's tensorboard) look. Same never-fatal stance.
        trace_dirs = []
        for job_dir in job_dirs:
            try:
                reports = _glob.glob(
                    os.path.join(job_dir, "profile_report*.json"))
                trace_dirs.extend(
                    _glob.glob(os.path.join(job_dir, "xprof-rank-*")))
            except Exception:
                continue
            for src in sorted(reports):
                try:
                    with open(src) as f:
                        files.append((os.path.basename(src), f.read()))
                except Exception:
                    continue
        if health:
            files.append(
                (HEALTH_FILE, json.dumps({"attempts": health}, indent=2))
            )
        paths = {}
        for name, text in files:
            path = os.path.join(out_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
            paths[name] = path
        import shutil as _shutil

        for src in sorted(trace_dirs):
            if not os.path.isdir(src):
                continue
            dst = os.path.join(out_dir, os.path.basename(src))
            try:
                _shutil.copytree(src, dst, dirs_exist_ok=True)
                paths[os.path.basename(src)] = dst
            except Exception:
                continue
        return paths
