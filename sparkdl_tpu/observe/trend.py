"""``python -m sparkdl_tpu.observe.trend`` — the perf-ledger trend
viewer.

``benchmarks/results/history.jsonl`` (PR 7's regression ledger) is
the repo's perf memory, but its trajectory was invisible except by
hand-reading JSONL. This renders it as one per-metric trajectory
table: every record's git sha, p50/p99 (or raw value), and the
relative delta vs the previous record of the SAME metric — so "how
did the cpu-proxy headline move across the last five PRs" is one
command, and the committed baselines (``BASELINE.json`` published
map, ``benchmarks/results/serve_baseline.json``) render beside the
trajectory for at-a-glance drift.

Direction-aware deltas: lower-is-better metrics (latency shapes, the
same hints :mod:`sparkdl_tpu.observe.compare` uses) mark a decrease
as improvement. ``--format json`` is the machine contract for CI
(the statusz smoke asserts its own ledger line renders).

Artifact-only, jax-free: a copied ledger renders anywhere.
"""

import argparse
import json
import os
import sys

from sparkdl_tpu.observe.compare import _higher_is_better
from sparkdl_tpu.observe.perf import default_history_path, read_history

TREND_SCHEMA = "sparkdl_tpu.observe.trend/1"


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_paths():
    root = _repo_root()
    return [
        os.path.join(root, "BASELINE.json"),
        os.path.join(root, "benchmarks", "results",
                     "serve_baseline.json"),
    ]


def load_baselines(paths):
    """``{metric: {"value": v, "source": basename}}`` from committed
    baseline docs. Two committed shapes exist: ``BASELINE.json``'s
    ``published`` map (private ``_``-prefixed and non-numeric entries
    skipped) and ``serve_baseline.json``'s history-record shape (a
    ``metrics`` map of name → ``{"value": ...}`` — the ledger line
    that was promoted to baseline). Missing/unreadable files are
    silently absent — baselines decorate the trajectory, they don't
    gate it."""
    out = {}
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        flat = {}
        for name, v in (doc.get("published") or {}).items():
            if not name.startswith("_"):
                flat[name] = v
        for name, m in (doc.get("metrics") or {}).items():
            flat[name] = m.get("value") if isinstance(m, dict) else m
        for name, v in flat.items():
            if not isinstance(v, (int, float)):
                continue
            out.setdefault(name, {
                "value": float(v),
                "source": os.path.basename(path),
            })
    return out


def build_trend(entries, baselines=None, only=None, last=None):
    """The trend document: per-metric rows (oldest first), each row
    carrying ts/git_sha/bench/value/p50/p99/unit and
    ``delta_vs_prev`` (relative, direction-adjusted so positive =
    improvement), plus the committed baseline when one names the
    metric."""
    by_metric = {}
    for idx, entry in enumerate(entries):
        for name, m in (entry.get("metrics") or {}).items():
            if only and not any(s in name for s in only):
                continue
            if not isinstance(m, dict):
                m = {"value": m}
            value = m.get("value")
            if not isinstance(value, (int, float)):
                continue
            by_metric.setdefault(name, []).append({
                "index": idx,
                "ts": entry.get("ts"),
                "git_sha": entry.get("git_sha"),
                "bench": entry.get("bench"),
                "host": entry.get("host"),
                "device_kind": entry.get("device_kind"),
                "value": float(value),
                "p50": m.get("p50"),
                "p99": m.get("p99"),
                "unit": m.get("unit"),
                "higher_is_better": m.get("higher_is_better"),
            })
    metrics = {}
    baselines = baselines or {}
    for name in sorted(by_metric):
        rows = by_metric[name]
        if last:
            rows = rows[-last:]
        hib = _higher_is_better(
            name, next((r["higher_is_better"] for r in rows
                        if r["higher_is_better"] is not None), None))
        prev = None
        for row in rows:
            if prev not in (None, 0):
                delta = (row["value"] - prev) / abs(prev)
                row["delta_vs_prev"] = delta if hib else -delta
            else:
                row["delta_vs_prev"] = None
            prev = row["value"]
        entry = {"higher_is_better": hib, "records": rows}
        if name in baselines:
            entry["baseline"] = baselines[name]
            newest = rows[-1]["value"]
            base = baselines[name]["value"]
            if base:
                d = (newest - base) / abs(base)
                entry["newest_vs_baseline"] = d if hib else -d
        metrics[name] = entry
    return {"schema": TREND_SCHEMA, "metrics": metrics,
            "records_total": len(entries)}


def _fmt_delta(d):
    if d is None:
        return "      -"
    return f"{d * 100:+6.1f}%"


def render_text(trend):
    lines = []
    if not trend["metrics"]:
        lines.append("trend: no ledger records"
                     + (f" (of {trend['records_total']} entries, none "
                        "matched)" if trend["records_total"] else ""))
        return "\n".join(lines)
    for name, entry in trend["metrics"].items():
        direction = ("higher is better" if entry["higher_is_better"]
                     else "lower is better")
        unit = next((r["unit"] for r in entry["records"]
                     if r.get("unit")), None)
        lines.append(f"{name} ({direction}"
                     + (f", {unit}" if unit else "") + ")")
        lines.append(f"  {'ts':<20} {'git sha':<10} {'value':>14} "
                     f"{'p50':>12} {'p99':>12} {'vs prev':>8}")
        for r in entry["records"]:
            lines.append(
                f"  {str(r.get('ts') or '-'):<20} "
                f"{str(r.get('git_sha') or '-'):<10} "
                f"{r['value']:>14.4g} "
                f"{(('%12.4g' % r['p50']) if isinstance(r.get('p50'), (int, float)) else '           -')} "
                f"{(('%12.4g' % r['p99']) if isinstance(r.get('p99'), (int, float)) else '           -')} "
                f"{_fmt_delta(r.get('delta_vs_prev'))}")
        base = entry.get("baseline")
        if base:
            line = (f"  committed baseline [{base['source']}]: "
                    f"{base['value']:.4g}")
            nvb = entry.get("newest_vs_baseline")
            if nvb is not None:
                line += f" (newest {_fmt_delta(nvb).strip()} vs it)"
            lines.append(line)
        lines.append("")
    return "\n".join(lines).rstrip()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.observe.trend",
        description="Render the perf ledger (history.jsonl) as "
                    "per-metric trajectory tables with deltas and "
                    "committed baselines.",
    )
    parser.add_argument("--history", default=None,
                        help="ledger path (default: the repo's "
                        "benchmarks/results/history.jsonl, or "
                        "SPARKDL_TPU_PERF_HISTORY)")
    parser.add_argument("--baseline", action="append", default=None,
                        help="committed baseline JSON (repeatable; "
                        "default: BASELINE.json + serve_baseline.json)")
    parser.add_argument("--metric", action="append", default=None,
                        help="restrict to metrics containing this "
                        "substring (repeatable; e.g. --metric serve "
                        "matches every serving series)")
    parser.add_argument("--last", type=int, default=None,
                        help="only the newest N records per metric")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    args = parser.parse_args(argv)

    history_path = args.history or default_history_path()
    entries = read_history(history_path)
    baselines = load_baselines(
        args.baseline if args.baseline else default_baseline_paths())
    trend = build_trend(
        entries, baselines=baselines,
        only=set(args.metric) if args.metric else None,
        last=args.last)
    trend["history_path"] = history_path
    if args.format == "json":
        print(json.dumps(trend, indent=2, sort_keys=True))
    else:
        print(render_text(trend))
    # 2 = nothing to show (CI treats an empty trend as a wiring bug).
    return 0 if trend["metrics"] else 2


if __name__ == "__main__":
    sys.exit(main())
