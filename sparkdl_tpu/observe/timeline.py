"""Structured event timeline: typed spans and instants with rank/host
attribution, exported as Chrome trace-event JSON.

Events are recorded per process (zero-dep, thread-safe, append-only)
and drained in batches — workers ship them to the driver over the
control plane, where :mod:`sparkdl_tpu.observe.aggregate` merges every
rank into ONE Chrome trace (``timeline.json``) that opens directly in
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``, alongside
the per-rank xprof traces from :mod:`sparkdl_tpu.utils.profiler`
(:func:`~sparkdl_tpu.utils.profiler.annotate` emits the SAME region
name into both, so the two views correlate 1:1).

Event shape (Chrome trace-event format, the subset Perfetto renders):

- spans:    ``{"ph": "X", "name", "cat", "ts", "dur", "tid", "args"}``
- instants: ``{"ph": "i", "name", "cat", "ts", "s": "p", "tid", "args"}``

``ts``/``dur`` are integer microseconds. ``ts`` is wall-clock
(``time.time``) so events from different processes on a gang's hosts
merge onto one comparable axis; ``dur`` is measured with the monotonic
``perf_counter`` so spans never go negative under clock slew. ``pid``
is deliberately absent here: the merger assigns one pid lane per rank
(driver = lane 0) with ``process_name`` metadata, which is what makes
the merged trace read as a gang-wide story rather than a pile of OS
pids.
"""

import contextlib
import threading
import time


def _tid():
    # Chrome trace tids are int32-ish; Python thread idents can exceed
    # that on 64-bit Linux. Fold, keeping same-thread stability.
    return threading.get_ident() & 0x7FFFFFFF


class Timeline:
    """Append-only per-process event buffer."""

    def __init__(self, clock=time.time, perf=time.perf_counter):
        self._clock = clock
        self._perf = perf
        self._lock = threading.Lock()
        self._events = []
        # Optional per-event mirror (the flight recorder): called with
        # each completed event OUTSIDE the buffer lock, must not raise.
        self.observer = None

    def _mirror(self, ev):
        obs = self.observer
        if obs is not None:
            try:
                obs(ev)
            except Exception:
                pass  # the mirror must never break recording

    def instant(self, name, cat="", tid=None, **args):
        """Record a point event (``ph: "i"``, process-scoped).

        ``tid`` overrides the recording thread's ident — lifecycles
        that span threads (a serving request crosses an HTTP handler
        and the engine thread) key their events on a logical id (the
        request id) so the tree renders as one track per request."""
        ev = {
            "name": name, "cat": cat or "event", "ph": "i",
            "ts": int(self._clock() * 1e6), "s": "p",
            "tid": _tid() if tid is None else int(tid),
            "args": args,
        }
        with self._lock:
            self._events.append(ev)
        self._mirror(ev)
        return ev

    def complete(self, name, start, dur, cat="", tid=None, **args):
        """Record a complete event (``ph: "X"``) with an EXPLICIT
        wall-clock ``start`` and ``dur`` (both seconds) — for spans
        whose endpoints were measured on different threads, where the
        :meth:`span` context manager cannot wrap the block."""
        ev = {
            "name": name, "cat": cat or "span", "ph": "X",
            "ts": int(start * 1e6), "dur": max(0, int(dur * 1e6)),
            "tid": _tid() if tid is None else int(tid), "args": args,
        }
        with self._lock:
            self._events.append(ev)
        self._mirror(ev)
        return ev

    @contextlib.contextmanager
    def span(self, name, cat="", **args):
        """Record a complete event (``ph: "X"``) around the block."""
        t0 = self._clock()
        p0 = self._perf()
        try:
            yield
        finally:
            ev = {
                "name": name, "cat": cat or "span", "ph": "X",
                "ts": int(t0 * 1e6),
                "dur": max(0, int((self._perf() - p0) * 1e6)),
                "tid": _tid(), "args": args,
            }
            with self._lock:
                self._events.append(ev)
            self._mirror(ev)

    def drain(self):
        """Pop and return all buffered events (the flush unit)."""
        with self._lock:
            events, self._events = self._events, []
        return events

    def __len__(self):
        with self._lock:
            return len(self._events)


def chrome_trace(groups):
    """Build one Chrome trace document from per-process event lists.

    ``groups``: iterable of ``(pid, label, events)`` — one trace
    process lane per logical gang member (the aggregator uses lane 0
    for the driver and lane ``rank + 1`` for each worker rank, labeled
    with rank and host). Events are sorted by ``ts`` so the file reads
    chronologically even before a viewer loads it.
    """
    out = []
    for pid, label, events in groups:
        out.append({
            "name": "process_name", "ph": "M", "pid": int(pid),
            "tid": 0, "ts": 0, "args": {"name": str(label)},
        })
        for ev in events:
            ev = dict(ev)
            ev["pid"] = int(pid)
            out.append(ev)
    # Metadata (ph: M) first, then chronological.
    out.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0)))
    return {"traceEvents": out, "displayTimeUnit": "ms"}
