"""``observe.perf``: step-time attribution, roofline/MFU accounting,
and the perf-regression ledger the compare gate reads.

ROADMAP item 3 says perf claims must be *measured, not asserted* — but
until this module the telemetry stack could only show raw
compile/execute histograms. Three pieces close the gap:

1. **Attribution** (:func:`attribution_report`): derive a per-step
   wall-time breakdown — ``compute`` / ``collective`` /
   ``host_callback`` / ``data_wait`` / ``checkpoint`` — from the spans
   the timeline already carries (``instrument_step`` step spans, the
   ``@_observed`` collective spans, checkpoint save/restore spans, the
   input pipeline's ``data.wait``). Pure interval arithmetic over
   drained/merged events: no jax, artifact-only, so the same math runs
   driver-side at :meth:`GangTelemetry.write` (→ ``perf.json``) and in
   ``observe.doctor`` on a laptop.

   The **overlap-efficiency** metric is the before/after number for
   the async-collective work: a collective span recorded on the step
   thread *blocks* it (serialized); one recorded on another thread
   while the step thread is not inside any instrumented wait is
   *overlapped with compute*. ``overlap_efficiency = overlapped
   collective time / total collective time`` — 0.0 for barrier-style
   ops on the step thread, > 0 once collectives ride
   ``hvd.allreduce_async``'s dispatch thread under compute (the ISSUE
   10 overlap arc; ``tests/observe/test_overlap_gang.py`` pins the
   ring-attention step above zero).
   Component seconds are *step-thread wall time*, so they sum to the
   step span's duration by construction (overlapped collective time is
   concurrent and reported separately).

2. **Roofline/MFU accounting**: :func:`register_step_cost` stores one
   executable's FLOPs/bytes (from the
   :func:`~sparkdl_tpu.utils.jax_compat.cost_analysis` /
   :func:`~sparkdl_tpu.utils.jax_compat.memory_analysis` shims —
   ``None`` on runtimes without a cost model, never an error) and
   :func:`note_step` divides them by each executed step's wall time
   into ``achieved_flops_per_sec`` / ``achieved_bytes_per_sec``
   gauges, plus ``mfu`` and ``membw_util`` against ONE per-device-kind
   peak table (:data:`PEAK_TABLE` — v4/v5e/v5p plus a cpu proxy
   constant, both env-overridable). ``step_operational_intensity`` vs
   the device's ridge point says which roofline wall you are on.
   Everything is behind the PR-3 zero-overhead latch.

3. **Regression ledger** (:func:`history_record` /
   :func:`append_history`): every bench run appends one
   schema-versioned JSON line — git sha, host fingerprint, device
   kind, metrics with optional rep samples — to
   ``benchmarks/results/history.jsonl``, the file
   ``python -m sparkdl_tpu.observe.compare`` diffs with noise-aware
   thresholds. The ledger is the memory the CI perf gate enforces
   against; see :mod:`sparkdl_tpu.observe.compare`.

The single source of truth for chip peaks (the old per-file
``PEAK_FLOPS = 197e12`` copies assumed v5e forever): ``bench.py``,
``benchmarks/model_bench.py`` and ``benchmarks/step_breakdown.py`` all
import :func:`peak_flops` keyed off the *probed* device kind.
"""

import json
import os
import socket
import subprocess
import sys
import time

PEAK_FLOPS_ENV = "SPARKDL_TPU_PEAK_FLOPS"
PEAK_BYTES_ENV = "SPARKDL_TPU_PEAK_BYTES_PER_S"
PEAK_ICI_ENV = "SPARKDL_TPU_PEAK_ICI_BYTES_PER_S"
HBM_BYTES_ENV = "SPARKDL_TPU_HBM_BYTES"
HISTORY_ENV = "SPARKDL_TPU_PERF_HISTORY"

BREAKDOWN_SCHEMA = "sparkdl_tpu.perf.breakdown/1"
HISTORY_SCHEMA = 1

# Wall-time categories the attribution understands, in render order.
# ``compute`` is the remainder of the step span not covered by any
# instrumented wait on the step thread.
COMPONENTS = ("compute", "collective", "host_callback", "data_wait",
              "checkpoint")

# timeline span cat -> breakdown component
_CAT_TO_COMPONENT = {
    "collective": "collective",
    "host": "host_callback",
    "data": "data_wait",
    "checkpoint": "checkpoint",
}

# Dense bf16 peak FLOPs/s, HBM bytes/s, and aggregate ICI
# (inter-chip interconnect) bytes/s per chip, keyed by the normalized
# device kind (public TPU specs; ICI row = total off-chip link
# bandwidth per chip, the denominator the static comms budget divides
# wire bytes by). The ``cpu`` entry is a nominal proxy constant — a
# deviceless dev container has no honest peak, but the CPU-proxy
# trajectory still wants a stable denominator so its MFU-shaped gauge
# moves only when the code does. Override any axis with
# SPARKDL_TPU_PEAK_FLOPS / SPARKDL_TPU_PEAK_BYTES_PER_S /
# SPARKDL_TPU_PEAK_ICI_BYTES_PER_S.
PEAK_TABLE = {
    "v4": (275e12, 1.23e12, 3.0e11),    # 2400 Gbps ICI
    "v5e": (197e12, 0.82e12, 2.0e11),   # 1600 Gbps ICI
    "v5p": (459e12, 2.77e12, 6.0e11),   # 4800 Gbps ICI
    # Nominal many-core AVX f32 peak + DDR bandwidth + a loopback/
    # shared-memory "interconnect" proxy: generous enough that no real
    # CPU measurement crosses 1.0, stable enough that the proxy MFU
    # only moves when the code does.
    "cpu": (1e12, 2e11, 1e10),
}

# Per-chip HBM capacity in bytes (public TPU specs) — the denominator
# the hbm-overcommit analysis pass and the reshard-feasibility
# pre-flight compare static peak estimates against. ``cpu`` is None:
# host RAM is not a chip budget, so capacity checks are skipped there
# unless SPARKDL_TPU_HBM_BYTES pins one explicitly.
HBM_BYTES = {
    "v4": 32 * 2**30,
    "v5e": 16 * 2**30,
    "v5p": 95 * 2**30,
    "cpu": None,
}

# Unknown accelerator kinds fall back to the v5e figure — the constant
# every pre-perf.py copy of PEAK_FLOPS hard-coded, kept so MFU
# trajectories survive the refactor unchanged.
DEFAULT_KIND = "v5e"


def normalize_device_kind(kind):
    """Map a PJRT ``device_kind`` string (``"TPU v5 lite"``,
    ``"TPU v4"``, ``"cpu"``...) onto a :data:`PEAK_TABLE` key."""
    k = (kind or "").lower()
    if "v5p" in k:
        return "v5p"
    if "v5e" in k or "v5 lite" in k or "v5lite" in k:
        return "v5e"
    if "v4" in k:
        return "v4"
    if "cpu" in k:
        return "cpu"
    return DEFAULT_KIND


def device_kind():
    """The probed device kind of this process's first jax device, or
    ``None`` when jax was never imported. Same no-import rule as the
    heartbeat's memory gauges: a telemetry path must never be the
    thing that initializes a backend."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        d = jax.devices()[0]
        return getattr(d, "device_kind", "") or d.platform
    except Exception:
        return None


def peak_flops(kind=None):
    """Peak FLOPs/s for ``kind`` (a raw ``device_kind`` string; default
    = the probed one). ``SPARKDL_TPU_PEAK_FLOPS`` overrides any kind —
    the pre-existing contract every bench honored."""
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        return float(env)
    return PEAK_TABLE[normalize_device_kind(kind or device_kind())][0]


def peak_bytes_per_sec(kind=None):
    """Peak HBM bytes/s for ``kind`` (env-overridable, like
    :func:`peak_flops`)."""
    env = os.environ.get(PEAK_BYTES_ENV)
    if env:
        return float(env)
    return PEAK_TABLE[normalize_device_kind(kind or device_kind())][1]


def peak_interconnect_bytes_per_sec(kind=None):
    """Aggregate per-chip ICI bytes/s for ``kind`` — the denominator
    the static comms budget (:mod:`sparkdl_tpu.analysis.comms`) turns
    wire bytes into predicted seconds with. Env-overridable via
    ``SPARKDL_TPU_PEAK_ICI_BYTES_PER_S``."""
    env = os.environ.get(PEAK_ICI_ENV)
    if env:
        return float(env)
    return PEAK_TABLE[normalize_device_kind(kind or device_kind())][2]


def hbm_capacity_bytes(kind=None):
    """Per-chip HBM capacity in bytes for ``kind``, or ``None`` when
    the kind has no chip budget (cpu). ``SPARKDL_TPU_HBM_BYTES``
    overrides any kind — the knob an operator with a nonstandard
    memory config (or a cpu rig that wants the overcommit pass live)
    pins."""
    env = os.environ.get(HBM_BYTES_ENV)
    if env:
        return float(env)
    return HBM_BYTES[normalize_device_kind(kind or device_kind())]


# -- step-time attribution ---------------------------------------------------


def _union(intervals):
    """Merge ``[(lo, hi), ...]`` into disjoint intervals."""
    out = []
    for lo, hi in sorted(intervals):
        if hi <= lo:
            continue
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _measure(intervals):
    return sum(hi - lo for lo, hi in intervals)


def _clip(intervals, lo, hi):
    return [(max(a, lo), min(b, hi)) for a, b in intervals
            if min(b, hi) > max(a, lo)]


def _intersect(a, b):
    """Intersection of two DISJOINT-SORTED interval lists."""
    out, i, j = [], 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _subtract(a, b):
    """Interval list ``a`` minus union-list ``b`` (both disjoint
    sorted)."""
    out = []
    for lo, hi in a:
        cur = lo
        for blo, bhi in b:
            if bhi <= cur or blo >= hi:
                continue
            if blo > cur:
                out.append((cur, blo))
            cur = max(cur, bhi)
            if cur >= hi:
                break
        if cur < hi:
            out.append((cur, hi))
    return out


def step_breakdown(events, step_cat="train"):
    """Per-step wall-time attribution over raw timeline events (the
    merged-trace or drained-worker event dicts; ``ts``/``dur`` integer
    microseconds).

    Returns one dict per *execute-phase* step span (``cat ==
    step_cat``, ``ph == "X"``; the ``phase="compile"`` first-call span
    is excluded — compile wall time is not compute) in timestamp
    order::

        {"step": int|None, "ts": µs, "dur_s": float,
         "components": {compute, collective, host_callback,
                        data_wait, checkpoint},   # step-thread seconds
         "overlapped_collective_s": float,        # concurrent, extra
         "collective_total_s": float,
         "overlap_efficiency": float|None}

    Attribution rules:

    - A categorized span **on the step span's thread** is time the
      step thread was blocked in that wait; per-category time is the
      *union measure* of its intervals clipped to the step window, so
      nested spans (``allgather`` calling ``reduce``) never double
      count. ``compute`` is the uncovered remainder — components sum
      to the step duration by construction.
    - A **collective span on another thread** overlapping the step
      window is an async collective. The portion of it during which
      the step thread was computing (not inside any same-thread wait)
      is ``overlapped_collective_s`` — concurrent time, reported next
      to (not inside) the wall-time components.
    - ``overlap_efficiency`` = overlapped / (overlapped + serialized)
      collective time; ``None`` when the step ran no collectives.
    """
    steps = [e for e in events
             if e.get("ph") == "X" and e.get("cat") == step_cat
             and isinstance(e.get("ts"), (int, float))
             # the first call's span is XLA compile wall time
             # (instrument_step phase="compile"); attributing it
             # would report a 30s compile as "compute" and mask the
             # real split the compile-vs-execute histograms keep
             # separate
             and (e.get("args") or {}).get("phase") != "compile"]
    cats = {}
    for e in events:
        comp = _CAT_TO_COMPONENT.get(e.get("cat"))
        if comp is None or e.get("ph") != "X":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        dur = e.get("dur", 0) or 0
        cats.setdefault(comp, []).append(
            (float(ts), float(ts) + float(dur), e.get("tid")))
    out = []
    for step in sorted(steps, key=lambda e: e["ts"]):
        lo = float(step["ts"])
        hi = lo + float(step.get("dur", 0) or 0)
        tid = step.get("tid")
        dur_s = (hi - lo) / 1e6
        components = {c: 0.0 for c in COMPONENTS}
        blocked = []
        async_collective = []
        for comp, spans in cats.items():
            same = _union(_clip(
                [(a, b) for a, b, t in spans if t == tid], lo, hi))
            components[comp] = _measure(same) / 1e6
            blocked.extend(same)
            if comp == "collective":
                async_collective = _union(_clip(
                    [(a, b) for a, b, t in spans if t != tid], lo, hi))
        blocked = _union(blocked)
        compute_iv = _subtract([(lo, hi)], blocked)
        components["compute"] = _measure(compute_iv) / 1e6
        overlapped = _measure(_intersect(async_collective, compute_iv)) / 1e6
        serialized = components["collective"]
        total_coll = serialized + _measure(async_collective) / 1e6
        eff = None
        if total_coll > 0:
            eff = overlapped / total_coll
        out.append({
            "step": step.get("args", {}).get("step"),
            "ts": step["ts"],
            "dur_s": dur_s,
            "components": components,
            "overlapped_collective_s": overlapped,
            "collective_total_s": total_coll,
            "overlap_efficiency": eff,
        })
    return out


def make_breakdown(total_s, components, *, source, extra=None):
    """The one breakdown document shape (``BREAKDOWN_SCHEMA``) shared
    by the telemetry-derived attribution and the hand-rolled
    ``benchmarks/step_breakdown.py`` decomposition, so the two are
    cross-checkable in one file format. ``components`` maps name →
    seconds; fractions are derived here."""
    total_s = float(total_s)
    doc = {
        "schema": BREAKDOWN_SCHEMA,
        "source": source,
        "total_s": total_s,
        "components": {k: float(v) for k, v in components.items()},
        "fractions": {
            k: (float(v) / total_s if total_s > 0 else None)
            for k, v in components.items()
        },
    }
    if extra:
        doc.update(extra)
    return doc


def attribution_report(events, step_cat="train"):
    """Aggregate :func:`step_breakdown` over one process's events into
    the ``perf.json`` / doctor document: summed components (a
    :func:`make_breakdown` doc), overall overlap efficiency, the
    per-step rows, and ``inter_step_data_wait_s``. Zero instrumented
    steps → ``{"steps": 0}`` so callers can skip rendering.

    ``inter_step_data_wait_s`` is the data-wait time that fell
    BETWEEN step windows: in the canonical ``for batch in
    prefetch_to_device(...): stepped(batch)`` pattern the refill (and
    its ``data.wait`` span) runs when the for-loop advances the
    iterator, strictly between the step spans — so a starved input
    pipeline shows up here, not in the per-step ``data_wait``
    component (which only catches iterators consumed *inside* the
    step function). Outside-the-window time, reported next to — not
    inside — the sum-to-step-duration components, like the overlapped
    collective time."""
    rows = step_breakdown(events, step_cat=step_cat)
    if not rows:
        return {"steps": 0}
    totals = {c: 0.0 for c in COMPONENTS}
    for r in rows:
        for c, v in r["components"].items():
            totals[c] += v
    total_s = sum(r["dur_s"] for r in rows)
    overlapped = sum(r["overlapped_collective_s"] for r in rows)
    coll_total = sum(r["collective_total_s"] for r in rows)
    step_windows = _union([
        (float(e["ts"]), float(e["ts"]) + float(e.get("dur", 0) or 0))
        for e in events
        if e.get("ph") == "X" and e.get("cat") == step_cat
        and isinstance(e.get("ts"), (int, float))])
    data_spans = _union([
        (float(e["ts"]), float(e["ts"]) + float(e.get("dur", 0) or 0))
        for e in events
        if e.get("ph") == "X" and e.get("cat") == "data"
        and isinstance(e.get("ts"), (int, float))])
    inter_step_wait = _measure(_subtract(data_spans, step_windows)) / 1e6
    doc = make_breakdown(total_s, totals, source="timeline")
    doc.update({
        "steps": len(rows),
        "overlapped_collective_s": overlapped,
        "collective_total_s": coll_total,
        "overlap_efficiency": (overlapped / coll_total
                               if coll_total > 0 else None),
        "inter_step_data_wait_s": inter_step_wait,
        "per_step": rows,
    })
    return doc


# -- differential attribution (perf forensics) -------------------------------

REGRESSION_SCHEMA = "sparkdl_tpu.perf.regression/1"


def _report_from_rows(rows):
    """An :func:`attribution_report`-shaped doc aggregated from
    precomputed per-step rows (the capped-rows fallback: when only
    ``perf.json``'s ``per_step`` survive, the diff still runs — it
    just cannot name grown span names)."""
    totals = {c: 0.0 for c in COMPONENTS}
    for r in rows:
        for c, v in (r.get("components") or {}).items():
            if c in totals and isinstance(v, (int, float)):
                totals[c] += float(v)
    total_s = sum(float(r.get("dur_s") or 0.0) for r in rows)
    overlapped = sum(float(r.get("overlapped_collective_s") or 0.0)
                     for r in rows)
    coll = sum(float(r.get("collective_total_s") or 0.0) for r in rows)
    doc = make_breakdown(total_s, totals, source="rows")
    doc.update({
        "steps": len(rows),
        "overlapped_collective_s": overlapped,
        "collective_total_s": coll,
        "overlap_efficiency": (overlapped / coll if coll > 0 else None),
        "per_step": list(rows),
    })
    return doc


def _window_report(window, step_cat="train"):
    """Normalize one diff side into ``(attribution doc, raw events)``.

    Accepts — in order of forensic fidelity — a raw timeline event
    list (→ :func:`attribution_report`, span names available), a list
    of precomputed per-step rows (``components``/``dur_s`` dicts), or
    an already-built attribution/breakdown doc. ``(None, None)`` when
    the window carries nothing attributable."""
    if isinstance(window, dict):
        if "events" in window and isinstance(window["events"],
                                             (list, tuple)):
            events = list(window["events"])
            doc = attribution_report(events, step_cat=step_cat)
            if window.get("mfu") is not None and "mfu" not in doc:
                doc["mfu"] = window["mfu"]
            return (doc if doc.get("steps") else None,
                    events if doc.get("steps") else None)
        if "components" in window or "per_step" in window:
            return (window if window.get("steps") else None), None
        return None, None
    if isinstance(window, (list, tuple)):
        items = [w for w in window if isinstance(w, dict)]
        if not items:
            return None, None
        if all("components" in w and "dur_s" in w for w in items):
            return _report_from_rows(items), None
        doc = attribution_report(items, step_cat=step_cat)
        if not doc.get("steps"):
            return None, None
        return doc, items
    return None, None


def _per_step_components(doc):
    """Mean step-thread seconds per step for every component."""
    steps = doc.get("steps") or 0
    comps = doc.get("components") or {}
    if not steps:
        return {c: 0.0 for c in COMPONENTS}
    return {c: float(comps.get(c, 0.0) or 0.0) / steps
            for c in COMPONENTS}


def _span_seconds_per_step(events, steps, step_cat="train"):
    """Per-step seconds by span name over raw events (non-step X
    spans) — the grown-span-names half of the diff."""
    if not events or not steps:
        return {}
    by_name = {}
    for e in events:
        if e.get("ph") != "X" or e.get("cat") == step_cat:
            continue
        name = e.get("name")
        ts = e.get("ts")
        if not name or not isinstance(ts, (int, float)):
            continue
        dur = float(e.get("dur", 0) or 0) / 1e6
        by_name[name] = by_name.get(name, 0.0) + dur
    return {n: s / steps for n, s in by_name.items()}


def _window_summary(doc):
    steps = doc.get("steps") or 0
    total_s = float(doc.get("total_s") or 0.0)
    return {
        "steps": steps,
        "step_s_mean": (total_s / steps if steps else None),
        "components_per_step": _per_step_components(doc),
        "overlap_efficiency": doc.get("overlap_efficiency"),
        "mfu": doc.get("mfu"),
        "inter_step_data_wait_s": doc.get("inter_step_data_wait_s"),
    }


def diff_attribution(baseline_window, regressed_window, *,
                     step_cat="train", noise_floor_s=1e-3,
                     rel_floor=0.05, top_spans=5):
    """Differential step attribution: WHY did steps get slower between
    two windows (the alert rule's own calibration window vs the window
    that fired)?

    Each window may be a raw timeline event list, a list of per-step
    attribution rows, or an :func:`attribution_report` doc — the
    capped-rows fallback means a 200-row ``perf.json`` still diffs,
    it just cannot name grown spans. Returns a
    :data:`REGRESSION_SCHEMA` doc::

        {"schema", "baseline": {...}, "regressed": {...},
         "delta": {"step_s", "step_factor", "components_per_step",
                   "overlap_efficiency", "mfu"},
         "top_growing_component": name|None,   # None = under the floor
         "growth_fraction": {...},  # share of step growth, grown comps
         "top_growing_spans": [{"name", "baseline_s_per_step",
                                "regressed_s_per_step", "delta_s"}],
         "significant": bool, "noise_floor_s": float}

    or ``None`` when either side has no attributable steps. The noise
    floor — ``max(noise_floor_s, rel_floor × baseline step time)`` —
    keeps run-to-run jitter from being named a grown component: a
    zero-delta pair reports ``significant: False`` and no culprit.
    """
    base_doc, base_events = _window_report(baseline_window,
                                           step_cat=step_cat)
    reg_doc, reg_events = _window_report(regressed_window,
                                         step_cat=step_cat)
    if base_doc is None or reg_doc is None:
        return None
    base = _window_summary(base_doc)
    reg = _window_summary(reg_doc)
    step_delta = reg["step_s_mean"] - base["step_s_mean"]
    floor = max(float(noise_floor_s), rel_floor * base["step_s_mean"])
    comp_delta = {
        c: reg["components_per_step"][c] - base["components_per_step"][c]
        for c in COMPONENTS
    }
    grown = {c: d for c, d in comp_delta.items() if d > floor}
    significant = step_delta > floor and bool(grown)
    top_component = (max(grown, key=grown.get) if significant else None)
    growth_fraction = {}
    if significant and step_delta > 0:
        growth_fraction = {c: d / step_delta for c, d in grown.items()}
    eff_delta = None
    if isinstance(base.get("overlap_efficiency"), (int, float)) and \
            isinstance(reg.get("overlap_efficiency"), (int, float)):
        eff_delta = (reg["overlap_efficiency"]
                     - base["overlap_efficiency"])
    mfu_delta = None
    if isinstance(base.get("mfu"), (int, float)) and \
            isinstance(reg.get("mfu"), (int, float)):
        mfu_delta = reg["mfu"] - base["mfu"]
    spans = []
    if base_events is not None and reg_events is not None:
        base_spans = _span_seconds_per_step(
            base_events, base["steps"], step_cat=step_cat)
        reg_spans = _span_seconds_per_step(
            reg_events, reg["steps"], step_cat=step_cat)
        for name in set(base_spans) | set(reg_spans):
            d = reg_spans.get(name, 0.0) - base_spans.get(name, 0.0)
            if d > floor:
                spans.append({
                    "name": name,
                    "baseline_s_per_step": base_spans.get(name, 0.0),
                    "regressed_s_per_step": reg_spans.get(name, 0.0),
                    "delta_s": d,
                })
        spans.sort(key=lambda s: -s["delta_s"])
        spans = spans[:top_spans]
    return {
        "schema": REGRESSION_SCHEMA,
        "baseline": base,
        "regressed": reg,
        "delta": {
            "step_s": step_delta,
            "step_factor": (reg["step_s_mean"] / base["step_s_mean"]
                            if base["step_s_mean"] else None),
            "components_per_step": comp_delta,
            "overlap_efficiency": eff_delta,
            "mfu": mfu_delta,
        },
        "top_growing_component": top_component,
        "growth_fraction": growth_fraction,
        "top_growing_spans": spans,
        "significant": significant,
        "noise_floor_s": floor,
    }


def render_diff_lines(diff, indent=""):
    """Human-readable lines for one :func:`diff_attribution` doc — the
    SHARED renderer doctor, ``observe.compare --explain`` and the
    forensics report all use, so the three surfaces read alike."""
    if not diff:
        return []
    base, reg = diff["baseline"], diff["regressed"]
    d = diff["delta"]
    lines = [
        "%sstep time: %.4fs -> %.4fs (x%.2f, %+.4fs) over %d vs %d "
        "step(s)" % (
            indent, base["step_s_mean"], reg["step_s_mean"],
            d["step_factor"] or 0.0, d["step_s"],
            base["steps"], reg["steps"]),
    ]
    for c in COMPONENTS:
        delta = d["components_per_step"].get(c, 0.0)
        marker = ""
        if c == diff.get("top_growing_component"):
            marker = "  <-- grew the most"
        lines.append(
            "%s  %-13s %.4fs/step -> %.4fs/step (%+.4fs)%s" % (
                indent, c, base["components_per_step"].get(c, 0.0),
                reg["components_per_step"].get(c, 0.0), delta, marker))
    if d.get("overlap_efficiency") is not None:
        lines.append("%s  overlap efficiency %+.1f%%" % (
            indent, d["overlap_efficiency"] * 100))
    if d.get("mfu") is not None:
        lines.append("%s  mfu %+.4f" % (indent, d["mfu"]))
    for s in diff.get("top_growing_spans") or ():
        lines.append(
            "%s  span %-24s %+0.4fs/step (%.4fs -> %.4fs)" % (
                indent, s["name"], s["delta_s"],
                s["baseline_s_per_step"], s["regressed_s_per_step"]))
    if not diff.get("significant"):
        lines.append(
            "%s  (delta under the %.4fs noise floor — no component "
            "named)" % (indent, diff["noise_floor_s"]))
    return lines


# -- roofline / MFU gauges ---------------------------------------------------

# name -> {"flops": float|None, "bytes_accessed": float|None}; written
# only behind the latch, so with telemetry off this dict never grows
# (the zero-overhead test pins that).
_step_costs = {}


def register_step_cost(name, executable):
    """Record one executable's analytic cost (FLOPs / bytes accessed /
    peak memory) so every subsequent :func:`note_step` can turn step
    wall time into achieved-FLOPs/s and MFU. ``executable`` is a
    ``Lowered`` or ``Compiled`` (the shims duck-type); a runtime with
    no cost model degrades to ``None`` and the gauges simply never
    appear. No-op (returns None) with telemetry off."""
    from sparkdl_tpu import observe
    from sparkdl_tpu.utils import jax_compat

    if not observe.enabled():
        return None
    cost = jax_compat.cost_analysis(executable)
    mem = jax_compat.memory_analysis(executable)
    if mem:
        # static budget for the OOM report's measured-vs-predicted line
        from sparkdl_tpu.observe import mem as mem_acct

        mem_acct.note_budget(name, mem)
    entry = {
        "flops": (cost or {}).get("flops"),
        "bytes_accessed": (cost or {}).get("bytes_accessed"),
    }
    if not any(v for v in entry.values()):
        return None
    # Resolve the device kind and peak denominators ONCE — they are
    # process-lifetime constants, and note_step runs on every
    # executed step of the instrumented hot path.
    kind = device_kind()
    entry["device_kind"] = normalize_device_kind(kind)
    entry["peak_flops"] = peak_flops(kind)
    entry["peak_bytes"] = peak_bytes_per_sec(kind)
    _step_costs[name] = entry
    if entry["flops"]:
        observe.set_gauge("step_cost_flops", entry["flops"], fn=name)
    if entry["bytes_accessed"]:
        observe.set_gauge("step_cost_bytes", entry["bytes_accessed"],
                          fn=name)
        if entry["flops"]:
            observe.set_gauge(
                "step_operational_intensity",
                entry["flops"] / entry["bytes_accessed"], fn=name)
    if mem and mem.get("temp_size_in_bytes") is not None:
        observe.set_gauge("step_temp_bytes", mem["temp_size_in_bytes"],
                          fn=name)
    return entry


def note_step(name, seconds):
    """Fold one executed step's wall time into the achieved-rate and
    roofline gauges — called by ``instrument_step`` on every
    execute-phase step (already behind the latch). Silent when no cost
    was registered for ``name`` (the missing-cost-model contract)."""
    from sparkdl_tpu import observe

    entry = _step_costs.get(name)
    if not entry or seconds <= 0:
        return
    norm = entry["device_kind"]
    flops, nbytes = entry.get("flops"), entry.get("bytes_accessed")
    if flops:
        achieved = flops / seconds
        observe.set_gauge("achieved_flops_per_sec", achieved, fn=name)
        pf = entry["peak_flops"]
        if pf:
            observe.set_gauge("mfu", achieved / pf, fn=name,
                              device_kind=norm)
    if nbytes:
        achieved_b = nbytes / seconds
        observe.set_gauge("achieved_bytes_per_sec", achieved_b, fn=name)
        pb = entry["peak_bytes"]
        if pb:
            observe.set_gauge("membw_util", achieved_b / pb, fn=name,
                              device_kind=norm)


def _reset_for_tests():
    _step_costs.clear()


# -- regression ledger -------------------------------------------------------


def default_history_path():
    """``benchmarks/results/history.jsonl`` at the repo root (env
    ``SPARKDL_TPU_PERF_HISTORY`` overrides; the values ``0`` / ``off``
    disable appending entirely)."""
    env = os.environ.get(HISTORY_ENV)
    if env and env.lower() not in ("0", "off"):
        return env
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "benchmarks", "results", "history.jsonl")


def host_fingerprint():
    """Stable who-measured-this string: comparisons across different
    fingerprints are apples-to-oranges and the compare CLI says so."""
    import platform as _platform

    return "%s/%s/cpu%s" % (
        socket.gethostname(), _platform.machine(), os.cpu_count() or 0)


def git_sha():
    """Short HEAD sha of the repo this module sits in, or None."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        sha = out.stdout.strip()
        return sha or None
    except Exception:
        return None


def _percentile(samples, q):
    """np.percentile's default linear interpolation, without the
    numpy import this artifact-side module avoids."""
    xs = sorted(float(s) for s in samples)
    if not xs:
        return None
    k = (len(xs) - 1) * q / 100.0
    f, c = int(k), min(int(k) + 1, len(xs) - 1)
    return xs[f] + (xs[c] - xs[f]) * (k - f)


def sample_metric(samples, *, unit, higher_is_better=False, digits=4):
    """ONE ledger metric dict from raw per-rep samples (already in the
    target unit): ``value`` = p50, with ``p99`` and the samples
    preserved so :mod:`sparkdl_tpu.observe.compare`'s median/IQR noise
    protection applies. The single definition of the shape
    :func:`history_record` documents — benchmarks must not hand-roll
    copies of it."""
    if not samples:
        raise ValueError("sample_metric needs at least one sample")
    p50 = round(_percentile(samples, 50), digits)
    return {
        "value": p50, "p50": p50,
        "p99": round(_percentile(samples, 99), digits),
        "samples": [round(float(s), digits) for s in samples],
        "unit": unit, "higher_is_better": higher_is_better,
    }


def history_record(metrics, *, device_kind=None, bench=None, extra=None):
    """One schema-versioned ledger line. ``metrics`` maps name →
    ``{"value": float, "unit": str, "samples": [...]?, "p50"?,
    "p99"?, "higher_is_better"?: bool}`` (plain numbers are wrapped).
    """
    norm = {}
    for name, m in metrics.items():
        if not isinstance(m, dict):
            m = {"value": m}
        if m.get("value") is None:
            continue
        norm[name] = {k: v for k, v in m.items() if v is not None}
    rec = {
        "schema": HISTORY_SCHEMA,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": git_sha(),
        "host": host_fingerprint(),
        "device_kind": device_kind,
        "bench": bench,
        "metrics": norm,
    }
    if extra:
        rec.update(extra)
    return rec


def append_history(record, path=None):
    """Append one record as a JSON line (creating parents). Best
    effort and silent on failure — the ledger must never fail the
    bench that feeds it. Returns the path written, or None when
    disabled/unwritable."""
    env = os.environ.get(HISTORY_ENV, "")
    if env.lower() in ("0", "off"):
        return None
    path = path or default_history_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
        return path
    except Exception:
        return None


def read_history(path=None):
    """Parsed ledger entries (skipping unparsable lines), oldest
    first. Missing file → empty list."""
    path = path or default_history_path()
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return []
    return out
