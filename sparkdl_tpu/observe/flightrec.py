"""Crash-surviving flight recorder: an mmap-backed ring of the last N
timeline events per rank.

The telemetry flusher ships events to the driver every few seconds and
once more at exit — but a SIGKILL (real preemption, the chaos
harness, the launcher reaping a hung gang) kills the process between
flushes and the final flush never happens. The flight recorder closes
that gap: every timeline event is ALSO written into a fixed-size ring
in an ``mmap``'d file, and the kernel writes dirty ``MAP_SHARED``
pages back regardless of how the process died — so the tail of a
SIGKILLed rank's story is recoverable from the file afterwards
(:meth:`FlightRecorder.read_tail`, merged into the run dir by
``observe.aggregate``).

Hot-path contract: recording is *lock-free* — no blocking between
writer threads, no fsync, no syscalls. Each event claims a slot via a
monotonic sequence counter (``itertools.count``: one atomic fetch-add
under the GIL) and writes its own slot independently. A reader (or a
write torn by the kill) sees at most one garbled slot, which fails
JSON validation and is dropped; every completed slot is ordered by its
embedded sequence number. Single-incarnation files: each worker
process opens its own ``flightrec-rank-<r>.ring`` in its attempt's
job dir (a relaunch gets a fresh job dir, so incarnations never
overwrite each other's tails).

File layout (little-endian)::

    header: magic "SDTFR1\\0\\0" | u32 slot_size | u32 nslots
    slot i: u64 seq (1-based; 0 = never written) | u32 len | payload
"""

import itertools
import json
import mmap
import os
import struct

MAGIC = b"SDTFR1\x00\x00"
_HEADER = struct.Struct("<8sII")
_SLOT_HEAD = struct.Struct("<QI")

EVENTS_ENV = "SPARKDL_TPU_FLIGHTREC_EVENTS"
DEFAULT_EVENTS = 256
DEFAULT_SLOT_SIZE = 1024

FILE_PREFIX = "flightrec-rank-"
FILE_SUFFIX = ".ring"


def ring_path(job_dir, rank):
    return os.path.join(job_dir, f"{FILE_PREFIX}{int(rank)}{FILE_SUFFIX}")


def default_events():
    try:
        return max(8, int(os.environ.get(EVENTS_ENV, DEFAULT_EVENTS)))
    except ValueError:
        return DEFAULT_EVENTS


class FlightRecorder:
    """Single-process writer over one ring file."""

    def __init__(self, path, nslots=None, slot_size=DEFAULT_SLOT_SIZE):
        self.path = path
        self.nslots = int(nslots if nslots is not None else default_events())
        self.slot_size = int(slot_size)
        size = _HEADER.size + self.nslots * self.slot_size
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._mm[:_HEADER.size] = _HEADER.pack(
            MAGIC, self.slot_size, self.nslots
        )
        self._seq = itertools.count(1)
        self._closed = False

    def record(self, event):
        """Write one timeline event dict. Never raises into the hot
        path — an unserializable arg or a closed ring drops the event
        (the in-memory timeline still has it)."""
        if self._closed:
            return
        try:
            payload = json.dumps(event, default=str).encode("utf-8")
        except (TypeError, ValueError):
            return
        cap = self.slot_size - _SLOT_HEAD.size
        if len(payload) > cap:
            # Oversized args: keep the identity fields, drop the rest —
            # a truncated-but-parseable record beats a dropped one.
            slim = {k: event.get(k) for k in
                    ("name", "cat", "ph", "ts", "dur", "tid")
                    if k in event}
            slim["truncated"] = True
            payload = json.dumps(slim, default=str).encode("utf-8")[:cap]
        seq = next(self._seq)
        off = _HEADER.size + ((seq - 1) % self.nslots) * self.slot_size
        mm = self._mm
        try:
            # Payload before the slot header: a reader that sees the
            # new (seq, len) sees the new bytes; a kill between the
            # two leaves a record that fails JSON validation.
            mm[off + _SLOT_HEAD.size:off + _SLOT_HEAD.size + len(payload)] \
                = payload
            _SLOT_HEAD.pack_into(mm, off, seq, len(payload))
        except (ValueError, IndexError):
            pass  # closed underneath us

    def flush(self):
        try:
            self._mm.flush()
        except (OSError, ValueError):
            pass

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.flush()
        try:
            self._mm.close()
        except (OSError, ValueError):
            pass

    # -- recovery ------------------------------------------------------------

    @staticmethod
    def read_tail(path):
        """Recover the ordered event tail from a ring file written by
        a (possibly SIGKILLed) process. Torn or garbled slots are
        dropped; returns events oldest-first. Raises ``ValueError`` on
        a file that was never a flight-recorder ring."""
        with open(path, "rb") as f:
            raw = f.read()
        if len(raw) < _HEADER.size:
            raise ValueError(f"{path}: truncated flight-recorder ring")
        magic, slot_size, nslots = _HEADER.unpack_from(raw, 0)
        if magic != MAGIC or slot_size <= _SLOT_HEAD.size or nslots <= 0:
            raise ValueError(f"{path}: not a flight-recorder ring")
        out = []
        for i in range(nslots):
            off = _HEADER.size + i * slot_size
            if off + _SLOT_HEAD.size > len(raw):
                break
            seq, ln = _SLOT_HEAD.unpack_from(raw, off)
            if seq == 0 or ln == 0 or ln > slot_size - _SLOT_HEAD.size:
                continue
            body = raw[off + _SLOT_HEAD.size:off + _SLOT_HEAD.size + ln]
            try:
                ev = json.loads(body.decode("utf-8"))
            except (UnicodeDecodeError, ValueError):
                continue  # torn write
            if isinstance(ev, dict):
                out.append((seq, ev))
        out.sort(key=lambda t: t[0])
        return [ev for _, ev in out]


def recover_job_dir(job_dir):
    """``{rank: [events...]}`` for every ring file in ``job_dir``
    (unreadable or non-ring files are skipped — recovery is
    postmortem code and must never fail the artifact write)."""
    out = {}
    try:
        names = os.listdir(job_dir)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith(FILE_PREFIX) and name.endswith(FILE_SUFFIX)):
            continue
        rank_s = name[len(FILE_PREFIX):-len(FILE_SUFFIX)]
        try:
            rank = int(rank_s)
            events = FlightRecorder.read_tail(os.path.join(job_dir, name))
        except (ValueError, OSError):
            continue
        out.setdefault(rank, []).extend(events)
    return out
