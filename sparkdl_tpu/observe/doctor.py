"""``observe.doctor``: one-command postmortem over a merged run dir.

A hung-gang incident leaves its evidence scattered across the run
directory the launcher wrote (``SPARKDL_TPU_TELEMETRY_DIR/run-*``):
verdict instants in ``timeline.json``, per-rank step/HBM gauges in
``metrics.json``/``metrics.prom``, the detector's final state in
``health.json``, faulthandler stacks in ``stack-rank-*.txt``, and the
flight-recorder tails of ranks that died between flushes in
``flightrec-rank-*.json``. This module merges them into ONE diagnosis::

    $ python -m sparkdl_tpu.observe.doctor /tmp/telemetry/run-1234-0
    observe.doctor: /tmp/telemetry/run-1234-0
    verdict: HANG (straggler)
      rank 1: stalled @ step 1, last entered reduce
      rank 0: progressed to step 2
    stack dumps: rank 1 (stack-rank-1.txt)
    supervisor: 1 relaunch(es); causes: HANG (straggler) — ...
    ...

``--format json`` emits the same diagnosis as one JSON document. The
exit code is the alerting contract: **nonzero when a hang verdict is
found** (CI's hang smoke asserts it), zero for a clean run, 2 when the
directory has no readable artifacts at all.

Deliberately artifact-only: no jax, no control plane, no live gang —
the doctor must run on a laptop against a copied run dir and reproduce
the verdict from the files alone.
"""

import argparse
import glob
import json
import os
import re
import sys


def _load_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_bytes(n):
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.1f} {unit}" if unit != "B"
                    else f"{int(n)} {unit}")
        n /= 1024.0
    return f"{n:.1f} TiB"


def _series_by_rank(metrics_doc):
    """rank-label -> {counters: {(name, label-items): v},
    gauges: {...}} from metrics.json."""
    out = {}
    for series in (metrics_doc or {}).get("series", ()):
        rank = series.get("labels", {}).get("rank")
        if rank is None:
            continue
        ranks = out.setdefault(rank, {"counters": {}, "gauges": {}})
        for kind in ("counters", "gauges"):
            for s in series.get(kind, ()):
                labels = {k: v for k, v in s.get("labels", {}).items()
                          if k != "rank"}
                key = (s.get("name"),
                       tuple(sorted(labels.items())))
                ranks[kind][key] = s.get("value")
    return out


def _gauge(rank_series, name, **labels):
    return rank_series.get("gauges", {}).get(
        (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
    )


def diagnose(run_dir):
    """Build the structured diagnosis dict for one run dir, or None
    when the directory holds no recognizable artifacts."""
    timeline = _load_json(os.path.join(run_dir, "timeline.json"))
    metrics = _load_json(os.path.join(run_dir, "metrics.json"))
    health = _load_json(os.path.join(run_dir, "health.json"))
    if timeline is None and metrics is None and health is None:
        return None

    events = [e for e in (timeline or {}).get("traceEvents", ())
              if isinstance(e, dict) and e.get("ph") != "M"]

    def named(name):
        return [e for e in events if e.get("name") == name]

    # -- verdict: health.json is authoritative, the timeline's
    # health.hang instant corroborates (either alone suffices — the
    # doctor must reproduce the verdict from whatever survived).
    verdict = None
    stalled, silent = set(), set()
    attempts = (health or {}).get("attempts", [])
    for att in attempts:
        if att.get("hang_verdict"):
            verdict = att["hang_verdict"]
        stalled.update(att.get("stalled", ()))
        silent.update(att.get("silent", ()))
    for ev in named("health.hang"):
        verdict = verdict or ev.get("args", {}).get("verdict")
        stalled.update(ev.get("args", {}).get("stalled", ()))
        silent.update(ev.get("args", {}).get("silent", ()))
    for ev in named("health.stall"):
        rank = ev.get("args", {}).get("rank")
        if rank is not None:
            stalled.add(rank)
    for ev in named("health.silent"):
        rank = ev.get("args", {}).get("rank")
        if rank is not None:
            silent.add(rank)

    # -- per-rank state: detector summaries first, gauge fallback.
    # Source the forensics from the attempt that HUNG, not the last
    # one — a clean resumed attempt overwrites step/collective with
    # its own (restarted) values and would repaint the postmortem.
    ranks = {}
    hung_attempts = [a for a in attempts if a.get("hang_verdict")]
    for att in (hung_attempts or attempts):
        for rank_s, info in (att.get("ranks") or {}).items():
            ranks[int(rank_s)] = {
                "step": info.get("step"),
                "collective": info.get("collective"),
                "hbm": info.get("hbm") or {},
            }
        if hung_attempts:
            break   # first hung attempt is the incident
    by_rank = _series_by_rank(metrics)
    for rank_label, series in by_rank.items():
        if not rank_label.isdigit():
            continue
        rank = int(rank_label)
        info = ranks.setdefault(
            rank, {"step": None, "collective": None, "hbm": {}})
        if info["step"] is None:
            step = _gauge(series, "worker_step")
            if step is not None:
                info["step"] = int(step)
        for kind in ("peak", "in_use", "limit", "live_buffers"):
            v = _gauge(series, "device_hbm_bytes", kind=kind)
            if v is not None and kind not in info["hbm"]:
                info["hbm"][kind] = v

    # -- supervisor story from the driver lane
    failures = [
        {"attempt": e.get("args", {}).get("attempt"),
         "verdict": e.get("args", {}).get("verdict"),
         "cause": e.get("args", {}).get("cause")}
        for e in named("gang.failure")
    ]
    resumes = [e.get("args", {}) for e in named("gang.resume")]
    hang_causes = [f for f in failures
                   if "hang" in str(f.get("cause", "")).lower()]
    if verdict is None and hang_causes:
        # Last resort: the supervisor recorded a HANG cause even
        # though health.json and the health.* instants were lost.
        m = re.search(r"HANG \((\w+)\)", hang_causes[0].get("cause") or "")
        verdict = m.group(1) if m else "hung"

    stack_dumps = {
        int(os.path.basename(p)[len("stack-rank-"):-len(".txt")]): p
        for p in glob.glob(os.path.join(run_dir, "stack-rank-*.txt"))
    }
    flight = {}
    for p in glob.glob(os.path.join(run_dir, "flightrec-rank-*.json")):
        doc = _load_json(p)
        if doc is not None:
            flight[int(doc.get("rank", -1))] = len(doc.get("events", ()))

    chaos = sorted({e.get("name") for e in events
                    if e.get("cat") == "chaos"})

    return {
        "run_dir": run_dir,
        "hang": verdict is not None,
        "verdict": verdict,
        "stalled_ranks": sorted(stalled),
        "silent_ranks": sorted(silent),
        "ranks": {str(r): ranks[r] for r in sorted(ranks)},
        "failures": failures,
        "resumes": resumes,
        "stack_dumps": {str(r): os.path.basename(p)
                        for r, p in sorted(stack_dumps.items())},
        "flight_recorder_events": {str(r): n
                                   for r, n in sorted(flight.items())},
        "chaos_injections": chaos,
    }


def render_text(diag):
    lines = [f"observe.doctor: {diag['run_dir']}"]
    if diag["hang"]:
        lines.append(f"verdict: HANG ({diag['verdict']})")
    else:
        lines.append("verdict: no hang found")
    stalled = set(diag["stalled_ranks"])
    silent = set(diag["silent_ranks"])
    for rank_s, info in diag["ranks"].items():
        rank = int(rank_s)
        state = ("stalled" if rank in stalled
                 else "silent" if rank in silent
                 else "progressed")
        line = f"  rank {rank}: {state}"
        if info.get("step") is not None:
            line += (f" @ step {info['step']}" if state == "stalled"
                     else f" to step {info['step']}")
        if info.get("collective"):
            line += f", last entered {info['collective']}"
        hbm = info.get("hbm") or {}
        peak = hbm.get("peak", hbm.get("in_use",
                                       hbm.get("live_buffers")))
        if peak is not None:
            line += f"; HBM high-water {_fmt_bytes(peak)}"
        lines.append(line)
    if diag["stack_dumps"]:
        lines.append("stack dumps: " + ", ".join(
            f"rank {r} ({name})"
            for r, name in diag["stack_dumps"].items()))
    if diag["flight_recorder_events"]:
        lines.append("flight recorder tails: " + ", ".join(
            f"rank {r} ({n} events)"
            for r, n in diag["flight_recorder_events"].items()))
    if diag["failures"]:
        causes = "; ".join(
            f"attempt {f.get('attempt')}: {f.get('verdict')} — "
            f"{f.get('cause')}" for f in diag["failures"])
        lines.append(f"supervisor: {len(diag['failures'])} classified "
                     f"failure(s): {causes}")
    if diag["resumes"]:
        steps = ", ".join(str(r.get("resume_step")) for r in diag["resumes"])
        lines.append(f"resumed: {len(diag['resumes'])} relaunch(es) "
                     f"(resume step(s): {steps})")
    if diag["chaos_injections"]:
        lines.append("chaos injections on the timeline: "
                     + ", ".join(diag["chaos_injections"]))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.observe.doctor",
        description="Postmortem diagnosis over a merged telemetry run "
                    "dir; exits nonzero when a hang verdict is found.",
    )
    parser.add_argument("run_dir", help="a run-* dir under "
                        "SPARKDL_TPU_TELEMETRY_DIR (or a copy of one)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    args = parser.parse_args(argv)

    diag = diagnose(args.run_dir)
    if diag is None:
        print(f"observe.doctor: no telemetry artifacts under "
              f"{args.run_dir} (expected timeline.json / metrics.json "
              f"/ health.json)", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(diag, indent=2, sort_keys=True))
    else:
        print(render_text(diag))
    return 1 if diag["hang"] else 0


if __name__ == "__main__":
    sys.exit(main())
