"""``observe.doctor``: one-command postmortem over a merged run dir.

A hung-gang incident leaves its evidence scattered across the run
directory the launcher wrote (``SPARKDL_TPU_TELEMETRY_DIR/run-*``):
verdict instants in ``timeline.json``, per-rank step/HBM gauges in
``metrics.json``/``metrics.prom``, the detector's final state in
``health.json``, faulthandler stacks in ``stack-rank-*.txt``, and the
flight-recorder tails of ranks that died between flushes in
``flightrec-rank-*.json``. This module merges them into ONE diagnosis::

    $ python -m sparkdl_tpu.observe.doctor /tmp/telemetry/run-1234-0
    observe.doctor: /tmp/telemetry/run-1234-0
    verdict: HANG (straggler)
      rank 1: stalled @ step 1, last entered reduce
      rank 0: progressed to step 2
    stack dumps: rank 1 (stack-rank-1.txt)
    supervisor: 1 relaunch(es); causes: HANG (straggler) — ...
    ...

``--format json`` emits the same diagnosis as one JSON document. The
exit code is the alerting contract: **nonzero when a hang verdict is
found** (CI's hang smoke asserts it), zero for a clean run, 2 when the
directory has no readable artifacts at all.

Serving run dirs (written by a
:class:`~sparkdl_tpu.models.server.ServingFrontend` with telemetry
opted in) get their own postmortem section: the slowest requests by
time-to-first-token, the admission-rejection/deferral breakdown, and
the batch-utilization summary — read from the same
``timeline.json``/``metrics.json`` shapes the gang artifacts use. A
server that died by SIGKILL stopped writing artifacts mid-story (or
never wrote any); the doctor merges the PR-5 flight-recorder ring
left in the run dir into the timeline — every ring event the written
trace is missing is the tail the kill cut off.

Deliberately artifact-only: no jax, no control plane, no live gang —
the doctor must run on a laptop against a copied run dir and reproduce
the verdict from the files alone.
"""

import argparse
import glob
import json
import os
import re
import sys


def _load_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_bytes(n):
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.1f} {unit}" if unit != "B"
                    else f"{int(n)} {unit}")
        n /= 1024.0
    return f"{n:.1f} TiB"


def _series_by_rank(metrics_doc):
    """rank-label -> {counters: {(name, label-items): v},
    gauges: {...}, histograms: {...: {"sum", "count"}}} from
    metrics.json."""
    out = {}
    for series in (metrics_doc or {}).get("series", ()):
        rank = series.get("labels", {}).get("rank")
        if rank is None:
            continue
        ranks = out.setdefault(
            rank, {"counters": {}, "gauges": {}, "histograms": {}})
        for kind in ("counters", "gauges"):
            for s in series.get(kind, ()):
                labels = {k: v for k, v in s.get("labels", {}).items()
                          if k != "rank"}
                key = (s.get("name"),
                       tuple(sorted(labels.items())))
                ranks[kind][key] = s.get("value")
        for s in series.get("histograms", ()):
            labels = {k: v for k, v in s.get("labels", {}).items()
                      if k != "rank"}
            key = (s.get("name"), tuple(sorted(labels.items())))
            ranks["histograms"][key] = {
                "sum": s.get("sum"), "count": s.get("count")}
    return out


def _gauge(rank_series, name, **labels):
    return rank_series.get("gauges", {}).get(
        (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
    )


def _diagnose_perf(run_dir, events, by_rank):
    """"Where the time went" (or None): per-rank step-time attribution
    — compute / collective / host-callback / data-wait / checkpoint
    fractions plus overlap efficiency — from ``perf.json`` when the
    aggregator wrote one, else recomputed from the merged timeline
    (events grouped by lane; lane ``rank + 1`` is rank ``r``), plus
    whatever MFU / achieved-FLOPs gauges the ranks exported. Pure
    artifact math (:mod:`sparkdl_tpu.observe.perf` imports no jax) so
    a copied run dir diagnoses anywhere."""
    from sparkdl_tpu.observe import perf as _perf

    ranks = {}
    doc = _load_json(os.path.join(run_dir, "perf.json"))
    if doc and isinstance(doc.get("ranks"), dict):
        ranks = {str(r): rep for r, rep in doc["ranks"].items()
                 if isinstance(rep, dict) and rep.get("steps")}
    if not ranks:
        by_lane = {}
        for e in events:
            pid = e.get("pid")
            if isinstance(pid, int) and pid >= 1:
                by_lane.setdefault(pid - 1, []).append(e)
        for rank in sorted(by_lane):
            rep = _perf.attribution_report(by_lane[rank])
            if rep.get("steps"):
                ranks[str(rank)] = rep
    out = {}
    for rank_s, rep in sorted(ranks.items()):
        entry = {
            "steps": rep.get("steps"),
            "total_s": rep.get("total_s"),
            "components": rep.get("components"),
            "fractions": rep.get("fractions"),
            "overlap_efficiency": rep.get("overlap_efficiency"),
            "inter_step_data_wait_s": rep.get("inter_step_data_wait_s"),
        }
        series = by_rank.get(rank_s, {})
        for name in ("mfu", "achieved_flops_per_sec"):
            for (g_name, _labels), v in series.get("gauges", {}).items():
                if g_name == name:
                    entry[name] = v
                    break
        out[rank_s] = entry
    return out or None


def _diagnose_comms(run_dir, by_rank):
    """Predicted-vs-measured communication (or None): the static comms
    budget the launcher pre-flight priced (``comms_report.json``, per
    step under a ring assumption) set against the runtime
    ``collective_bytes_total`` counters each rank actually moved —
    with a measured-per-step/predicted ratio when the rank's executed
    step count is recoverable from ``train_step_total``."""
    doc = _load_json(os.path.join(run_dir, "comms_report.json"))
    reports = [r for r in (doc or {}).get("reports", ())
               if isinstance(r, dict)]
    measured = {}
    for rank_label, series in by_rank.items():
        if not rank_label.isdigit():
            continue
        by_op = {}
        steps = None
        for (name, labels), v in series.get("counters", {}).items():
            if name == "collective_bytes_total":
                by_op[dict(labels).get("op", "?")] = int(v)
            elif (name == "train_step_total"
                  and dict(labels).get("phase") == "execute"):
                steps = int(v)
        if by_op:
            measured[rank_label] = {
                "bytes_by_op": by_op,
                "bytes_total": sum(by_op.values()),
                "steps": steps,
            }
    if not reports and not measured:
        return None
    predicted = sum(
        r.get("totals", {}).get("wire_bytes_per_device") or 0
        for r in reports
    )
    for entry in measured.values():
        if predicted and entry["steps"]:
            entry["per_step_vs_predicted"] = round(
                (entry["bytes_total"] / entry["steps"]) / predicted, 3)
    return {
        "reports": [
            {"name": r.get("name"),
             "device_kind": r.get("device_kind"),
             "totals": r.get("totals")}
            for r in reports
        ],
        "predicted_wire_bytes_per_device_per_step": predicted or None,
        "measured_by_rank": measured,
    }


def _diagnose_fixit(run_dir):
    """Suggested/applied fixes (or None): the verified fixit reports
    the launcher pre-flight wrote (``fixit_report.json``, schema
    ``sparkdl_tpu.analysis.fixit_report/1``) — per fix: the rule, the
    machine action, whether its four proofs held, whether it was
    applied or degraded to the original finding, and the predicted
    peak-HBM delta. Pure artifact math — the doctor renders the
    remediation story without importing jax or the analysis engine."""
    doc = _load_json(os.path.join(run_dir, "fixit_report.json"))
    reports = [r for r in (doc or {}).get("reports", ())
               if isinstance(r, dict)]
    if not reports:
        return None
    out = []
    for rep in reports:
        fixes = []
        for entry in rep.get("fixes", ()):
            proofs = entry.get("proofs") or {}
            mem = (proofs.get("budget_delta") or {}).get("memory") or {}
            fixes.append({
                "rule_id": entry.get("rule_id"),
                "action": entry.get("action"),
                "verified": bool(entry.get("verified")),
                "applied": bool(entry.get("applied")),
                "degraded": bool(entry.get("degraded")),
                "degrade_reason": entry.get("degrade_reason"),
                "description": (entry.get("fix") or {}).get("description"),
                "proofs_ok": {k: bool((v or {}).get("ok"))
                              for k, v in proofs.items()},
                "peak_bytes_delta": mem.get("peak_bytes_delta"),
            })
        out.append({
            "name": rep.get("name"),
            "mode": rep.get("mode"),
            "summary": rep.get("summary") or {},
            "fixes": fixes,
            "unfixable": len(rep.get("unfixable") or ()),
        })
    return {"reports": out}


def _diagnose_alerts(run_dir):
    """Live-alert section (or None when the run predates the alert
    engine / never enabled it): the ``alerts.json`` the launcher
    wrote — rule catalog, baseline, and every firing, exactly as the
    engine saw them mid-run. Artifact-only like everything else here:
    no jax, no live gang, reproduced from the file alone."""
    doc = _load_json(os.path.join(run_dir, "alerts.json"))
    if not isinstance(doc, dict):
        return None
    fired = [a for a in doc.get("alerts", ()) if isinstance(a, dict)]
    return {
        "enabled": bool(doc.get("enabled")),
        "rules": [r.get("rule") for r in doc.get("rules", ())
                  if isinstance(r, dict)],
        "baseline_step_s": doc.get("baseline_step_s"),
        "baseline_source": doc.get("baseline_source"),
        "fired": fired,
    }


def _diagnose_forensics(run_dir):
    """Perf-forensics section (or None when no capture ever ran): the
    ``regression_report.json`` the forensics manager wrote — one entry
    per trigger, each carrying the differential attribution between
    the alert rule's own calibration window and the window that fired
    — plus every worker-side ``profile_report-rank-*.json`` capture
    (bounded profile window: uncapped attribution rows, device-memory
    snapshot, xprof trace dir) the aggregator recovered from the job
    dirs. Artifact-only like everything else here: the diff is
    rendered from the stored doc, never recomputed."""
    doc = _load_json(os.path.join(run_dir, "regression_report.json"))
    reports = [r for r in (doc or {}).get("reports", ())
               if isinstance(r, dict)]
    captures = []
    for p in sorted(glob.glob(os.path.join(run_dir,
                                           "profile_report*.json"))):
        rep = _load_json(p)
        if not isinstance(rep, dict):
            continue
        attribution = rep.get("attribution") or {}
        captures.append({
            "file": os.path.basename(p),
            "rank": rep.get("rank"),
            "reason": rep.get("reason"),
            "rule": rep.get("rule"),
            "steps_captured": rep.get("steps_captured"),
            "window_s": rep.get("window_s"),
            "trace_dir": rep.get("trace_dir"),
            "attribution_steps": attribution.get("steps"),
            "fractions": attribution.get("fractions"),
            "overlap_efficiency": attribution.get("overlap_efficiency"),
            "device_memory": rep.get("device_memory") or None,
        })
    trace_dirs = sorted(
        os.path.basename(p)
        for p in glob.glob(os.path.join(run_dir, "xprof-rank-*"))
        if os.path.isdir(p))
    if not reports and not captures and not trace_dirs:
        return None
    return {"reports": reports, "captures": captures,
            "trace_dirs": trace_dirs}


def _diagnose_elastic(run_dir):
    """Elastic-controller section (or None when the run predates
    autonomous elasticity / never enabled it): the ``elastic.json``
    decision log — every grow/yield/reclaim the controller planned,
    emitted, refused or cancelled, reproduced from the artifact
    alone."""
    doc = _load_json(os.path.join(run_dir, "elastic.json"))
    if not isinstance(doc, dict):
        return None
    decisions = [d for d in doc.get("decisions", ())
                 if isinstance(d, dict)]
    return {
        "enabled": bool(doc.get("enabled")),
        "arbiter": bool(doc.get("arbiter")),
        "current_np": doc.get("current_np"),
        "available_np": doc.get("available_np"),
        "transitions": doc.get("transitions") or {},
        "decisions": decisions,
    }


def _diagnose_serving(events, by_rank, top_n=5):
    """Serving-run section (or None for pure gang dirs): slowest
    requests by TTFT, the admission rejection/deferral breakdown, and
    the batch-utilization summary — sourced from the ``cat="serving"``
    span tree plus the ``server_*``/``engine_*`` metric series a
    :class:`~sparkdl_tpu.models.server.ServingFrontend` run leaves."""
    req_spans = [e for e in events
                 if e.get("cat") == "serving"
                 and e.get("name") == "request" and e.get("ph") == "X"]
    srv = {}
    for series in by_rank.values():
        for kind in ("counters", "gauges", "histograms"):
            for (name, labels), v in series.get(kind, {}).items():
                if name.startswith(("server_", "engine_")):
                    srv.setdefault(kind, {})[(name, labels)] = v
    if not req_spans and not srv:
        return None

    by_code = {}
    for (name, labels), v in srv.get("counters", {}).items():
        if name == "server_requests_total":
            by_code[dict(labels).get("code", "?")] = int(v)
    rejections = {}
    for (name, labels), v in srv.get("counters", {}).items():
        if name in ("server_admission_rejections_total",
                    "engine_admission_deferrals_total"):
            reason = dict(labels).get("reason", "?")
            if name.startswith("engine_"):
                reason += " (deferred, requeued)"
            rejections[reason] = int(v)

    slowest = sorted(
        (e.get("args", {}) for e in req_spans
         if e.get("args", {}).get("ttft_s") is not None),
        key=lambda a: a["ttft_s"], reverse=True,
    )[:top_n]
    slowest = [{k: a.get(k) for k in
                ("rid", "ttft_s", "queue_wait_s", "tokens",
                 "tokens_per_sec", "code", "prompt_len")}
               for a in slowest]

    util = srv.get("histograms", {}).get(("engine_batch_utilization", ()))
    utilization = None
    if util and util.get("count"):
        utilization = {
            "mean": round(util["sum"] / util["count"], 4),
            "chunks": int(util["count"]),
        }
    return {
        "requests": len(req_spans),
        "by_code": by_code,
        "slowest_requests_by_ttft": slowest,
        "admission_rejections": rejections,
        "batch_utilization": utilization,
    }


def _diagnose_memory(run_dir, by_rank, health):
    """The memory section (or None when the run carried no memory
    evidence): per-rank category tables from the ``mem_bytes{category}``
    / ``host_rss_bytes`` gauges (the beacon samples in ``health.json``
    fill gaps for a rank whose final flush never landed), the leak
    alerts' named categories from ``alerts.json``, and every
    ``oom_report*.json`` the mem ``oom_guard`` wrote — the OOM verdict
    that flips the doctor's exit code. Artifact-only, like the rest."""
    ranks = {}
    for rank_label, series in by_rank.items():
        if not rank_label.isdigit():
            continue
        entry = {}
        rss = _gauge(series, "host_rss_bytes")
        if rss is not None:
            entry["rss_bytes"] = rss
        cats = {}
        for (name, labels), v in series.get("gauges", {}).items():
            if name == "mem_bytes":
                cat = dict(labels).get("category")
                if cat is not None:
                    cats[cat] = v
        if cats:
            entry["categories"] = cats
        if entry:
            ranks[rank_label] = entry
    for att in (health or {}).get("attempts", []):
        for rank_s, info in (att.get("ranks") or {}).items():
            mem = info.get("mem") or {}
            if not mem:
                continue
            entry = ranks.setdefault(str(rank_s), {})
            if entry.get("rss_bytes") is None \
                    and mem.get("rss") is not None:
                entry["rss_bytes"] = mem["rss"]
            cats = entry.setdefault("categories", {})
            for cat, v in (mem.get("categories") or {}).items():
                cats.setdefault(cat, v)
            if mem.get("unattributed") is not None:
                cats.setdefault("unattributed", mem["unattributed"])
            if not cats:
                del entry["categories"]

    leaks = []
    alerts = _load_json(os.path.join(run_dir, "alerts.json")) or {}
    for rec in alerts.get("alerts") or ():
        if rec.get("rule") in ("hbm_leak", "host_rss_growth"):
            d = rec.get("detail") or {}
            leaks.append({
                "rule": rec.get("rule"),
                "rank": rec.get("rank"),
                "category": d.get("category"),
                "slope_bytes_per_step": d.get("slope_bytes_per_step"),
                "threshold_bytes_per_step":
                    d.get("threshold_bytes_per_step"),
            })

    ooms = []
    for p in sorted(glob.glob(os.path.join(run_dir,
                                           "oom_report*.json"))):
        rep = _load_json(p)
        if not isinstance(rep, dict):
            continue
        ooms.append({
            "file": os.path.basename(p),
            "phase": rep.get("phase"),
            "rank": rep.get("rank"),
            "error": str(rep.get("error") or "")[:400],
            "categories": rep.get("categories") or {},
            "unattributed": rep.get("unattributed"),
            "host_rss_bytes": rep.get("host_rss_bytes"),
            "device": rep.get("device") or {},
            "static_budget_bytes": rep.get("static_budget_bytes"),
            "largest_buffers": (rep.get("largest_buffers") or [])[:3],
            "hints": rep.get("hints") or [],
        })

    if not ranks and not leaks and not ooms:
        return None
    return {"ranks": ranks, "leaks": leaks, "oom_reports": ooms,
            "oom": bool(ooms)}


def diagnose(run_dir):
    """Build the structured diagnosis dict for one run dir, or None
    when the directory holds no recognizable artifacts."""
    timeline = _load_json(os.path.join(run_dir, "timeline.json"))
    metrics = _load_json(os.path.join(run_dir, "metrics.json"))
    health = _load_json(os.path.join(run_dir, "health.json"))
    # Crash path: a process SIGKILLed between artifact writes (a
    # serving frontend killed mid-burst — or before its first write,
    # leaving no timeline.json at all) still mirrored its newest
    # events into the flight-recorder ring in the run dir. Recover
    # the tail straight from the mmap file and MERGE it: any ring
    # event not already in timeline.json is story the kill cut off
    # (flightrec has no jax; the doctor stays artifact-only).
    from sparkdl_tpu.observe.flightrec import recover_job_dir

    ring_events = []
    for evs in recover_job_dir(run_dir).values():
        ring_events.extend(e for e in evs if isinstance(e, dict))
    fixit = _diagnose_fixit(run_dir)
    # An OOM-killed process may have written NOTHING but its report —
    # a dir holding only oom_report.json still diagnoses.
    has_oom = bool(glob.glob(os.path.join(run_dir, "oom_report*.json")))
    if (timeline is None and metrics is None and health is None
            and not ring_events and fixit is None and not has_oom):
        return None

    events = [e for e in (timeline or {}).get("traceEvents", ())
              if isinstance(e, dict) and e.get("ph") != "M"]

    def _ev_key(e):
        # stable under the ring's oversized-args truncation (which
        # keeps name/ph/ts/tid) — dedupe must not resurrect events
        # the timeline already has in full
        return (e.get("ts"), e.get("name"), e.get("tid"), e.get("ph"))

    seen = {_ev_key(e) for e in events}
    ring_fresh = [e for e in ring_events
                  if e.get("ph") != "M" and _ev_key(e) not in seen]
    events.extend(ring_fresh)

    def named(name):
        return [e for e in events if e.get("name") == name]

    # -- verdict: health.json is authoritative, the timeline's
    # health.hang instant corroborates (either alone suffices — the
    # doctor must reproduce the verdict from whatever survived).
    verdict = None
    stalled, silent = set(), set()
    attempts = (health or {}).get("attempts", [])
    for att in attempts:
        if att.get("hang_verdict"):
            verdict = att["hang_verdict"]
        stalled.update(att.get("stalled", ()))
        silent.update(att.get("silent", ()))
    for ev in named("health.hang"):
        verdict = verdict or ev.get("args", {}).get("verdict")
        stalled.update(ev.get("args", {}).get("stalled", ()))
        silent.update(ev.get("args", {}).get("silent", ()))
    for ev in named("health.stall"):
        rank = ev.get("args", {}).get("rank")
        if rank is not None:
            stalled.add(rank)
    for ev in named("health.silent"):
        rank = ev.get("args", {}).get("rank")
        if rank is not None:
            silent.add(rank)

    # -- per-rank state: detector summaries first, gauge fallback.
    # Source the forensics from the attempt that HUNG, not the last
    # one — a clean resumed attempt overwrites step/collective with
    # its own (restarted) values and would repaint the postmortem.
    ranks = {}
    hung_attempts = [a for a in attempts if a.get("hang_verdict")]
    for att in (hung_attempts or attempts):
        for rank_s, info in (att.get("ranks") or {}).items():
            ranks[int(rank_s)] = {
                "step": info.get("step"),
                "collective": info.get("collective"),
                "hbm": info.get("hbm") or {},
            }
        if hung_attempts:
            break   # first hung attempt is the incident
    by_rank = _series_by_rank(metrics)
    for rank_label, series in by_rank.items():
        if not rank_label.isdigit():
            continue
        rank = int(rank_label)
        info = ranks.setdefault(
            rank, {"step": None, "collective": None, "hbm": {}})
        if info["step"] is None:
            step = _gauge(series, "worker_step")
            if step is not None:
                info["step"] = int(step)
        for kind in ("peak", "in_use", "limit", "live_buffers"):
            v = _gauge(series, "device_hbm_bytes", kind=kind)
            if v is not None and kind not in info["hbm"]:
                info["hbm"][kind] = v

    # -- supervisor story from the driver lane
    failures = [
        {"attempt": e.get("args", {}).get("attempt"),
         "verdict": e.get("args", {}).get("verdict"),
         "cause": e.get("args", {}).get("cause")}
        for e in named("gang.failure")
    ]
    resumes = [e.get("args", {}) for e in named("gang.resume")]
    # Elastic resume: every resharded restore left a gang.reshard
    # event carrying the recorded→surviving axes, the bytes it moved
    # and the memory-accounted high water vs the plan's bound — the
    # whole topology transition, reproducible from artifacts alone.
    reshards = [e.get("args", {}) for e in named("gang.reshard")]
    hang_causes = [f for f in failures
                   if "hang" in str(f.get("cause", "")).lower()]
    if verdict is None and hang_causes:
        # Last resort: the supervisor recorded a HANG cause even
        # though health.json and the health.* instants were lost.
        m = re.search(r"HANG \((\w+)\)", hang_causes[0].get("cause") or "")
        verdict = m.group(1) if m else "hung"

    stack_dumps = {
        int(os.path.basename(p)[len("stack-rank-"):-len(".txt")]): p
        for p in glob.glob(os.path.join(run_dir, "stack-rank-*.txt"))
    }
    flight = {}
    for p in glob.glob(os.path.join(run_dir, "flightrec-rank-*.json")):
        doc = _load_json(p)
        if doc is not None:
            flight[int(doc.get("rank", -1))] = len(doc.get("events", ()))

    chaos = sorted({e.get("name") for e in events
                    if e.get("cat") == "chaos"})

    return {
        "run_dir": run_dir,
        "recovered_from_flight_recorder": bool(ring_fresh),
        "flight_recorder_recovered_events": len(ring_fresh),
        "serving": _diagnose_serving(events, by_rank),
        "memory": _diagnose_memory(run_dir, by_rank, health),
        "alerts": _diagnose_alerts(run_dir),
        "forensics": _diagnose_forensics(run_dir),
        "elastic": _diagnose_elastic(run_dir),
        "perf": _diagnose_perf(run_dir, events, by_rank),
        "comms": _diagnose_comms(run_dir, by_rank),
        "fixit": fixit,
        "hang": verdict is not None,
        "verdict": verdict,
        "stalled_ranks": sorted(stalled),
        "silent_ranks": sorted(silent),
        "ranks": {str(r): ranks[r] for r in sorted(ranks)},
        "failures": failures,
        "resumes": resumes,
        "reshards": reshards,
        "stack_dumps": {str(r): os.path.basename(p)
                        for r, p in sorted(stack_dumps.items())},
        "flight_recorder_events": {str(r): n
                                   for r, n in sorted(flight.items())},
        "chaos_injections": chaos,
    }


def render_text(diag):
    lines = [f"observe.doctor: {diag['run_dir']}"]
    if diag["hang"]:
        lines.append(f"verdict: HANG ({diag['verdict']})")
    else:
        lines.append("verdict: no hang found")
    if (diag.get("memory") or {}).get("oom"):
        n = len(diag["memory"]["oom_reports"])
        lines.append(f"verdict: OOM ({n} report(s))")
    stalled = set(diag["stalled_ranks"])
    silent = set(diag["silent_ranks"])
    for rank_s, info in diag["ranks"].items():
        rank = int(rank_s)
        state = ("stalled" if rank in stalled
                 else "silent" if rank in silent
                 else "progressed")
        line = f"  rank {rank}: {state}"
        if info.get("step") is not None:
            line += (f" @ step {info['step']}" if state == "stalled"
                     else f" to step {info['step']}")
        if info.get("collective"):
            line += f", last entered {info['collective']}"
        hbm = info.get("hbm") or {}
        peak = hbm.get("peak", hbm.get("in_use",
                                       hbm.get("live_buffers")))
        if peak is not None:
            line += f"; HBM high-water {_fmt_bytes(peak)}"
        lines.append(line)
    if diag["stack_dumps"]:
        lines.append("stack dumps: " + ", ".join(
            f"rank {r} ({name})"
            for r, name in diag["stack_dumps"].items()))
    if diag["flight_recorder_events"]:
        lines.append("flight recorder tails: " + ", ".join(
            f"rank {r} ({n} events)"
            for r, n in diag["flight_recorder_events"].items()))
    if diag["failures"]:
        causes = "; ".join(
            f"attempt {f.get('attempt')}: {f.get('verdict')} — "
            f"{f.get('cause')}" for f in diag["failures"])
        lines.append(f"supervisor: {len(diag['failures'])} classified "
                     f"failure(s): {causes}")
    if diag["resumes"]:
        steps = ", ".join(str(r.get("resume_step")) for r in diag["resumes"])
        lines.append(f"resumed: {len(diag['resumes'])} relaunch(es) "
                     f"(resume step(s): {steps})")
    for r in diag.get("reshards") or ():
        def axes_s(a):
            return ("{" + ", ".join(f"{k}={v}" for k, v in
                                    sorted((a or {}).items())) + "}")
        line = (f"reshard: {r.get('direction')} "
                f"{axes_s(r.get('source_axes'))} -> "
                f"{axes_s(r.get('target_axes'))} at step "
                f"{r.get('step')}: {r.get('params')} param(s) in "
                f"{r.get('groups')} group(s), "
                f"{_fmt_bytes(r.get('bytes_moved'))} moved")
        hw = r.get("high_water_accounted_bytes")
        bound = r.get("restore_high_water_bytes")
        if hw is not None:
            line += f"; restore high-water {_fmt_bytes(hw)}"
            if bound is not None:
                line += f" (plan bound {_fmt_bytes(bound)})"
            hbm = r.get("hbm_bytes")
            if hbm:
                line += f" vs HBM {_fmt_bytes(hbm)}"
        lines.append(line)
    if diag["chaos_injections"]:
        lines.append("chaos injections on the timeline: "
                     + ", ".join(diag["chaos_injections"]))
    if diag.get("recovered_from_flight_recorder"):
        lines.append(
            f"NOTE: {diag.get('flight_recorder_recovered_events')} "
            "event(s) recovered from the flight-recorder ring "
            "(the process died before its final artifact write)")
    alerts = diag.get("alerts")
    if alerts:
        fired = alerts.get("fired") or []
        if not alerts.get("enabled"):
            pass
        elif not fired:
            lines.append(
                f"alerts: none fired ({len(alerts.get('rules') or [])}"
                " rule(s) evaluated)")
        else:
            from sparkdl_tpu.observe.alerts import format_alert_line

            lines.append(f"alerts: {len(fired)} fired")
            for a in fired:
                lines.append("  " + format_alert_line(a))
    elastic = diag.get("elastic")
    if elastic and elastic.get("enabled"):
        decisions = elastic.get("decisions") or []
        head = (f"elastic: {len(decisions)} decision(s)"
                if decisions else "elastic: enabled, no decisions")
        if elastic.get("arbiter"):
            head += " (arbiter on)"
        lines.append(head)
        for d in decisions:
            line = (f"  [{d.get('direction')}] np {d.get('from_np')} "
                    f"-> {d.get('to_np')} ({d.get('reason')}): "
                    f"{d.get('outcome')}")
            if d.get("resume_step") is not None:
                line += f" from step {d['resume_step']}"
            lines.append(line)
    perf = diag.get("perf")
    if perf:
        lines.append("where the time went (per step-thread second):")
        for rank_s, p in sorted(perf.items(), key=lambda kv: kv[0]):
            fr = p.get("fractions") or {}
            parts = ", ".join(
                f"{name.replace('_', ' ')} {fr[name] * 100:.1f}%"
                for name in ("compute", "collective", "host_callback",
                             "data_wait", "checkpoint")
                if isinstance(fr.get(name), (int, float))
                and fr[name] > 0.0005
            )
            line = (f"  rank {rank_s}: {parts or 'no attributed time'}"
                    f" over {p.get('steps')} step(s)")
            eff = p.get("overlap_efficiency")
            if eff is not None:
                line += f"; collective overlap {eff * 100:.0f}%"
            if p.get("mfu") is not None:
                line += f"; MFU {p['mfu'] * 100:.2f}%"
            wait = p.get("inter_step_data_wait_s")
            if isinstance(wait, (int, float)) and wait > 0.0005:
                line += f"; +{wait:.3f}s data wait between steps"
            lines.append(line)
    forensics = diag.get("forensics")
    if forensics:
        from sparkdl_tpu.observe.perf import render_diff_lines

        reports = forensics.get("reports") or []
        captures = forensics.get("captures") or []
        lines.append(
            f"perf forensics: {len(reports)} regression report(s), "
            f"{len(captures)} capture(s)")
        for rep in reports:
            head = (f"  [{rep.get('rule') or rep.get('reason')}] "
                    f"rank {rep.get('rank')}")
            cap = rep.get("capture") or {}
            if cap.get("report"):
                head += f" (capture: {cap['report']})"
            lines.append(head)
            diff = rep.get("diff")
            if diff:
                lines.extend(render_diff_lines(diff, indent="    "))
            else:
                lines.append(
                    "    (no attributable windows to diff — see the "
                    "capture artifacts)")
        for c in captures:
            line = (f"  capture rank {c.get('rank')} "
                    f"[{c.get('rule') or c.get('reason')}] "
                    f"({c['file']}): {c.get('steps_captured')} step(s)")
            if isinstance(c.get("window_s"), (int, float)):
                line += f" in {c['window_s']:.1f}s"
            fr = c.get("fractions") or {}
            parts = ", ".join(
                f"{name.replace('_', ' ')} {fr[name] * 100:.1f}%"
                for name in ("compute", "collective", "host_callback",
                             "data_wait", "checkpoint")
                if isinstance(fr.get(name), (int, float))
                and fr[name] > 0.0005)
            if parts:
                line += f"; {parts}"
            if c.get("trace_dir"):
                line += f"; trace {c['trace_dir']}/"
            lines.append(line)
        if forensics.get("trace_dirs"):
            lines.append("  xprof traces recovered: "
                         + ", ".join(forensics["trace_dirs"]))
    comms = diag.get("comms")
    if comms:
        pred = comms.get("predicted_wire_bytes_per_device_per_step")
        for rep in comms.get("reports", ()):
            t = rep.get("totals") or {}
            lines.append(
                f"static comms budget [{rep.get('name')}]: "
                f"{t.get('count')} collective(s), "
                f"{_fmt_bytes(t.get('wire_bytes_per_device'))}/device"
                "/step predicted on the wire "
                f"(~{(t.get('predicted_s') or 0) * 1e3:.3f} ms, ring, "
                f"{rep.get('device_kind')})")
        for rank_s, m in sorted(comms.get("measured_by_rank",
                                          {}).items()):
            line = (f"  measured rank {rank_s}: "
                    f"{_fmt_bytes(m.get('bytes_total'))} via "
                    + ", ".join(f"{op} {_fmt_bytes(b)}"
                                for op, b in
                                sorted(m.get("bytes_by_op",
                                             {}).items())))
            if m.get("steps"):
                line += f" over {m['steps']} step(s)"
            ratio = m.get("per_step_vs_predicted")
            if ratio is not None:
                line += f"; {ratio:.2f}x the predicted budget/step"
            elif pred is None:
                line += (" (no static budget to compare — the "
                         "pre-flight prices registered steps only)")
            lines.append(line)
    fixit = diag.get("fixit")
    if fixit:
        for rep in fixit.get("reports", ()):
            s = rep.get("summary") or {}
            lines.append(
                f"suggested fixes [{rep.get('name')}] "
                f"({rep.get('mode')}): {s.get('proposed', 0)} "
                f"proposed, {s.get('verified', 0)} verified, "
                f"{s.get('applied', 0)} applied, "
                f"{s.get('degraded', 0)} degraded"
                + (f"; {rep['unfixable']} unfixable finding(s)"
                   if rep.get("unfixable") else ""))
            for fx in rep.get("fixes", ()):
                state = ("applied" if fx.get("applied")
                         else "verified" if fx.get("verified")
                         else "degraded")
                line = (f"  [{state}] {fx.get('rule_id')} -> "
                        f"{fx.get('action')}")
                delta = fx.get("peak_bytes_delta")
                if isinstance(delta, (int, float)):
                    line += f" (peak {_fmt_bytes(delta)})"
                if fx.get("degrade_reason"):
                    line += f": {fx['degrade_reason']}"
                elif fx.get("description"):
                    line += f": {fx['description']}"
                proofs = fx.get("proofs_ok") or {}
                if proofs:
                    line += (" [proofs: " + ", ".join(
                        f"{k}={'ok' if v else 'FAIL'}"
                        for k, v in sorted(proofs.items())) + "]")
                lines.append(line)
    memory = diag.get("memory")
    if memory:
        lines.append("memory:")
        for rank_s, entry in sorted(memory["ranks"].items()):
            cats = entry.get("categories") or {}
            parts = ", ".join(
                f"{c} {_fmt_bytes(v)}"
                for c, v in sorted(cats.items(),
                                   key=lambda kv: -(kv[1] or 0)))
            line = f"  rank {rank_s}:"
            if entry.get("rss_bytes") is not None:
                line += f" host RSS {_fmt_bytes(entry['rss_bytes'])}"
            if parts:
                line += f"; {parts}"
            lines.append(line)
        for leak in memory["leaks"]:
            where = (f" rank {leak['rank']}"
                     if leak.get("rank") is not None else "")
            line = (f"  leak [{leak['rule']}]{where}: category "
                    f"'{leak.get('category')}' growing "
                    f"{_fmt_bytes(leak.get('slope_bytes_per_step'))}"
                    "/step")
            thr = leak.get("threshold_bytes_per_step")
            if thr is not None:
                line += f" (threshold {_fmt_bytes(thr)}/step)"
            lines.append(line)
        for oom in memory["oom_reports"]:
            where = (f" rank {oom['rank']}"
                     if oom.get("rank") is not None else "")
            lines.append(f"  OOM [{oom.get('phase')}]{where} "
                         f"({oom['file']}): {oom.get('error')}")
            cats = oom.get("categories") or {}
            if cats:
                lines.append("    categories at death: " + ", ".join(
                    f"{c} {_fmt_bytes(v)}"
                    for c, v in sorted(cats.items(),
                                       key=lambda kv: -(kv[1] or 0))))
            if oom.get("unattributed") is not None:
                lines.append("    unattributed: "
                             + _fmt_bytes(oom["unattributed"]))
            peak = (oom.get("device") or {}).get("peak")
            budget = oom.get("static_budget_bytes")
            if peak is not None or budget is not None:
                lines.append(
                    f"    measured peak {_fmt_bytes(peak)} vs static "
                    f"budget {_fmt_bytes(budget)}")
            for buf in oom.get("largest_buffers") or ():
                lines.append(
                    f"    largest: {buf.get('count')} x "
                    f"{buf.get('shape')} {buf.get('dtype')} = "
                    f"{_fmt_bytes(buf.get('bytes'))}")
            for hint in oom.get("hints") or ():
                lines.append(f"    hint: {hint}")
    srv = diag.get("serving")
    if srv:
        codes = ", ".join(f"{c}: {n}" for c, n in
                          sorted(srv["by_code"].items()))
        lines.append(f"serving: {srv['requests']} traced request(s)"
                     + (f" ({codes})" if codes else ""))
        if srv["slowest_requests_by_ttft"]:
            lines.append("  slowest requests by TTFT:")
            for r in srv["slowest_requests_by_ttft"]:
                extra = ""
                if r.get("queue_wait_s") is not None:
                    extra += f", queued {r['queue_wait_s'] * 1e3:.1f} ms"
                if r.get("tokens_per_sec"):
                    extra += f", {r['tokens_per_sec']:.1f} tok/s"
                lines.append(
                    f"    rid {r.get('rid')}: "
                    f"ttft {r['ttft_s'] * 1e3:.1f} ms"
                    f" ({r.get('tokens')} tok, code {r.get('code')}"
                    f"{extra})")
        if srv["admission_rejections"]:
            lines.append("  admission rejections: " + "; ".join(
                f"{reason}: {n}" for reason, n in
                sorted(srv["admission_rejections"].items())))
        util = srv.get("batch_utilization")
        if util:
            lines.append(
                f"  batch utilization: {util['mean']:.2f} mean over "
                f"{util['chunks']} decode chunk(s)")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.observe.doctor",
        description="Postmortem diagnosis over a merged telemetry run "
                    "dir; exits nonzero when a hang or OOM verdict is "
                    "found.",
    )
    parser.add_argument("run_dir", help="a run-* dir under "
                        "SPARKDL_TPU_TELEMETRY_DIR (or a copy of one)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    args = parser.parse_args(argv)

    diag = diagnose(args.run_dir)
    if diag is None:
        print(f"observe.doctor: no telemetry artifacts under "
              f"{args.run_dir} (expected timeline.json / metrics.json "
              f"/ health.json)", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(diag, indent=2, sort_keys=True))
    else:
        print(render_text(diag))
    oom = (diag.get("memory") or {}).get("oom")
    return 1 if (diag["hang"] or oom) else 0


if __name__ == "__main__":
    sys.exit(main())
