"""``observe.alerts``: a declarative streaming SLO rule engine over
the LIVE gang telemetry stream.

PR 3/5/7 built the post-hoc half of the single-pane-of-glass story
(run-dir artifacts, hang post-mortems, attribution — all read by
``observe.doctor`` after the fact); the only *in-flight* detector was
the binary hang/stall verdict. Operators of a long gang need the
mid-run regression signal: "step time doubled twenty minutes ago",
"rank 3's beats are getting sparse", "HBM high-water is 94% of the
budget" — before the run dies, not in the postmortem.

This module is that signal. A small **declarative rule catalog**
(:data:`RULES`) is evaluated by :class:`AlertEngine.poll` inside the
launcher's existing monitor loop — the same cadence-throttled pass
that polls the :class:`~sparkdl_tpu.observe.health.HangDetector` —
over three live inputs that already exist:

- the :class:`~sparkdl_tpu.observe.aggregate.GangTelemetry` event
  journal (rolling window of execute-phase step spans → rolling
  median step time, rolling overlap efficiency);
- the merged live metric snapshots (``mfu``, ``server_queue_depth``);
- the detector's per-rank liveness (beat ages, HBM gauges from the
  PR 5 heartbeat payloads).

Rule catalog (severities in parentheses; each rule latches ONCE per
(rule, rank) per gang launch — a sustained condition is one alert,
not a page storm):

``step_time_regression`` (critical)
    Rolling median execute step time over the window exceeds
    ``SPARKDL_TPU_ALERT_STEP_FACTOR`` × the baseline. The baseline
    is, in priority order: ``SPARKDL_TPU_ALERT_STEP_BASELINE_S``
    (explicit seconds), a committed ledger record
    (``benchmarks/results/history.jsonl`` — newest entry carrying a
    ``step_time_s`` / ``train_step_seconds_mean`` metric), else
    self-calibrated: the smallest rolling median this run has shown
    (so a mid-run slowdown fires against the run's own healthy past).
``heartbeat_gap`` (warning)
    A progressing rank's last beat is older than
    ``SPARKDL_TPU_ALERT_HEARTBEAT_GAP_FRAC`` × the stall window —
    the early warning BELOW the hang threshold (a rank the detector
    already classed stalled/silent is the hang machinery's story).
``hbm_high_water`` (critical)
    A rank's heartbeat HBM gauge (in_use, falling back to peak)
    crossed ``SPARKDL_TPU_ALERT_HBM_FRAC`` of the per-chip
    ``hbm_capacity_bytes`` budget (PR 8's table; dormant on chips
    with no budget unless ``SPARKDL_TPU_HBM_BYTES`` pins one).
``queue_depth_growth`` (warning)
    Total serving queue depth — the merged ``server_queue_depth``
    gauge plus every :class:`~sparkdl_tpu.models.fleet.FleetFrontend`
    registered in-process with the statusz module (a fleet's own
    registry is private and never crosses the control plane) — is
    growing faster than ``SPARKDL_TPU_ALERT_QUEUE_GROWTH`` per
    second over the window (dormant unless the knob is set —
    growth-rate floors are workload-specific).
``server_ttft`` (warning)
    Any in-process registered fleet's p99 time-to-first-token —
    estimated from its ``server_ttft_seconds`` histogram buckets —
    exceeds ``SPARKDL_TPU_ALERT_TTFT_P99_S`` seconds (dormant unless
    set — TTFT SLOs are workload-specific). With the chip-budget
    arbiter on (ISSUE 16), this firing is a demand signal: training
    yields chips to the fleet.
``hbm_leak`` (critical)
    A rank's device memory (the beacon's mem sample, ``hbm``) is
    growing faster than ``SPARKDL_TPU_ALERT_HBM_LEAK_BYTES_PER_STEP``
    bytes per unit progress — a robust slope (median of per-interval
    slopes) over the rolling sample window, normalized by the rank's
    own step/request progress so a fast rank and a slow rank leak at
    the same *per-step* rate fire identically (dormant unless set).
    The firing names the fastest-growing category from the beacon's
    category table — what ``observe.doctor`` renders as the leak
    suspect.
``host_rss_growth`` (warning)
    Same slope machinery over the beacon's host RSS sample — the
    host-side leak detector (prefetch buffers, compile cache,
    plain-Python leaks), threshold
    ``SPARKDL_TPU_ALERT_RSS_GROWTH_BYTES_PER_STEP`` bytes per unit
    progress (dormant unless set). Provable end-to-end on CPU CI via
    the ``SPARKDL_TPU_CHAOS_LEAK_BYTES_PER_STEP`` injector.
``mfu_drop`` (warning)
    Any rank's live ``mfu`` gauge fell below
    ``SPARKDL_TPU_ALERT_MFU_MIN`` (dormant unless set).
``overlap_drop`` (warning)
    Rolling window overlap efficiency (PR 10's metric, recomputed
    live from the journal) fell below
    ``SPARKDL_TPU_ALERT_OVERLAP_MIN`` (dormant unless set).

Every firing emits a typed ``alert.<rule>`` timeline instant
(``cat="alert"``, landing on the driver lane of the merged trace), a
``gang_alerts_total{rule,severity}`` counter, and a record in the
engine's report — written to the run dir as ``alerts.json`` (via
:meth:`GangTelemetry.add_alert_report`), which ``observe.doctor``
renders in its "alerts" section, artifact-only. A clean run writes
``alerts.json`` too, with an empty ``alerts`` list: the
false-positive guard is auditable, not just absent.

Zero-overhead contract (the PR 3 latch, extended): the engine is only
constructed by :func:`maybe_make_engine` when BOTH telemetry is
opted in and ``SPARKDL_TPU_ALERTS`` is truthy. Without the env there
is no engine object, no rule evaluation, no per-step work, no
thread — the monitor loop's ``engine is not None`` test is the whole
cost.
"""

import collections
import os
import time

ALERTS_ENV = "SPARKDL_TPU_ALERTS"
WINDOW_S_ENV = "SPARKDL_TPU_ALERT_WINDOW_S"
CHECK_S_ENV = "SPARKDL_TPU_ALERT_CHECK_S"
STEP_FACTOR_ENV = "SPARKDL_TPU_ALERT_STEP_FACTOR"
STEP_BASELINE_ENV = "SPARKDL_TPU_ALERT_STEP_BASELINE_S"
MIN_STEPS_ENV = "SPARKDL_TPU_ALERT_MIN_STEPS"
MFU_MIN_ENV = "SPARKDL_TPU_ALERT_MFU_MIN"
OVERLAP_MIN_ENV = "SPARKDL_TPU_ALERT_OVERLAP_MIN"
QUEUE_GROWTH_ENV = "SPARKDL_TPU_ALERT_QUEUE_GROWTH"
TTFT_P99_ENV = "SPARKDL_TPU_ALERT_TTFT_P99_S"
HBM_FRAC_ENV = "SPARKDL_TPU_ALERT_HBM_FRAC"
HEARTBEAT_GAP_FRAC_ENV = "SPARKDL_TPU_ALERT_HEARTBEAT_GAP_FRAC"
HBM_LEAK_ENV = "SPARKDL_TPU_ALERT_HBM_LEAK_BYTES_PER_STEP"
RSS_GROWTH_ENV = "SPARKDL_TPU_ALERT_RSS_GROWTH_BYTES_PER_STEP"

DEFAULT_WINDOW_S = 60.0
DEFAULT_CHECK_S = 5.0
DEFAULT_STEP_FACTOR = 2.0
DEFAULT_MIN_STEPS = 5
DEFAULT_HBM_FRAC = 0.9
DEFAULT_HEARTBEAT_GAP_FRAC = 0.5

ALERTS_SCHEMA = "sparkdl_tpu.observe.alerts/1"

SEV_WARNING = "warning"
SEV_CRITICAL = "critical"

# Ledger metric names accepted as a committed step-time baseline
# (seconds, lower is better) — in practice most gangs self-calibrate,
# but a repo that ledgers its gang step time gets the committed
# baseline for free.
LEDGER_STEP_METRICS = ("step_time_s", "train_step_seconds_mean")

# The declarative catalog: (rule name, severity, evaluator method
# name, one-liner for docs/statusz). Evaluators run in this order and
# return a list of (latch_key, detail_dict) firings.
RULES = (
    ("step_time_regression", SEV_CRITICAL, "_check_step_time",
     "rolling median step time exceeds factor x baseline"),
    ("heartbeat_gap", SEV_WARNING, "_check_heartbeat_gap",
     "beat age beyond the warn fraction of the stall window"),
    ("hbm_high_water", SEV_CRITICAL, "_check_hbm",
     "device HBM in use approaching the per-chip capacity budget"),
    ("hbm_leak", SEV_CRITICAL, "_check_hbm_leak",
     "device memory growing per unit progress beyond the bound"),
    ("host_rss_growth", SEV_WARNING, "_check_rss_growth",
     "host RSS growing per unit progress beyond the bound"),
    ("queue_depth_growth", SEV_WARNING, "_check_queue_growth",
     "server_queue_depth growing faster than the configured rate"),
    ("server_ttft", SEV_WARNING, "_check_server_ttft",
     "fleet p99 time-to-first-token above the configured bound"),
    ("mfu_drop", SEV_WARNING, "_check_mfu",
     "live MFU gauge below the configured floor"),
    ("overlap_drop", SEV_WARNING, "_check_overlap",
     "rolling overlap efficiency below the configured floor"),
)


def alerts_enabled(env=None):
    env = os.environ if env is None else env
    return str(env.get(ALERTS_ENV) or "").strip().lower() not in (
        "", "0", "false", "off")


def _env_float(env, name, default):
    v = env.get(name)
    if v in (None, ""):
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name}={v!r} is not a number") from None


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return None
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def _histogram_quantile(buckets, counts, q):
    """Upper-bound quantile estimate from cumulative-style histogram
    buckets (``buckets`` are the finite upper bounds, ``counts`` the
    per-bucket observation counts, one trailing overflow count
    allowed). Returns the smallest bucket bound whose cumulative count
    reaches ``q`` of the total — the standard Prometheus-style
    conservative estimate — or None when the histogram is empty or
    the quantile lands in the overflow bucket with no finite bound."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            if i < len(buckets):
                return float(buckets[i])
            # Overflow bucket: the best upper bound we have is "beyond
            # the largest finite bucket" — report that bound so the
            # rule still fires when the tail blew past every bucket.
            return float(buckets[-1]) if buckets else None
    return float(buckets[-1]) if buckets else None


def maybe_make_engine(telemetry, detector=None, num_workers=None,
                      env=None):
    """The latch: an :class:`AlertEngine` only when BOTH telemetry is
    live (``telemetry`` is a GangTelemetry) and ``SPARKDL_TPU_ALERTS``
    is set truthy; None otherwise — no object, no evaluation."""
    env = os.environ if env is None else env
    if telemetry is None or not alerts_enabled(env):
        return None
    return AlertEngine(telemetry, detector=detector,
                       num_workers=num_workers, env=env)


class AlertEngine:
    """Streaming rule evaluation over the live gang. ``poll`` is
    called from the launcher monitor loop (throttled internally to
    ``SPARKDL_TPU_ALERT_CHECK_S``); everything else is bookkeeping.
    Thread-safety: poll runs on ONE thread (the monitor loop);
    ``records``/``report`` snapshot under no lock because firings
    only ever append from that same thread."""

    def __init__(self, telemetry, detector=None, num_workers=None,
                 env=None, clock=time.monotonic, wall=time.time):
        env = os.environ if env is None else env
        self._telemetry = telemetry
        self._detector = detector
        self.num_workers = num_workers
        self._clock = clock
        self._wall = wall
        self.window_s = _env_float(env, WINDOW_S_ENV, DEFAULT_WINDOW_S)
        self.check_s = _env_float(env, CHECK_S_ENV, DEFAULT_CHECK_S)
        self.step_factor = _env_float(
            env, STEP_FACTOR_ENV, DEFAULT_STEP_FACTOR)
        self.min_steps = int(_env_float(
            env, MIN_STEPS_ENV, DEFAULT_MIN_STEPS))
        self.hbm_frac = _env_float(env, HBM_FRAC_ENV, DEFAULT_HBM_FRAC)
        self.heartbeat_gap_frac = _env_float(
            env, HEARTBEAT_GAP_FRAC_ENV, DEFAULT_HEARTBEAT_GAP_FRAC)
        self.mfu_min = _env_float(env, MFU_MIN_ENV, None)
        self.overlap_min = _env_float(env, OVERLAP_MIN_ENV, None)
        self.queue_growth = _env_float(env, QUEUE_GROWTH_ENV, None)
        self.ttft_p99_s = _env_float(env, TTFT_P99_ENV, None)
        self.hbm_leak_bps = _env_float(env, HBM_LEAK_ENV, None)
        self.rss_growth_bps = _env_float(env, RSS_GROWTH_ENV, None)
        # Baseline resolution order: explicit env seconds, committed
        # ledger record, self-calibration (the min rolling median the
        # run has shown, per rank).
        explicit = _env_float(env, STEP_BASELINE_ENV, None)
        self._baseline_source = "env" if explicit is not None else None
        self._baselines = {}          # rank -> baseline seconds
        # rank -> the telemetry events of the window that (last)
        # calibrated that rank's baseline — the healthy past the perf
        # forensics differ uses as the baseline side of
        # diff_attribution, so a regression report explains exactly
        # the regression that fired. Empty for env/ledger baselines.
        self._baseline_windows = {}
        self._explicit_baseline = explicit
        if explicit is None:
            ledger = self._ledger_baseline()
            if ledger is not None:
                self._explicit_baseline = ledger
                self._baseline_source = "ledger"
        self._fired = {}              # (rule, rank) -> record
        self._records = []
        self._queue_samples = collections.deque(maxlen=256)
        # rank -> deque of (progress, hbm_bytes, rss_bytes, categories)
        # fed from each poll's live beacon mem samples — the leak
        # rules' rolling window (engine-owned, like _queue_samples).
        self._mem_samples = {}
        self._next_check = 0.0

    # -- elastic world changes -----------------------------------------------

    def set_world(self, num_workers, detector=None):
        """Rebind the engine to a resized gang (ISSUE 16): one engine
        now spans attempts, and an elastic shrink/grow changes both
        the rank universe and each rank's workload share. Always swap
        in the new attempt's detector; on an actual world-size change,
        drop the self-calibrated per-rank step-time baselines and the
        per-rank alert latches for ranks that no longer exist —
        rank k's data shard after a resize is a different rank k, so
        its old healthy floor would fire false regressions (and a
        departed rank's latch would suppress a future real one)."""
        if detector is not None:
            self._detector = detector
        if num_workers is None or num_workers == self.num_workers:
            return
        self.num_workers = num_workers
        # Self-calibrated baselines are per-(rank, shard); all stale.
        # Explicit env / ledger baselines are world-independent and
        # survive untouched (``_explicit_baseline`` is not cleared).
        self._baselines.clear()
        self._baseline_windows.clear()
        if self._baseline_source == "self":
            self._baseline_source = None
        for latch in [k for k in self._fired
                      if isinstance(k[1], int) and k[1] >= num_workers]:
            del self._fired[latch]
        # Leak windows for departed ranks are stale the same way: a
        # relaunched rank k after a resize is a different workload.
        for rank in [r for r in self._mem_samples if r >= num_workers]:
            del self._mem_samples[rank]

    # -- baseline ------------------------------------------------------------

    @staticmethod
    def _ledger_baseline():
        """Newest committed ledger entry carrying a recognized
        step-time metric (seconds), or None. Best-effort: an absent
        or malformed ledger must never break a launch."""
        try:
            from sparkdl_tpu.observe.perf import read_history

            for entry in reversed(read_history()):
                for name in LEDGER_STEP_METRICS:
                    m = (entry.get("metrics") or {}).get(name)
                    if isinstance(m, dict):
                        m = m.get("value")
                    if isinstance(m, (int, float)) and m > 0:
                        return float(m)
        except Exception:
            pass
        return None

    def baseline_for(self, rank):
        if self._explicit_baseline is not None:
            return self._explicit_baseline
        return self._baselines.get(rank)

    def baseline_window(self, rank):
        """The telemetry events the rank's current self-calibrated
        baseline was computed from — the healthy-past side that
        ``perf.diff_attribution`` compares a regressed window against.
        Empty when the baseline came from env/ledger (no window)."""
        return list(self._baseline_windows.get(rank) or ())

    # -- the poll ------------------------------------------------------------

    def poll(self):
        """One throttled evaluation pass; returns the records fired
        by THIS pass (empty between check intervals)."""
        now = self._clock()
        if now < self._next_check:
            return []
        self._next_check = now + self.check_s
        ctx = self._build_context()
        fired = []
        for rule, severity, method, _doc in RULES:
            try:
                firings = getattr(self, method)(ctx) or []
            except Exception:
                # A rule must never take down the monitor loop — a
                # broken evaluator silently skips its pass (the other
                # rules still run) rather than killing the gang watch.
                continue
            for key, detail in firings:
                rec = self._fire(rule, severity, key, detail)
                if rec is not None:
                    fired.append(rec)
        return fired

    def _build_context(self):
        events = self._telemetry.recent_events(self.window_s,
                                               now=self._wall())
        events = self._drop_stale_ranks(events)
        # Execute-phase step durations per rank (seconds), window-
        # scoped — compile spans excluded exactly like observe.perf.
        step_durs = {}
        for rank, evs in events.items():
            durs = [
                float(e.get("dur", 0) or 0) / 1e6
                for e in evs
                if e.get("ph") == "X" and e.get("cat") == "train"
                and (e.get("args") or {}).get("phase") == "execute"
            ]
            if durs:
                step_durs[rank] = durs
        gauges = {}
        try:
            for extra, snap in self._telemetry.live_labeled():
                rank = extra.get("rank")
                for g in snap.get("gauges", ()):
                    gauges.setdefault(g["name"], []).append(
                        (rank, g.get("labels") or {}, g.get("value")))
        except Exception:
            pass
        live = self._detector.live_state() if self._detector else {}
        return {"events": events, "step_durs": step_durs,
                "gauges": gauges, "live": self._drop_stale_ranks(live)}

    def _drop_stale_ranks(self, by_rank):
        """Filter a rank-keyed mapping down to the CURRENT world: after
        an elastic shrink the telemetry window still holds the departed
        ranks' trailing events, and alerting on a rank that was
        deliberately resized away is noise, not signal."""
        world = self.num_workers
        if world is None:
            return by_rank
        return {r: v for r, v in by_rank.items()
                if not (isinstance(r, int) and r >= world)}

    # -- rule evaluators -----------------------------------------------------

    def _check_step_time(self, ctx):
        out = []
        for rank, durs in sorted(ctx["step_durs"].items()):
            if len(durs) < self.min_steps:
                continue
            med = _median(durs)
            base = self.baseline_for(rank)
            if base is None:
                # First qualifying window calibrates; later windows
                # only ever lower it (the run's healthy floor).
                self._baselines[rank] = med
                self._baseline_windows[rank] = list(
                    ctx["events"].get(rank) or ())
                if self._baseline_source is None:
                    self._baseline_source = "self"
                continue
            if self._explicit_baseline is None and med < base:
                self._baselines[rank] = med
                self._baseline_windows[rank] = list(
                    ctx["events"].get(rank) or ())
                continue
            if med > self.step_factor * base:
                out.append((rank, {
                    "rank": rank,
                    "median_step_s": round(med, 6),
                    "baseline_step_s": round(base, 6),
                    "factor": round(med / base, 3),
                    "threshold_factor": self.step_factor,
                    "baseline_source": self._baseline_source,
                    "steps_in_window": len(durs),
                }))
        return out

    def _check_heartbeat_gap(self, ctx):
        detector = self._detector
        if detector is None:
            return []
        warn_at = self.heartbeat_gap_frac * detector.stall_s
        out = []
        for rank, info in sorted(ctx["live"].items()):
            age = info.get("beat_age_s")
            if (info.get("state") == "progressing"
                    and isinstance(age, (int, float))
                    and age > warn_at):
                out.append((rank, {
                    "rank": rank,
                    "beat_age_s": age,
                    "warn_at_s": round(warn_at, 3),
                    "stall_s": detector.stall_s,
                }))
        return out

    def _check_hbm(self, ctx):
        from sparkdl_tpu.observe.perf import hbm_capacity_bytes

        try:
            capacity = hbm_capacity_bytes()
        except Exception:
            capacity = None
        if not capacity:
            return []
        out = []
        for rank, info in sorted(ctx["live"].items()):
            hbm = info.get("hbm") or {}
            used = hbm.get("in_use", hbm.get("peak"))
            if (isinstance(used, (int, float))
                    and used > self.hbm_frac * capacity):
                out.append((rank, {
                    "rank": rank,
                    "hbm_bytes": used,
                    "capacity_bytes": capacity,
                    "fraction": round(used / capacity, 4),
                    "threshold_fraction": self.hbm_frac,
                }))
        return out

    def _ingest_mem_samples(self, ctx):
        """Fold each live rank's beacon mem sample into its rolling
        leak window. Idempotent within a poll (an unchanged
        progress/value pair is not re-appended), so both leak rules
        may call it without double-counting — and samples accumulate
        even while the thresholds are unset, like the queue rule's."""
        for rank, info in ctx["live"].items():
            if not isinstance(rank, int):
                continue
            mem = info.get("mem") or {}
            progress = info.get("progress")
            if not mem or not isinstance(progress, (int, float)):
                continue
            cats = dict(mem.get("categories") or {})
            if mem.get("unattributed") is not None:
                cats["unattributed"] = mem["unattributed"]
            sample = (float(progress), mem.get("hbm"), mem.get("rss"),
                      cats)
            dq = self._mem_samples.setdefault(
                rank, collections.deque(maxlen=256))
            if dq and dq[-1][:3] == sample[:3]:
                continue
            dq.append(sample)

    @staticmethod
    def _robust_slope(points):
        """Median of per-interval slopes over ``[(progress, value)]``
        — one outlier sample (a GC pause, a transient spike) cannot
        fake or mask a trend the way a first-vs-last delta could.
        None when fewer than two progress-advancing intervals carry
        values."""
        slopes = [
            (v1 - v0) / (p1 - p0)
            for (p0, v0), (p1, v1) in zip(points, points[1:])
            if p1 > p0 and v0 is not None and v1 is not None
        ]
        return _median(slopes) if len(slopes) >= 2 else None

    def _mem_growth_firings(self, ctx, idx, threshold):
        """Shared leak evaluator body: per-rank robust slope of sample
        field ``idx`` (1=hbm, 2=rss) per unit progress, fired against
        ``threshold`` bytes/step. Returns (rank, slope, span, window)
        tuples for ranks over the bound."""
        self._ingest_mem_samples(ctx)
        if threshold is None:
            return []
        out = []
        for rank, dq in sorted(self._mem_samples.items()):
            window = list(dq)
            if len(window) < 2:
                continue
            span = window[-1][0] - window[0][0]
            if span < self.min_steps:
                continue   # not enough progress to call a trend
            slope = self._robust_slope(
                [(s[0], s[idx]) for s in window])
            if slope is not None and slope > threshold:
                out.append((rank, slope, span, window))
        return out

    @staticmethod
    def _growing_category(window, span):
        """The fastest-growing category over the window — the leak
        suspect the doctor names. Falls back to 'unattributed' when
        the table is empty (nothing registered = everything leaks
        outside the trees)."""
        first, last = window[0][3] or {}, window[-1][3] or {}
        best, best_rate = None, 0.0
        for cat in set(first) | set(last):
            rate = (last.get(cat, 0) - first.get(cat, 0)) / max(span, 1)
            if rate > best_rate:
                best, best_rate = cat, rate
        return best or "unattributed"

    def _check_hbm_leak(self, ctx):
        out = []
        for rank, slope, span, window in self._mem_growth_firings(
                ctx, 1, self.hbm_leak_bps):
            out.append((rank, {
                "rank": rank,
                "slope_bytes_per_step": round(slope, 1),
                "threshold_bytes_per_step": self.hbm_leak_bps,
                "progress_span": round(span, 1),
                "category": self._growing_category(window, span),
                "hbm_bytes": window[-1][1],
            }))
        return out

    def _check_rss_growth(self, ctx):
        out = []
        for rank, slope, span, window in self._mem_growth_firings(
                ctx, 2, self.rss_growth_bps):
            out.append((rank, {
                "rank": rank,
                "slope_bytes_per_step": round(slope, 1),
                "threshold_bytes_per_step": self.rss_growth_bps,
                "progress_span": round(span, 1),
                # host-side growth has no HBM category table; the
                # category the doctor names IS the host heap
                "category": "host_rss",
                "rss_bytes": window[-1][2],
            }))
        return out

    def _check_queue_growth(self, ctx):
        # Two live sources: the merged server_queue_depth gauge (a
        # worker that exports one through gang telemetry) and any
        # FleetFrontend registered IN-PROCESS with the statusz module
        # — the fleet's own registry is private and never crosses the
        # control plane, so without this the rule could not see the
        # colocated serving tier at all.
        depths = ctx["gauges"].get("server_queue_depth") or []
        total = sum(v for _r, _l, v in depths
                    if isinstance(v, (int, float)))
        have_source = bool(depths)
        try:
            from sparkdl_tpu.observe.statusz import fleet_status

            for fleet in fleet_status() or ():
                d = fleet.get("queue_depth")
                if isinstance(d, (int, float)):
                    total += d
                    have_source = True
        except Exception:
            pass
        if not have_source:
            return []
        now = self._clock()
        self._queue_samples.append((now, total))
        if self.queue_growth is None:
            return []
        cutoff = now - self.window_s
        window = [(t, v) for t, v in self._queue_samples if t >= cutoff]
        if len(window) < 2:
            return []
        (t0, v0), (t1, v1) = window[0], window[-1]
        span = t1 - t0
        if span < self.window_s / 4:
            return []   # not enough history to call a trend yet
        rate = (v1 - v0) / span
        if rate > self.queue_growth:
            return [(None, {
                "depth": v1,
                "growth_per_s": round(rate, 4),
                "threshold_per_s": self.queue_growth,
                "window_s": round(span, 1),
            })]
        return []

    def _check_server_ttft(self, ctx):
        # Fleet-level SLO, not a rank-level one: every FleetFrontend
        # registered in-process with the statusz module exports a
        # server_ttft_seconds histogram; estimate p99 from its buckets
        # (conservative upper bound) and fire once per fleet index
        # when the bound is configured and exceeded.
        if self.ttft_p99_s is None:
            return []
        try:
            from sparkdl_tpu.observe.statusz import live_fleets
        except Exception:
            return []
        out = []
        for idx, fleet in enumerate(live_fleets() or ()):
            metrics = getattr(fleet, "metrics", None)
            if metrics is None:
                continue
            try:
                snap = metrics.snapshot()
            except Exception:
                continue
            for h in snap.get("histograms", ()):
                if h.get("name") != "server_ttft_seconds":
                    continue
                count = h.get("count") or sum(h.get("counts") or ())
                if count < self.min_steps:
                    continue
                p99 = _histogram_quantile(
                    h.get("buckets") or (), h.get("counts") or (), 0.99)
                if p99 is not None and p99 > self.ttft_p99_s:
                    out.append((f"fleet{idx}", {
                        "fleet": idx,
                        "ttft_p99_s": round(p99, 6),
                        "threshold_s": self.ttft_p99_s,
                        "requests": count,
                    }))
        return out

    def _check_mfu(self, ctx):
        if self.mfu_min is None:
            return []
        out = []
        for rank, labels, v in ctx["gauges"].get("mfu", ()):
            # merged-snapshot rank labels are STRINGS ("0", "driver");
            # normalize worker ranks to ints so the record carries the
            # same rank shape as the event-based rules (the doctor and
            # top render ' rank N' from it)
            if isinstance(rank, str) and rank.isdigit():
                rank = int(rank)
            if isinstance(v, (int, float)) and v < self.mfu_min:
                out.append((rank, {
                    "rank": rank if isinstance(rank, int) else None,
                    "mfu": round(v, 6),
                    "threshold": self.mfu_min,
                    "fn": labels.get("fn"),
                }))
        return out

    def _check_overlap(self, ctx):
        if self.overlap_min is None:
            return []
        from sparkdl_tpu.observe.perf import attribution_report

        out = []
        for rank, evs in sorted(ctx["events"].items()):
            rep = attribution_report(evs)
            eff = rep.get("overlap_efficiency")
            if (rep.get("steps", 0) >= self.min_steps
                    and isinstance(eff, (int, float))
                    and eff < self.overlap_min):
                out.append((rank, {
                    "rank": rank,
                    "overlap_efficiency": round(eff, 4),
                    "threshold": self.overlap_min,
                    "steps_in_window": rep["steps"],
                }))
        return out

    # -- firing + report -----------------------------------------------------

    def _fire(self, rule, severity, key, detail):
        """Latch-once per (rule, key): emit the timeline instant and
        counter, append the record. Returns the record, or None when
        this (rule, key) already fired this launch."""
        latch = (rule, key)
        if latch in self._fired:
            return None
        from sparkdl_tpu import observe

        record = {
            "rule": rule,
            "severity": severity,
            "rank": key if isinstance(key, int) else None,
            "ts": self._wall(),
            "detail": dict(detail),
        }
        self._fired[latch] = record
        self._records.append(record)
        observe.instant(f"alert.{rule}", cat="alert",
                        severity=severity, **detail)
        observe.inc("gang_alerts_total", rule=rule, severity=severity)
        return record

    def records(self):
        return list(self._records)

    def report(self):
        """The ``alerts.json`` payload: catalog + config + firings.
        Written by :meth:`GangTelemetry.write` even when ``alerts``
        is empty — a clean run's artifact says the rules ran."""
        return {
            "schema": ALERTS_SCHEMA,
            "enabled": True,
            "window_s": self.window_s,
            "check_s": self.check_s,
            "rules": [
                {"rule": r, "severity": s, "doc": doc}
                for r, s, _m, doc in RULES
            ],
            "baseline_step_s": (
                self._explicit_baseline
                if self._explicit_baseline is not None
                else ({str(r): round(b, 6)
                       for r, b in sorted(self._baselines.items())}
                      or None)),
            "baseline_source": self._baseline_source,
            "alerts": self.records(),
        }


def format_alert_line(record):
    """The one human rendering of a firing record —
    ``[severity] rule rank N: k=v, ...`` — shared by
    ``observe.doctor`` and ``observe.top`` so the two surfaces can
    never render the same ``alerts.json`` differently."""
    where = (f" rank {record['rank']}"
             if record.get("rank") is not None else "")
    detail = record.get("detail") or {}
    extras = ", ".join(f"{k}={v}" for k, v in sorted(detail.items())
                       if k != "rank")
    return (f"[{record.get('severity')}] {record.get('rule')}{where}"
            + (f": {extras}" if extras else ""))


__all__ = [
    "AlertEngine", "maybe_make_engine", "alerts_enabled",
    "format_alert_line",
    "RULES", "ALERTS_SCHEMA", "SEV_WARNING", "SEV_CRITICAL",
]
