"""The autotune search driver: measured trials over the registered
knob space, judged by ``observe.compare``, emitted as a verified
profile.

Search model (deliberately boring — the budget is wall-clock, not
cleverness): greedy coordinate descent over the tunable knobs a trial
harness honors. One baseline trial on defaults, then per knob each
declared candidate value measured against the current best config;
the best *improving* value (per the compare gate's median/IQR verdict
— a noisy-but-flat knob is a tie, never an improvement) is adopted
before the next knob. Every measured trial is one run of a REAL bench
harness appending its own ``history.jsonl`` ledger line, so the
search leaves the same audit trail a human benchmarking session
would.

Pruning: before any trial, the declared space is filtered against a
step-time attribution report (``observe.perf`` breakdown fractions,
or a serving stat report). A knob declares the component that must be
material for it to matter (``knobs.Knob.component``); when the report
shows that component negligible the knob is dropped from the plan and
the drop is LOGGED — a step that is 80% compute never explores
prefetch depth, a serving run with near-zero queue wait never
explores ``max_queue``. No attribution report = no pruning (unknown
is not irrelevant).

Trial accounting is loud: the driver logs the plan (trial count ≤
space size by construction — greedy measures each candidate value
once), refuses a ``--max-trials`` bound it cannot fit instead of
silently truncating, and the emitted profile carries every trial's
compare verdict as evidence.

Proof-or-degrade: a non-empty winner is re-measured — fresh default
run, fresh winner run — and only a verification pass emits
``status: "verified"``. A winner whose verification regresses is
emitted ``status: "degraded"`` (knobs empty, candidate recorded), and
the launcher pre-flight applies nothing.

CLI::

    python -m sparkdl_tpu.perf.autotune --bench cpu-proxy
    python -m sparkdl_tpu.perf.autotune --bench gbdt \\
        --values SPARKDL_TPU_GBDT_MAX_BINS=64,256 --reps 3
    python -m sparkdl_tpu.perf.autotune --bench cpu-proxy --dry-run
"""

import argparse
import dataclasses
import json
import logging
import os
import subprocess
import sys

from sparkdl_tpu.observe.compare import compare_records
from sparkdl_tpu.perf import profile as profile_mod
from sparkdl_tpu.utils import knobs as knob_reg

logger = logging.getLogger("sparkdl.perf")

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# A candidate must clear the SAME noise-aware bar the CI gate uses.
DEFAULT_FLOOR = 0.05
DEFAULT_IQR_K = 1.0
# attribution fraction below which a component-gated knob is pruned
MIN_COMPONENT_FRACTION = 0.05
# "a step that is 80% compute never explores prefetch depth"
COMPUTE_BOUND_FRACTION = 0.8


class TrialError(RuntimeError):
    """One measured trial failed (bench crashed, no ledger line)."""


@dataclasses.dataclass
class Trial:
    """One measured configuration and its verdict vs the then-best."""
    overrides: dict
    metrics: dict = None
    decision: str = "failed"     # improved | ok | regression | failed
    delta: float = None          # primary-metric relative delta
    threshold: float = None
    error: str = None


@dataclasses.dataclass
class SearchResult:
    bench: str
    primary_metric: str
    baseline: dict               # ledger-shaped metrics of defaults
    trials: list
    best_overrides: dict
    best_metrics: dict
    pruned: list                 # [(knob name, reason)]
    space_size: int
    device_kind: str = None


# -- trial runners -----------------------------------------------------------


class SubprocessTrialRunner:
    """Run one bench harness as a subprocess with knob overrides in
    its environment, and read the trial's metrics back from the
    ledger line the bench itself appended — the autotuner consumes
    the exact record the CI gate would, not a private side channel.

    ``history_path`` defaults to the repo ledger
    (``benchmarks/results/history.jsonl``): autotune trials are real
    measurements and land in the same memory.
    """

    bench = None                 # registry bench key
    ledger_bench = None          # the `bench` tag its harness writes
    primary_metric = None

    def __init__(self, *, history_path=None, extra_args=(),
                 extra_env=None, timeout=1800):
        from sparkdl_tpu.observe import perf as operf

        self.history_path = history_path or operf.default_history_path()
        self.extra_args = list(extra_args)
        self.extra_env = dict(extra_env or {})
        self.timeout = timeout

    def command(self):
        raise NotImplementedError

    def attribution(self):
        """Breakdown-fractions report used for pruning, or None."""
        return None

    def pick_primary(self, metrics):
        """Primary metric for a runner that declares none: sole metric
        of the ledger line, or a subclass's shape-aware choice."""
        if len(metrics) != 1:
            raise TrialError(
                f"{self.bench} ledger line has {len(metrics)} "
                "metrics and the runner declares no primary")
        return next(iter(metrics))

    def _bounded_run(self, args, env):
        """subprocess with a REAL timeout (the bench.py lesson): a
        child wedged in an accelerator runtime can survive the
        kill-then-communicate path of ``subprocess.run``, so kill the
        whole process group and abandon the pipes after a grace
        period. A timeout is a failed TRIAL (TrialError), never a
        crashed search."""
        import signal

        p = subprocess.Popen(
            args, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, start_new_session=True,
        )
        try:
            out, err = p.communicate(timeout=self.timeout)
            return p.returncode, out, err
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()
            try:
                p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                pass
            raise TrialError(
                f"{self.bench} trial timed out after {self.timeout}s "
                "(killed)")

    def run(self, overrides):
        from sparkdl_tpu.observe import perf as operf

        env = dict(os.environ)
        env.update(self.extra_env)
        env.update({k: str(v) for k, v in overrides.items()})
        env["SPARKDL_TPU_PERF_HISTORY"] = self.history_path
        before = len(operf.read_history(self.history_path))
        rc, _, err = self._bounded_run(
            self.command() + self.extra_args, env)
        if rc != 0:
            raise TrialError(
                f"{self.bench} trial exited {rc}: "
                f"{err.strip()[-400:]}")
        # Attribute ONLY a line this harness appended during this
        # trial (bench tag checked): the default ledger is shared, and
        # silently adopting a concurrent writer's record would back a
        # "verified" profile with someone else's numbers.
        new = [e for e in operf.read_history(self.history_path)[before:]
               if self.ledger_bench is None
               or e.get("bench") == self.ledger_bench]
        if not new:
            raise TrialError(
                f"{self.bench} trial appended no "
                f"bench={self.ledger_bench!r} ledger line to "
                f"{self.history_path} (ledger disabled, or a "
                "concurrent writer raced the trial?)")
        entry = new[-1]
        self.device_kind = entry.get("device_kind")
        metrics = entry.get("metrics") or {}
        if self.primary_metric is None:
            self.primary_metric = self.pick_primary(metrics)
        if self.primary_metric not in metrics:
            raise TrialError(
                f"{self.bench} ledger line is missing the primary "
                f"metric {self.primary_metric!r}")
        return metrics


class CpuProxyRunner(SubprocessTrialRunner):
    """The flagship bench's deviceless headline (``bench.py`` —
    cpu-proxy on hosts without a chip, the on-chip metric when
    hardware exists; the ledger line's sole metric is the primary
    either way)."""

    bench = "cpu-proxy"
    ledger_bench = "bench.py"

    def command(self):
        return [sys.executable, os.path.join(ROOT, "bench.py")]

    def attribution(self):
        # Static, by construction rather than measurement: the
        # measured program is ONE jitted lax.scan over fixed
        # device-resident batches — no input pipeline, no host
        # callbacks, no collectives. Declaring it lets the pruner do
        # its job (drop data-pipeline knobs) without pretending a
        # telemetry run happened.
        return {
            "source": "static:bench.py single fused scan",
            "fractions": {"compute": 1.0, "data_wait": 0.0,
                          "collective": 0.0, "host_callback": 0.0},
        }


class GbdtRunner(SubprocessTrialRunner):
    bench = "gbdt"
    ledger_bench = "gbdt_bench"
    primary_metric = "gbdt_fit_rows_per_sec"

    def command(self):
        return [sys.executable,
                os.path.join(ROOT, "benchmarks", "gbdt_bench.py")]


class ServeRunner(SubprocessTrialRunner):
    bench = "serve"
    ledger_bench = "serve_bench"
    primary_metric = "serve_tokens_per_sec"

    def command(self):
        return [sys.executable,
                os.path.join(ROOT, "benchmarks", "serve_bench.py")]


class AttentionRunner(SubprocessTrialRunner):
    """Flash-attention kernel-leg bench — the tile-knob search target
    (``SPARKDL_TPU_FLASH_BLOCK_Q``/``_KV``). Trials read the A/B
    section's KERNEL ledger line: on TPU that is the real pallas
    kernel, on cpu the interpret-mode emulation — tile choices change
    the measured program either way, which is what makes the search
    meaningful off-hardware (the fallback leg would be tile-blind on
    cpu). The harness emits one ``attn_ms_s{seq}`` metric per
    measured sequence; the shortest is the primary (the serving-side
    regime), and verification still holds the whole record to
    no-worse."""

    bench = "attention"
    ledger_bench = "attention_bench:kernel"

    def command(self):
        return [sys.executable,
                os.path.join(ROOT, "benchmarks", "attention_bench.py")]

    def attribution(self):
        # static, like CpuProxyRunner: one jitted kernel scan — no
        # input pipeline, no collectives
        return {
            "source": "static:attention_bench jitted kernel scan",
            "fractions": {"compute": 1.0, "data_wait": 0.0,
                          "collective": 0.0, "host_callback": 0.0},
        }

    def pick_primary(self, metrics):
        seqs = sorted(
            (m for m in metrics if m.startswith("attn_ms_s")),
            key=lambda m: int(m.rsplit("_s", 1)[1]))
        if not seqs:
            raise TrialError(
                "attention kernel ledger line has no attn_ms_s* metric")
        return seqs[0]


RUNNERS = {"cpu-proxy": CpuProxyRunner, "gbdt": GbdtRunner,
           "serve": ServeRunner, "attention": AttentionRunner}


# -- space derivation + pruning ---------------------------------------------


def derive_space(bench, *, knob_names=None, value_overrides=None):
    """The declared search space: ``[(Knob, [values]), ...]`` from the
    registry's tunable knobs for ``bench``. ``knob_names`` restricts
    (and may name any tunable knob — the operator widening the space
    past the declared bench mapping is a decision, not an error);
    ``value_overrides`` (name → list) replaces a knob's declared
    trial values."""
    value_overrides = dict(value_overrides or {})
    if knob_names:
        ks = []
        for name in knob_names:
            kb = knob_reg.get(name)
            if kb is None or not kb.tunable:
                raise SystemExit(
                    f"autotune: {name} is not a registered tunable "
                    "knob (see sparkdl_tpu/utils/knobs.py)")
            ks.append(kb)
    else:
        ks = knob_reg.tunable_knobs(bench)
    space = []
    consumed = set()
    for kb in ks:
        if kb.name in value_overrides:
            consumed.add(kb.name)
        values = [str(v) for v in
                  value_overrides.get(kb.name, kb.trial_values)]
        if values:
            space.append((kb, values))
    unused = sorted(set(value_overrides) - consumed)
    if unused:
        # the loud-accounting contract: a typo'd --values must not
        # silently measure the declared space instead
        raise SystemExit(
            f"autotune: --values for {unused} match no knob in the "
            f"search space ({sorted(kb.name for kb in ks)}); check "
            "the spelling or add --knob")
    return space


def prune_space(space, report, *, min_fraction=MIN_COMPONENT_FRACTION,
                compute_bound=COMPUTE_BOUND_FRACTION):
    """Drop knobs whose gating component a measured (or declared)
    report shows is immaterial. Returns ``(kept, pruned)`` where
    ``pruned`` is ``[(knob name, reason), ...]`` — every drop is
    visible, nothing is silently capped."""
    fractions = (report or {}).get("fractions") or {}
    kept, pruned = [], []
    for kb, values in space:
        if kb.component:
            f = fractions.get(kb.component)
            if (f is None and kb.component == "data_wait"
                    and fractions.get("compute", 0.0) >= compute_bound):
                # the headline pruning rule: a compute-bound step has
                # no data-wait to hide even when the report carries no
                # explicit data_wait row
                f = 0.0
            if f is not None and f < min_fraction:
                pruned.append((kb.name,
                               f"{kb.component} fraction {f:.3f} < "
                               f"{min_fraction:g} "
                               f"(source: {report.get('source')})"))
                continue
        kept.append((kb, values))
    return kept, pruned


def _non_default(kb, values):
    return [v for v in values if v != (kb.default or "")]


# -- judging -----------------------------------------------------------------


def judge(base_metrics, cand_metrics, primary, *, floor=DEFAULT_FLOOR,
          iqr_k=DEFAULT_IQR_K):
    """One compare-gate verdict between two ledger-shaped metric maps:
    ``(decision, delta, threshold)`` on the PRIMARY metric, through
    the exact :func:`observe.compare.compare_records` math the CI
    gate runs — medians of rep samples, IQR-aware thresholds."""
    report = compare_records({"metrics": base_metrics},
                             {"metrics": cand_metrics},
                             floor=floor, iqr_k=iqr_k)
    row = next((r for r in report["metrics"] if r["metric"] == primary),
               None)
    if row is None:
        return "failed", None, None
    return row["status"], row["delta"], row["threshold"]


# -- the search --------------------------------------------------------------


def autotune(runner, space, *, floor=DEFAULT_FLOOR, iqr_k=DEFAULT_IQR_K,
             attribution=None, max_trials=None, log=logger.info):
    """Greedy coordinate-descent search; returns a
    :class:`SearchResult`. ``attribution`` overrides the runner's own
    report (an operator feeding a real telemetry ``perf.json``)."""
    report = attribution if attribution is not None \
        else runner.attribution()
    space, pruned = prune_space(space, report)
    for name, reason in pruned:
        log(f"pruned {name}: {reason}")
    plan = [(kb, v) for kb, values in space
            for v in _non_default(kb, values)]
    space_size = 1
    for kb, values in space:
        space_size *= len(set(values) | {kb.default or ""})
    n_trials = 1 + len(plan)     # baseline + one per candidate value
    log(f"trial plan: {n_trials} measured trial(s) "
        f"(1 baseline + {len(plan)} candidate(s)) over "
        f"{len(space)} knob(s); configuration space size {space_size}; "
        f"pruned {len(pruned)} knob(s)")
    if max_trials is not None and n_trials > max_trials:
        raise SystemExit(
            f"autotune: trial plan needs {n_trials} trials but "
            f"--max-trials={max_trials}; narrow the space with "
            "--knob/--values instead of silently truncating")

    log("measuring baseline (defaults)")
    baseline = runner.run({})
    primary = runner.primary_metric
    best_metrics, best_overrides = baseline, {}
    trials = []
    for kb, values in space:
        adopted = None
        for v in _non_default(kb, values):
            overrides = dict(best_overrides)
            overrides[kb.name] = v
            try:
                metrics = runner.run(overrides)
            except TrialError as e:
                log(f"trial {kb.name}={v} FAILED: {e}")
                trials.append(Trial(overrides=overrides, error=str(e)))
                continue
            decision, delta, thr = judge(
                best_metrics, metrics, primary,
                floor=floor, iqr_k=iqr_k)
            trials.append(Trial(overrides=overrides, metrics=metrics,
                                decision=decision, delta=delta,
                                threshold=thr))
            log(f"trial {kb.name}={v}: {decision}"
                + (f" ({delta:+.1%} vs thr {thr:.1%})"
                   if delta is not None else ""))
            if decision == "improved" and (
                    adopted is None or delta > adopted[2]):
                adopted = (v, metrics, delta)
        if adopted is not None:
            v, metrics, delta = adopted
            best_overrides[kb.name] = v
            best_metrics = metrics
            log(f"adopted {kb.name}={v} ({delta:+.1%})")
    return SearchResult(
        bench=runner.bench, primary_metric=primary, baseline=baseline,
        trials=trials, best_overrides=best_overrides,
        best_metrics=best_metrics, pruned=pruned,
        space_size=space_size,
        device_kind=getattr(runner, "device_kind", None),
    )


def verify_and_emit(runner, result, *, floor=DEFAULT_FLOOR,
                    iqr_k=DEFAULT_IQR_K, log=logger.info):
    """The proof-or-degrade step: re-measure defaults and the winner
    fresh, pass them through the compare gate, and emit the profile
    doc — ``verified`` with the knobs on a pass (ties included: the
    contract is *no worse*, and a tie still pins the searched space),
    ``degraded`` with empty knobs on a regression."""
    evidence = {
        "primary_metric": result.primary_metric,
        "baseline": result.baseline,
        "pruned": [list(p) for p in result.pruned],
        "space_size": result.space_size,
        "trials": [
            {"overrides": t.overrides, "decision": t.decision,
             "delta": t.delta, "threshold": t.threshold,
             **({"error": t.error} if t.error else {})}
            for t in result.trials
        ],
    }
    if not result.best_overrides:
        log("search found no improving knob: defaults are the profile")
        evidence["verification"] = "skipped (empty winner = defaults)"
        return profile_mod.make_profile(
            {}, device_kind=result.device_kind, bench=result.bench,
            status=profile_mod.STATUS_VERIFIED, evidence=evidence)

    log("verification trial: fresh default run")
    v_default = runner.run({})
    log("verification trial: fresh winner run "
        f"({result.best_overrides})")
    v_winner = runner.run(result.best_overrides)
    report = compare_records({"metrics": v_default},
                             {"metrics": v_winner},
                             floor=floor, iqr_k=iqr_k)
    row = next((r for r in report["metrics"]
                if r["metric"] == result.primary_metric), None)
    evidence["verification"] = {
        "default": v_default, "winner": v_winner,
        "primary": row, "regressions": report["regressions"],
    }
    # "no worse" means the WHOLE record: a winner that improves the
    # primary but regresses a co-measured metric (gbdt predict
    # throughput, serve queue wait...) must not verify. Secondary
    # metrics count only when the compare gate's sample protection is
    # live on them (>= 4 rep samples on either side) — degrading a
    # real winner over one unprotected timed invocation would violate
    # the module's own never-a-single-invocation rule.
    def _protected(name):
        for side in (v_default, v_winner):
            samples = (side.get(name) or {}).get("samples") or ()
            if len(samples) >= 4:
                return True
        return False

    secondary_regressions = [
        r["metric"] for r in report["metrics"]
        if r["status"] == "regression"
        and r["metric"] != result.primary_metric
        and _protected(r["metric"])
    ]
    regressed = (row is None or row["status"] == "regression"
                 or bool(secondary_regressions))
    if regressed:
        log("VERIFICATION REGRESSED: degrading to defaults "
            f"(candidate was {result.best_overrides})")
        return profile_mod.make_profile(
            {}, device_kind=result.device_kind, bench=result.bench,
            status=profile_mod.STATUS_DEGRADED,
            candidate_knobs=result.best_overrides, evidence=evidence)
    log(f"verification passed ({row['delta']:+.1%} on "
        f"{result.primary_metric}); emitting verified profile")
    return profile_mod.make_profile(
        result.best_overrides, device_kind=result.device_kind,
        bench=result.bench, status=profile_mod.STATUS_VERIFIED,
        evidence=evidence)


# -- CLI ---------------------------------------------------------------------


def _parse_values(specs):
    out = {}
    for spec in specs or ():
        name, _, vals = spec.partition("=")
        if not vals:
            raise SystemExit(
                f"autotune: --values wants NAME=v1,v2 (got {spec!r})")
        out[name] = [v for v in vals.split(",")]
    return out


def _load_attribution(path):
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"autotune: unreadable attribution {path}: {e}")
    # accept a perf.json attribution doc or any breakdown doc — both
    # carry the fractions map the pruner reads
    if not isinstance(doc.get("fractions"), dict):
        raise SystemExit(
            f"autotune: {path} has no 'fractions' map (want an "
            "observe.perf breakdown/attribution document)")
    doc.setdefault("source", path)
    return doc


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.perf.autotune",
        description="Search the registered knob space with measured "
                    "bench trials; emit a verified per-device-kind "
                    "profile the launcher pre-flight applies.")
    ap.add_argument("--bench", choices=sorted(RUNNERS),
                    default="cpu-proxy")
    ap.add_argument("--knob", action="append", default=None,
                    help="restrict the space to this knob (repeatable)")
    ap.add_argument("--values", action="append", default=None,
                    metavar="NAME=v1,v2",
                    help="override a knob's trial values (repeatable)")
    ap.add_argument("--floor", type=float, default=DEFAULT_FLOOR)
    ap.add_argument("--iqr-k", type=float, default=DEFAULT_IQR_K)
    ap.add_argument("--attribution", default=None,
                    help="observe.perf breakdown JSON used for "
                    "pruning (default: the runner's own report)")
    ap.add_argument("--history", default=None,
                    help="ledger path for trial lines (default: the "
                    "repo history.jsonl)")
    ap.add_argument("--out", default=None,
                    help="profile output path ('-' = stdout only; "
                    "default: benchmarks/profiles/<kind>/<bench>.json)")
    ap.add_argument("--reps", type=int, default=None,
                    help="per-trial rep count forwarded to harnesses "
                    "that take --reps (gbdt)")
    ap.add_argument("--tiny", action="store_true",
                    help="smoke shapes (SPARKDL_TPU_BENCH_TINY=1)")
    ap.add_argument("--trial-timeout", type=float, default=1800)
    ap.add_argument("--max-trials", type=int, default=None,
                    help="refuse (loudly) a plan larger than this — "
                    "never a silent cap")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the (pruned) trial plan and exit")
    ap.add_argument("--bench-arg", action="append", default=None,
                    help="extra argv token forwarded to the bench "
                    "harness (repeatable)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(message)s")

    extra_args = list(args.bench_arg or ())
    extra_env = {}
    if args.tiny:
        extra_env["SPARKDL_TPU_BENCH_TINY"] = "1"
    if args.reps is not None and args.bench == "gbdt":
        extra_args += ["--reps", str(args.reps)]
    runner = RUNNERS[args.bench](
        history_path=args.history, extra_args=extra_args,
        extra_env=extra_env, timeout=args.trial_timeout)

    space = derive_space(args.bench, knob_names=args.knob,
                         value_overrides=_parse_values(args.values))
    if not space:
        raise SystemExit(
            f"autotune: no tunable knobs registered for bench "
            f"{args.bench!r}")
    attribution = (_load_attribution(args.attribution)
                   if args.attribution else None)

    if args.dry_run:
        report = attribution if attribution is not None \
            else runner.attribution()
        kept, pruned = prune_space(space, report)
        plan = {
            "bench": args.bench,
            "knobs": {kb.name: _non_default(kb, values)
                      for kb, values in kept},
            "pruned": [list(p) for p in pruned],
            "trials": 1 + sum(len(_non_default(kb, v))
                              for kb, v in kept),
        }
        print(json.dumps(plan, indent=2, sort_keys=True))
        return 0

    result = autotune(runner, space, floor=args.floor,
                      iqr_k=args.iqr_k, attribution=attribution,
                      max_trials=args.max_trials)
    doc = verify_and_emit(runner, result, floor=args.floor,
                          iqr_k=args.iqr_k)
    if args.out == "-":
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    try:
        path = profile_mod.save_profile(doc, args.out)
    except profile_mod.ProfileError as e:
        # an unkeyable device kind must not discard a finished search
        # (hours of measured trials): print the document, name the
        # problem, let the operator --out it somewhere explicit
        print(json.dumps(doc, indent=2, sort_keys=True))
        print(f"autotune: could not save the profile ({e}); the "
              "document is printed above — rerun with an explicit "
              "--out to keep it", file=sys.stderr)
        return 1
    print(json.dumps({"profile": path, "status": doc["status"],
                      "knobs": doc["knobs"],
                      **({"candidate_knobs": doc["candidate_knobs"]}
                         if "candidate_knobs" in doc else {})},
                     indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
