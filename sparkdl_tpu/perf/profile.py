"""Autotuned performance profiles: schema, storage, and the launcher
pre-flight that applies them.

A profile is the autotuner's emitted winner — a small JSON document
(schema ``sparkdl_tpu.perf.profile/1``) mapping registered *tunable*
env knobs to values, keyed by the device kind it was measured on and
stamped with the host fingerprint + git sha that measured it:

.. code-block:: json

    {"schema": "sparkdl_tpu.perf.profile/1",
     "device_kind": "cpu",
     "host": "host/x86_64/cpu64",
     "git_sha": "1b268b0", "created": "2026-08-04T00:00:00Z",
     "bench": "cpu-proxy",
     "status": "verified",
     "knobs": {"SPARKDL_TPU_LOSS_CHUNK": "1024"},
     "evidence": {"...": "trial + verification compare reports"}}

Committed profiles live one-per-(device kind, bench) under
``benchmarks/profiles/<kind>/<bench>.json`` — benches tune disjoint
knob subsets, so a kind composes its per-bench profiles. The launcher
pre-flight (:func:`preflight_env`, called by ``_launch_gang_once`` for
every attempt) resolves every profile for the launch's device kind and
merges their knobs into each worker's environment **under the
operator**: a
knob already present in the driver's env is never overridden — the
profile supplies defaults, the operator keeps the last word. Because
application happens per attempt inside the launch function the
supervisor retries, a relaunched gang re-inherits the profile through
exactly the env-forwarding path the restart context rides (pinned by
``tests/perf/test_profile.py``).

Proof-or-degrade (the PR 9 fix-engine contract): the autotuner only
emits ``status: "verified"`` after a fresh winner-vs-default
verification trial passes the ``observe.compare`` gate. A winner whose
verification regresses is emitted as ``status: "degraded"`` — the
document records the candidate knobs and the failing compare report,
but :func:`preflight_env` applies **nothing** and logs why. Unknown or
non-tunable knob names in a profile are skipped loudly, never
exported: a profile must not become an arbitrary-env injection path.

``SPARKDL_TPU_PERF_PROFILE`` steers resolution: unset = the committed
``benchmarks/profiles/`` directory; a directory = per-device-kind
lookup there; a file = exactly that profile; ``0``/``off`` = disabled.
"""

import json
import logging
import os
import sys
import time

logger = logging.getLogger("sparkdl.perf")

PROFILE_SCHEMA = "sparkdl_tpu.perf.profile/1"
PROFILE_ENV = "SPARKDL_TPU_PERF_PROFILE"

STATUS_VERIFIED = "verified"
STATUS_DEGRADED = "degraded"


class ProfileError(ValueError):
    """A profile document violates the schema contract."""


def default_profile_dir():
    """``benchmarks/profiles`` at the repo root — the committed home
    of per-device-kind profiles."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "benchmarks", "profiles")


# Raw device-kind tokens we can honestly key a profile by. Deliberately
# NOT observe.perf.normalize_device_kind: that helper falls back to
# DEFAULT_KIND ("v5e") for anything unknown — correct for MFU
# denominators, catastrophic for profiles (a WORKER_PLATFORM=tpu pin
# on a v4 pod must not load v5e-measured knobs). Unknown = None =
# no profile.
_KIND_TOKENS = (("v5p", "v5p"), ("v5e", "v5e"), ("v5 lite", "v5e"),
                ("v5lite", "v5e"), ("v4", "v4"), ("cpu", "cpu"))


def strict_device_kind(raw):
    """Normalize a raw device-kind/platform string, or None when the
    kind cannot be named with confidence (never a default guess)."""
    if not raw:
        return None
    low = str(raw).lower()
    for token, kind in _KIND_TOKENS:
        if token in low:
            return kind
    return None


def profile_path(device_kind, bench, root=None):
    """Committed home of one (device kind, bench) profile:
    ``benchmarks/profiles/<kind>/<bench>.json`` — benches tune
    disjoint knob subsets, so a kind keeps one profile per bench and
    the pre-flight applies their union. The kind must resolve
    strictly; keying a profile by a guessed kind would misfile it."""
    kind = strict_device_kind(device_kind)
    if kind is None:
        raise ProfileError(
            f"cannot key a profile by device kind {device_kind!r} "
            "(unknown kind — profiles are measurements, not guesses)")
    return os.path.join(root or default_profile_dir(), kind,
                        f"{bench}.json")


def make_profile(knobs_map, *, device_kind, bench, status,
                 evidence=None, candidate_knobs=None):
    """Build one schema-versioned profile doc. ``knobs_map`` must name
    registered TUNABLE knobs only (the apply side re-checks, but a
    malformed profile should fail at emit time, where the autotuner
    can see it)."""
    from sparkdl_tpu.observe import perf as operf
    from sparkdl_tpu.utils import knobs as knob_reg

    if status not in (STATUS_VERIFIED, STATUS_DEGRADED):
        raise ProfileError(f"unknown profile status {status!r}")
    for name in knobs_map:
        kb = knob_reg.get(name)
        if kb is None or not kb.tunable:
            raise ProfileError(
                f"profile knob {name!r} is not a registered tunable "
                "knob (sparkdl_tpu/utils/knobs.py)")
    doc = {
        "schema": PROFILE_SCHEMA,
        "device_kind": device_kind,
        "host": operf.host_fingerprint(),
        "git_sha": operf.git_sha(),
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "bench": bench,
        "status": status,
        "knobs": {k: str(v) for k, v in knobs_map.items()},
    }
    if candidate_knobs:
        # the degraded case: what the search picked before the
        # verification trial refused it — kept for the postmortem
        doc["candidate_knobs"] = {
            k: str(v) for k, v in candidate_knobs.items()}
    if evidence:
        doc["evidence"] = evidence
    return doc


def save_profile(doc, path=None):
    path = path or profile_path(doc.get("device_kind"),
                                doc.get("bench"))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_profile(path):
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ProfileError(f"unreadable profile {path}: {e}")
    if not isinstance(doc, dict) or doc.get("schema") != PROFILE_SCHEMA:
        raise ProfileError(
            f"{path} is not a {PROFILE_SCHEMA} document "
            f"(schema={doc.get('schema') if isinstance(doc, dict) else None!r})")
    if not isinstance(doc.get("knobs"), dict):
        raise ProfileError(f"{path} has no knobs map")
    return doc


def _initialized_backend_kind():
    """The probed device kind, but ONLY when this process's jax
    backend is already live. ``operf.device_kind()`` guards against
    jax never being *imported*, yet ``jax.devices()`` on an imported-
    but-uninitialized jax would initialize the backend right here —
    and the launcher pre-flight runs in the DRIVER, where a first-
    touch TPU init would grab the chip lease out from under the
    workers it is about to spawn. No live backend = None, never an
    init."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        backends = getattr(jax.lib.xla_bridge, "_backends", None)
        if not backends:
            return None
    except Exception:
        return None
    from sparkdl_tpu.observe import perf as operf

    return strict_device_kind(operf.device_kind())


def resolve_launch_device_kind(env=None):
    """The device kind a launch is about to run on, WITHOUT
    initializing a backend in the driver (the telemetry no-import
    rule, tightened to no-*init*): an operator platform pin wins, then
    an already-INITIALIZED jax backend's probed kind, then the absence
    of accelerator device nodes (no ``/dev/accel*`` = cpu). Anything
    that cannot be named with confidence (a bare ``tpu`` pin, device
    nodes with no live backend) returns None — applying another
    kind's profile would be a guess, and profiles are measurements."""
    env = os.environ if env is None else env
    pinned = env.get("SPARKDL_TPU_WORKER_PLATFORM") \
        or env.get("SPARKDL_TPU_BENCH_PLATFORM")
    if pinned:
        return strict_device_kind(pinned)
    kind = _initialized_backend_kind()
    if kind is not None:
        return kind
    import glob

    if not (glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")
            or glob.glob("/dev/nvidia*")):
        return "cpu"
    return None


def find_profiles(env=None):
    """Resolve every profile applicable to this launch, in
    deterministic (bench-name) order. Returns ``[(doc, path), ...]``
    (empty when none apply — the common case for a host class with no
    committed profiles). An EXPLICIT ``SPARKDL_TPU_PERF_PROFILE``
    path that names neither a file nor a directory raises — an
    operator who pinned a profile must never silently run without it
    — and a malformed profile raises (committed artifacts must not
    rot silently)."""
    import glob as globmod

    env = os.environ if env is None else env
    spec = (env.get(PROFILE_ENV) or "").strip()
    if spec.lower() in ("0", "off", "none"):
        return []
    if spec and os.path.isfile(spec):
        return [(load_profile(spec), spec)]
    if spec and not os.path.isdir(spec):
        raise ProfileError(
            f"{PROFILE_ENV}={spec} is neither a profile file nor a "
            "profile directory")
    root = spec if spec else default_profile_dir()
    kind = resolve_launch_device_kind(env)
    if kind is None:
        return []
    paths = sorted(globmod.glob(
        os.path.join(root, kind, "*.json")))
    # legacy flat spelling (<root>/<kind>.json) still honored
    flat = os.path.join(root, f"{kind}.json")
    if os.path.isfile(flat):
        paths.append(flat)
    out = []
    for p in paths:
        try:
            out.append((load_profile(p), p))
        except ProfileError as e:
            # quarantine a rotten profile to itself: one malformed
            # committed file must not stop the kind's OTHER profiles
            # from applying
            logger.warning("perf profile %s ignored: %s", p, e)
    return out


def profile_env_delta(doc, base_env):
    """The env vars a profile contributes UNDER ``base_env``: only
    registered tunable knobs, only where the operator has not already
    set the var, and nothing at all from a degraded profile."""
    from sparkdl_tpu.utils import knobs as knob_reg

    if doc.get("status") != STATUS_VERIFIED:
        logger.warning(
            "perf profile (bench=%s, device_kind=%s) is %s — "
            "verification regressed vs defaults; running on defaults",
            doc.get("bench"), doc.get("device_kind"),
            doc.get("status"))
        return {}
    delta = {}
    for name, value in sorted(doc.get("knobs", {}).items()):
        kb = knob_reg.get(name)
        if kb is None or not kb.tunable:
            logger.warning(
                "perf profile names %r, which is not a registered "
                "tunable knob — skipped (profiles are not an env "
                "injection path)", name)
            continue
        if name in base_env:
            # operator keeps the last word
            continue
        delta[name] = str(value)
    return delta


def preflight_env(base_env=None):
    """The launcher pre-flight: resolve + apply every profile for this
    launch (benches tune disjoint knob subsets, so a device kind's
    per-bench profiles compose; a knob two profiles both name keeps
    the first and logs the conflict). Returns the env delta to merge
    into every worker env (empty when nothing applies). Logs one line
    per applying profile; a cross-host profile (same device kind,
    different fingerprint) applies but says so — same advisory honesty
    as ``observe.compare``. Never raises: a broken profile must not
    take down a launch (it logs and degrades to defaults)."""
    from sparkdl_tpu.observe import perf as operf

    base_env = os.environ if base_env is None else base_env
    delta = {}
    try:
        for doc, path in find_profiles(base_env):
            one = profile_env_delta(doc, base_env)
            for name in sorted(set(one) & set(delta)):
                logger.warning(
                    "perf profile %s also names %s (=%s); keeping the "
                    "earlier profile's %s", path, name, one[name],
                    delta[name])
                one.pop(name)
            if one:
                cross = (doc.get("host")
                         and doc.get("host") != operf.host_fingerprint())
                logger.info(
                    "perf profile %s (bench=%s, device_kind=%s%s): "
                    "applying %s",
                    path, doc.get("bench"), doc.get("device_kind"),
                    " — measured on a DIFFERENT host, advisory numbers"
                    if cross else "",
                    ", ".join(f"{k}={v}"
                              for k, v in sorted(one.items())))
            delta.update(one)
        return delta
    except ProfileError as e:
        logger.warning("perf profile ignored: %s", e)
        return delta
    except Exception:
        logger.warning("perf profile pre-flight failed; launching on "
                       "defaults", exc_info=True)
        return delta


def main(argv=None):
    """``python -m sparkdl_tpu.perf.profile [PATH]``: show the profile
    that would apply to a launch from this environment (or validate an
    explicit PATH) — the operator's dry-run of the pre-flight."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.perf.profile",
        description="Inspect/validate autotuned perf profiles.")
    ap.add_argument("path", nargs="?", help="profile JSON to validate "
                    "(default: resolve like the launcher pre-flight)")
    args = ap.parse_args(argv)
    if args.path:
        doc = load_profile(args.path)
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    found = find_profiles()
    if not found:
        print("no profile applies to this environment "
              f"(device kind: {resolve_launch_device_kind()!r})")
        return 1
    delta = preflight_env(os.environ)
    for doc, path in found:
        print(f"profile: {path} (bench={doc.get('bench')}, "
              f"status={doc.get('status')})")
    print(json.dumps({"would_apply": delta}, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
