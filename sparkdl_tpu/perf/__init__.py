"""``sparkdl_tpu.perf``: the self-tuning runtime — close the loop from
ledger to knobs (ROADMAP item 4, ISSUE 12 tentpole).

The platform *measures* everything (PR 7 attribution/MFU, the
``history.jsonl`` ledger, ``observe.compare``'s noise-aware medians)
and *rewrites* programs under machine-checked proofs (PR 9 lint-to-fix)
— this package composes the two into an autotuner:

- :mod:`sparkdl_tpu.perf.autotune` — the search driver. Derives its
  knob space from the :mod:`sparkdl_tpu.utils.knobs` registry (knobs
  are data, not code — the XGBoost-``hist`` idiom: the method is
  fixed, the bins are searched), runs short measured trials through
  the EXISTING bench harnesses (``bench.py`` cpu-proxy,
  ``benchmarks/serve_bench.py``, ``benchmarks/gbdt_bench.py``), judges
  every candidate with ``observe.compare``'s rep-sample medians + IQR
  thresholds (never a single timed invocation), and prunes the space
  with step-time attribution — a step that is 80% compute never
  explores prefetch depth; a serving run with near-zero queue wait
  never explores ``max_queue``.
- :mod:`sparkdl_tpu.perf.profile` — the committed per-device-kind
  profile the winner is emitted as (schema
  ``sparkdl_tpu.perf.profile/1``, keyed by device kind + host
  fingerprint), and the launcher pre-flight that applies it through
  the same worker-env forwarding path every supervised relaunch
  already inherits. The PR 9 proof-or-degrade contract carries over:
  a profile is only emitted ``verified`` after a fresh
  winner-vs-default verification trial passes the compare gate;
  a regressing winner degrades to defaults — and says so.

CLI: ``python -m sparkdl_tpu.perf.autotune --bench cpu-proxy``.
"""

from sparkdl_tpu.perf.profile import (  # noqa: F401
    PROFILE_ENV,
    PROFILE_SCHEMA,
    ProfileError,
    load_profile,
    preflight_env,
    save_profile,
)
