"""Gradient-boosted-tree estimators with the reference's public surface.

Real implementations of the reference's all-stub estimator module
(reference ``sparkdl/xgboost/xgboost.py`` — every constructor and method
there raises NotImplementedError; the docstrings define the contract).
The param surface reproduces reference ``xgboost.py:38-106`` including
the renamed-param contract (SURVEY.md §5.6): ``use_gpu`` not ``gpu_id``
(``:258``), ``baseMarginCol`` not ``base_margin`` (``:261-262``),
``weightCol`` not ``sample_weight`` (``:282-285``),
``validationIndicatorCol`` not ``eval_set`` (``:277-281``), and
``missing`` with sparse-vector semantics (``:41-47``).

The training engine is the TPU-native histogram GBDT in
:mod:`sparkdl_tpu.xgboost.booster`; with ``num_workers > 1`` training
runs as a HorovodRunner gang whose per-level histogram allreduce rides
the same XLA/ICI collectives as deep-learning training — the Rabit
replacement required by BASELINE.json.
"""

import json
import logging
import os

import numpy as np

from sparkdl_tpu.ml import (
    Estimator,
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasValidationIndicatorCol,
    HasWeightCol,
    MLReadable,
    MLWritable,
    Model,
    Param,
    Params,
    TypeConverters,
)
from sparkdl_tpu.ml.dataframe import (
    extract_matrix,
    to_output,
    to_pandas,
)
from sparkdl_tpu.ml.util import params_from_json, params_to_json
from sparkdl_tpu.xgboost import booster as _booster_mod

logger = logging.getLogger("sparkdl.xgboost")

# Booster hyper-parameters auto-supported in the constructors — the
# analogue of "automatically supports most of the parameters in
# `xgboost.XGBClassifier`" (reference xgboost.py:253-256). Each becomes
# a discoverable Param (reference xgboost.py:304-305).
_BOOSTER_PARAM_DEFS = {
    "n_estimators": (100, TypeConverters.toInt, "number of boosting rounds."),
    "max_depth": (6, TypeConverters.toInt, "maximum tree depth."),
    "learning_rate": (0.3, TypeConverters.toFloat,
                      "boosting learning rate (eta)."),
    "objective": (None, TypeConverters.toString,
                  "learning objective: reg:squarederror, binary:logistic "
                  "or multi:softprob."),
    "reg_lambda": (1.0, TypeConverters.toFloat, "L2 regularization term."),
    "reg_alpha": (0.0, TypeConverters.toFloat, "L1 regularization term."),
    "gamma": (0.0, TypeConverters.toFloat,
              "minimum loss reduction required to make a split."),
    "min_child_weight": (1.0, TypeConverters.toFloat,
                         "minimum sum of instance hessian in a child."),
    "subsample": (1.0, TypeConverters.toFloat,
                  "row subsample ratio per boosting round."),
    "colsample_bytree": (1.0, TypeConverters.toFloat,
                         "feature subsample ratio per tree."),
    "max_bin": (256, TypeConverters.toInt,
                "number of histogram bins for the hist tree method."),
    "tree_method": ("hist", TypeConverters.toString,
                    "tree construction algorithm; this TPU implementation "
                    "always uses the histogram method."),
    "random_state": (0, TypeConverters.toInt, "random seed."),
    "monotone_constraints": (None, TypeConverters.identity,
                             "per-feature monotonicity: tuple/str/dict "
                             "of {-1, 0, 1} (xgboost semantics); the "
                             "trained forest is monotone in each "
                             "constrained feature."),
    "num_class": (None, TypeConverters.toInt,
                  "number of classes for multi:softprob."),
    "eval_metric": (None, TypeConverters.toString,
                    "metric for the validation set: rmse, logloss, "
                    "mlogloss or error."),
    "early_stopping_rounds": (None, TypeConverters.toInt,
                              "stop when the validation metric has not "
                              "improved for this many rounds."),
    "verbose_eval": (False, TypeConverters.toBoolean,
                     "print the validation metric each round."),
    "xgb_model": (None, TypeConverters.identity,
                  "a Booster to continue training from (the value "
                  "returned by model.get_booster())."),
    "scale_pos_weight": (1.0, TypeConverters.toFloat,
                         "weight multiplier for positive-class rows "
                         "(binary objectives)."),
    "base_score": (None, TypeConverters.toFloat,
                   "initial prediction: a probability for logistic "
                   "objectives, a raw value otherwise."),
}

# xgboost.XGBClassifier params that have no effect on this runtime
# (threading/GPU/booster-variant knobs): accepted with a warning, so
# mains written against xgboost's sklearn API run unmodified — the
# "automatically supports most of the parameters" posture (reference
# xgboost.py:253-256) without silently absorbing typos.
_IGNORED_PARAMS = {
    "n_jobs", "nthread", "verbosity", "silent", "booster",
    "enable_categorical", "max_cat_to_onehot", "predictor",
    "sampling_method", "interaction_constraints",
    "importance_type", "device", "grow_policy", "max_leaves",
    "colsample_bylevel", "colsample_bynode", "max_delta_step",
}

# Params the reference explicitly rejects, with the replacement the user
# should use instead (reference xgboost.py:176-182, :258-267).
_BLOCKED_PARAMS = {
    "gpu_id": "use_gpu",
    "base_margin": "baseMarginCol",
    "base_margin_eval_set": "baseMarginCol",
    "sample_weight": "weightCol",
    "sample_weight_eval_set": "weightCol",
    "eval_set": "validationIndicatorCol",
    "output_margin": "rawPredictionCol (margins are always emitted there)",
    "validate_features": None,
}


class _XgboostParams(HasFeaturesCol, HasLabelCol, HasWeightCol,
                     HasPredictionCol, HasValidationIndicatorCol):
    """Shared Param surface (reference ``xgboost.py:38-106``)."""

    missing = Param(
        Params._dummy(), "missing",
        "the value to treat as missing in the features, default np.nan. "
        "Using 0.0 as the missing value performs better. Note that in a "
        "Spark DataFrame the inactive slots of a sparse vector mean 0, "
        "not missing, unless missing=0 is set. "
        "(Contract: reference xgboost.py:41-47.)")

    callbacks = Param(
        Params._dummy(), "callbacks",
        "arbitrary training callback functions, invoked each boosting "
        "round. Saved with cloudpickle, which is not fully "
        "self-contained: loading may fail under different dependency "
        "versions. (Contract: reference xgboost.py:49-56.)")

    num_workers = Param(
        Params._dummy(), "num_workers",
        "number of boosting workers; each worker corresponds to one "
        "task slot / TPU chip, and histogram reduction runs over the "
        "same ICI collectives as deep-learning training. (Contract: "
        "reference xgboost.py:58-64.)",
        typeConverter=TypeConverters.toInt)

    use_gpu = Param(
        Params._dummy(), "use_gpu",
        "accepted for API compatibility (reference xgboost.py:65-71); "
        "this runtime binds workers to TPU chips, so the flag is a "
        "no-op and training is accelerator-resident either way.")

    force_repartition = Param(
        Params._dummy(), "force_repartition",
        "force the input rows to be reshuffled across workers before "
        "training rather than trusting the existing partitioning. "
        "(Contract: reference xgboost.py:72-80.)")

    use_external_storage = Param(
        Params._dummy(), "use_external_storage",
        "spill the training matrix to disk (memory-mapped) for "
        "exceptionally large datasets; values are rounded to "
        "external_storage_precision digits, trading precision for "
        "memory. baseMarginCol and weightCol are unsupported in this "
        "mode. (Contract: reference xgboost.py:81-97.)")

    external_storage_precision = Param(
        Params._dummy(), "external_storage_precision",
        "significant digits kept when spilling features to disk. "
        "(Contract: reference xgboost.py:91-97.)",
        typeConverter=TypeConverters.toInt)

    baseMarginCol = Param(
        Params._dummy(), "baseMarginCol",
        "column holding per-row base margins for training and "
        "validation; use this instead of base_margin / "
        "base_margin_eval_set fit-method params. Not available for "
        "distributed training. (Contract: reference xgboost.py:99-106.)")

    def __init__(self):
        super().__init__()
        self._setDefault(
            missing=float("nan"), num_workers=1, use_gpu=False,
            force_repartition=False, use_external_storage=False,
            external_storage_precision=5,
        )
        for name, (default, conv, doc) in _BOOSTER_PARAM_DEFS.items():
            p = Param(self, name, doc + " (passed through to the TPU "
                      "histogram booster)", conv)
            setattr(self, name, p)
            self._defaultParamMap[p] = default

    # -- shared estimator plumbing -----------------------------------------

    def _apply_kwargs(self, kwargs):
        for k, v in kwargs.items():
            if k in _BLOCKED_PARAMS:
                repl = _BLOCKED_PARAMS[k]
                hint = f"; use {repl} instead" if repl else ""
                raise ValueError(
                    f"Param {k!r} is not supported (reference contract"
                    f"{hint})."
                )
            if k in _IGNORED_PARAMS:
                logger.warning(
                    "Param %r has no effect on the TPU booster and is "
                    "ignored.", k,
                )
                continue
            if not self.hasParam(k):
                raise ValueError(
                    f"Unknown param {k!r}. Discoverable params are the "
                    "entries with Param(parent=...) on this class."
                )
            if v is not None:
                self._set(**{k: v})

    def _booster_params(self, n_classes):
        p = {}
        for name in _BOOSTER_PARAM_DEFS:
            if name in ("verbose_eval", "early_stopping_rounds", "xgb_model"):
                continue
            v = self.getOrDefault(self.getParam(name))
            if v is not None:
                p[name] = v
        p["missing"] = self.getOrDefault(self.missing)
        if self._is_classifier():
            if n_classes > 2:
                p["objective"] = p.get("objective") or "multi:softprob"
                p["num_class"] = n_classes
            else:
                p["objective"] = p.get("objective") or "binary:logistic"
                p["num_class"] = 2
        else:
            p["objective"] = p.get("objective") or "reg:squarederror"
            p.pop("num_class", None)
        p.pop("tree_method", None)  # hist is the only method
        return p

    def _is_classifier(self):
        raise NotImplementedError


def _fit_booster(params, X, y, w, base_margin, X_val, y_val,
                 early_stopping_rounds, verbose_eval, callbacks,
                 xgb_model, num_workers, force_repartition):
    """Single-process or gang-distributed booster training."""
    eval_set = [(X_val, y_val)] if X_val is not None and len(X_val) else None
    if num_workers <= 1:
        return _booster_mod.train(
            params, X, y, sample_weight=w, base_margin=base_margin,
            eval_set=eval_set, early_stopping_rounds=early_stopping_rounds,
            verbose_eval=verbose_eval, callbacks=callbacks,
            xgb_model=xgb_model,
        )

    if base_margin is not None:
        # Contract: baseMarginCol "is not available for distributed
        # training" (reference xgboost.py:102-105).
        raise ValueError(
            "baseMarginCol is not available for distributed training "
            "(num_workers > 1)."
        )

    def gang_main(params, X, y, w, eval_set, esr, verbose, callbacks,
                  xgb_model):
        import sparkdl_tpu.hvd as hvd
        from sparkdl_tpu.xgboost import booster as B

        hvd.init()
        rank = hvd.rank()

        def hist_reduce(a):
            return hvd.allreduce(a, op=hvd.Sum)

        bst = B.train(
            params, X, y, sample_weight=w,
            eval_set=eval_set, early_stopping_rounds=esr,
            verbose_eval=verbose and rank == 0,
            hist_reduce=hist_reduce, callbacks=callbacks,
            xgb_model=xgb_model,
        )
        return bst if rank == 0 else None

    # Shard rows on the driver so each worker's payload carries ONLY its
    # shard (the eval set stays replicated: every worker must compute
    # the identical metric for deterministic early stopping).
    idx = np.arange(len(X))
    if force_repartition:
        # force_repartition: deterministic reshuffle so every worker
        # gets an unbiased shard (reference xgboost.py:72-80).
        np.random.RandomState(0).shuffle(idx)
    shards = np.array_split(idx, num_workers)
    per_rank = [
        {"X": X[s], "y": y[s], "w": None if w is None else w[s]}
        for s in shards
    ]

    from sparkdl_tpu.horovod.launcher import (
        SlotProbeError,
        available_slots,
        launch_gang,
    )

    # One boosting worker per task slot (reference xgboost.py:58-64):
    # cluster gang when slots exist, local subprocess gang otherwise.
    # The fallback oversubscribes the host, so it is never silent —
    # and SPARKDL_TPU_XGB_STRICT_SLOTS=1 turns it into the same
    # fail-fast HorovodRunner(np>0) applies.
    strict = os.environ.get("SPARKDL_TPU_XGB_STRICT_SLOTS") == "1"
    try:
        slots = available_slots()
    except SlotProbeError as e:
        if strict:
            raise
        logger.warning(
            "xgboost: slot discovery failed (%s); falling back to %d "
            "local subprocess workers.", e, num_workers,
        )
        np_arg = -num_workers
    else:
        if slots >= num_workers:
            np_arg = num_workers
        elif strict:
            raise RuntimeError(
                f"num_workers={num_workers} exceeds the {slots} available "
                "task slots and SPARKDL_TPU_XGB_STRICT_SLOTS=1 forbids "
                "the oversubscribed local fallback (reference "
                "xgboost.py:58-64)."
            )
        else:
            logger.warning(
                "num_workers=%d exceeds the %d available task slots; "
                "training falls back to %d OVERSUBSCRIBED local "
                "subprocess workers (slower, same result). Set "
                "SPARKDL_TPU_XGB_STRICT_SLOTS=1 to fail fast instead.",
                num_workers, slots, num_workers,
            )
            np_arg = -num_workers
    return launch_gang(
        np=np_arg, main=gang_main,
        kwargs=dict(
            params=params, X=None, y=None, w=None, eval_set=eval_set,
            esr=early_stopping_rounds, verbose=verbose_eval,
            callbacks=callbacks, xgb_model=xgb_model,
        ),
        driver_log_verbosity="log_callback_only",
        per_rank_kwargs=per_rank,
    )


def _partition_gang_main(partition_pdf, params, colspec, esr, verbose,
                         callbacks, xgb_model, use_external_storage,
                         storage_precision):
    """Executor-side estimator worker: trains on the rows of THIS
    barrier task's partition only (reference ``xgboost.py:58-64`` —
    each worker trains on its partition-resident data; nothing is
    collected to the driver)."""
    import numpy as np

    import sparkdl_tpu.hvd as hvd
    from sparkdl_tpu.ml.dataframe import extract_matrix
    from sparkdl_tpu.xgboost import booster as B

    hvd.init()  # idempotent: the barrier bootstrap already rendezvoused
    rank = hvd.rank()
    n_rows = 0 if partition_pdf is None else len(partition_pdf)
    # Fast-fail on skew, SYMMETRICALLY: every rank reports its row
    # count through one tiny allgather BEFORE any data-dependent
    # collective, so an empty partition aborts the whole gang at once.
    # (The naive alternative — the empty rank raising unilaterally —
    # leaves its peers blocked in the histogram allreduce until the
    # control plane tears the gang down: a slow, timeout-shaped
    # failure instead of an immediate typed one.)
    counts = hvd.allgather(np.array([[n_rows]], np.int64))[:, 0]
    if (counts == 0).any():
        empty = [int(r) for r in np.nonzero(counts == 0)[0]]
        raise ValueError(
            f"empty input partition(s) at rank(s) {empty} (fewer rows "
            f"than num_workers, or skewed partitioning) — lower "
            f"num_workers or set force_repartition=True"
        )
    X = extract_matrix(partition_pdf, colspec["features"])
    y = partition_pdf[colspec["label"]].to_numpy(np.float32)
    w = (partition_pdf[colspec["weight"]].to_numpy(np.float32)
         if colspec.get("weight") else None)
    eval_set = None
    if colspec.get("val"):
        mask = partition_pdf[colspec["val"]].to_numpy(bool)
        X_val, y_val = X[mask], y[mask]
        X, y = X[~mask], y[~mask]
        if w is not None:
            w = w[~mask]
        # Early stopping is deterministic only if every worker scores
        # the IDENTICAL validation set — gather the per-partition val
        # rows across the gang (training rows stay partition-resident).
        # Guard rail: the gather replicates the val set num_workers×,
        # on the very path built for exceptionally large datasets
        # (reference xgboost.py:81-97) — warn before it gets expensive.
        warn_bytes = int(os.environ.get(
            "SPARKDL_TPU_VAL_GATHER_WARN_BYTES", 256 << 20))
        # float64, not int64: the collective canonicalizes ints to 32
        # bits (x64 off), and a >2 GiB total wrapping negative would
        # mute the guard in exactly the huge-data case it exists for
        total_val = int(hvd.allreduce(
            np.array([float(X_val.nbytes + y_val.nbytes)], np.float64),
            op=hvd.Sum)[0])
        if total_val * hvd.size() > warn_bytes:
            logger.warning(
                "validationIndicatorCol selects ~%.1f MB of rows; "
                "gathering them to all %d workers replicates ~%.1f MB "
                "for deterministic early stopping. Shrink the "
                "validation fraction, or raise "
                "SPARKDL_TPU_VAL_GATHER_WARN_BYTES to silence this.",
                total_val / 2**20, hvd.size(),
                total_val * hvd.size() / 2**20,
            )
        X_val = hvd.allgather(X_val)
        y_val = hvd.allgather(y_val)
        eval_set = [(X_val, y_val)] if len(X_val) else None
    if use_external_storage:
        # Spill executor-side: each worker memory-maps only its own
        # shard (reference xgboost.py:81-97 — this is the path the
        # driver-collect design could never reach at scale).
        import tempfile

        spill = os.path.join(
            tempfile.mkdtemp(prefix="sparkdl-xgb-spill-"), "X.npy"
        )
        np.save(spill, np.round(X, storage_precision).astype(np.float32))
        X = np.load(spill, mmap_mode="r")

    bst = B.train(
        params, np.asarray(X), y, sample_weight=w, eval_set=eval_set,
        early_stopping_rounds=esr, verbose_eval=verbose and rank == 0,
        hist_reduce=lambda a: hvd.allreduce(a, op=hvd.Sum),
        callbacks=callbacks, xgb_model=xgb_model,
    )
    return bst if rank == 0 else None


class _XgboostEstimator(Estimator, _XgboostParams, MLReadable, MLWritable):
    """Shared fit/persistence (real versions of reference
    ``xgboost.py:109-122``)."""

    def __init__(self, **kwargs):
        super().__init__()
        self._apply_kwargs(kwargs)

    def _resolve_columns(self, pdf):
        X = extract_matrix(pdf, self.getFeaturesCol())
        y = pdf[self.getLabelCol()].to_numpy(np.float32)
        w = None
        if self.isDefined(self.weightCol) and self.getOrDefault(self.weightCol):
            w = pdf[self.getOrDefault(self.weightCol)].to_numpy(np.float32)
        bm = None
        if self.isDefined(self.baseMarginCol) and self.getOrDefault(self.baseMarginCol):
            bm = pdf[self.getOrDefault(self.baseMarginCol)].to_numpy(np.float32)
        val_mask = None
        if (self.isDefined(self.validationIndicatorCol)
                and self.getOrDefault(self.validationIndicatorCol)):
            val_mask = pdf[
                self.getOrDefault(self.validationIndicatorCol)
            ].to_numpy(bool)
        return X, y, w, bm, val_mask

    def _fit_partitioned_on_spark(self, dataset, num_workers):
        """Distributed fit over partition-resident executor data;
        returns None (caller falls back to the driver-collect path)
        when no Spark backend is live."""
        try:
            from sparkdl_tpu.horovod.spark_backend import (
                maybe_launch_estimator_on_spark,
            )
        except ImportError:
            return None

        if (self.isDefined(self.baseMarginCol)
                and self.getOrDefault(self.baseMarginCol)):
            raise ValueError(
                "baseMarginCol is not available for distributed training "
                "(num_workers > 1)."
            )
        weight = (self.getOrDefault(self.weightCol)
                  if self.isDefined(self.weightCol) else None)
        if self.getOrDefault(self.use_external_storage) and weight:
            raise ValueError(
                "weightCol/baseMarginCol do not work with "
                "use_external_storage=True (reference xgboost.py:87)."
            )

        n_classes = 0
        if self._is_classifier():
            # Label cardinality via a distributed distinct — k values
            # reach the driver, never the dataset.
            label_col = self.getLabelCol()
            vals = np.asarray(
                [r[0] for r in dataset.select(label_col).distinct().collect()
                 if r[0] is not None],
                np.float32,
            )
            labels = np.unique(vals[~np.isnan(vals)])
            n_classes = int(labels.size)
            expected = np.arange(n_classes, dtype=labels.dtype)
            if n_classes < 2 or not np.array_equal(labels, expected):
                raise ValueError(
                    "XgboostClassifier requires integer labels "
                    f"0..k-1 with k>=2; got label values {labels.tolist()}"
                )

        colspec = {
            "features": self.getFeaturesCol(),
            "label": self.getLabelCol(),
            "weight": weight,
            "val": (self.getOrDefault(self.validationIndicatorCol)
                    if self.isDefined(self.validationIndicatorCol) else None),
        }
        result = maybe_launch_estimator_on_spark(
            dataset, num_workers, _partition_gang_main,
            kwargs=dict(
                params=self._booster_params(n_classes),
                colspec=colspec,
                esr=self.getOrDefault(self.early_stopping_rounds),
                verbose=self.getOrDefault(self.verbose_eval),
                callbacks=(self.getOrDefault(self.callbacks)
                           if self.isDefined(self.callbacks) else None),
                xgb_model=self.getOrDefault(self.xgb_model),
                use_external_storage=self.getOrDefault(
                    self.use_external_storage),
                storage_precision=self.getOrDefault(
                    self.external_storage_precision),
            ),
            driver_log_verbosity="log_callback_only",
            force_repartition=bool(
                self.getOrDefault(self.force_repartition)),
        )
        if result is None:
            return None
        model = self._model_class()(result.value)
        self._copyValues(model)
        return model

    def _fit(self, dataset):
        from sparkdl_tpu.ml.dataframe import is_spark_df

        num_workers = int(self.getOrDefault(self.num_workers))
        if num_workers > 1:
            model = None
            if is_spark_df(dataset):
                model = self._fit_partitioned_on_spark(dataset, num_workers)
                if model is not None:
                    return model
                reason = ("no live SparkSession / barrier backend for "
                          "this DataFrame")
            else:
                reason = "the input is not a Spark DataFrame"
            # Never change semantics silently (fail-fast philosophy,
            # reference runner_base.py:56-58): the user asked for a
            # num_workers-way partition-resident fit and is about to
            # get single-node driver-collect training instead.
            logger.warning(
                "num_workers=%d requested but distributed training is "
                "unavailable (%s); falling back to SINGLE-NODE "
                "driver-collect training. The whole dataset will be "
                "materialized on this machine.",
                num_workers, reason,
            )
        pdf, _ = to_pandas(dataset)
        X, y, w, bm, val_mask = self._resolve_columns(pdf)
        if val_mask is not None:
            X_val, y_val = X[val_mask], y[val_mask]
            X, y = X[~val_mask], y[~val_mask]
            w = None if w is None else w[~val_mask]
            bm = None if bm is None else bm[~val_mask]
        else:
            X_val = y_val = None

        if self.getOrDefault(self.use_external_storage):
            # External storage: spill the (rounded) matrix to disk and
            # train from a memory map — precision for memory, per the
            # contract (reference xgboost.py:81-97).
            if w is not None or bm is not None:
                raise ValueError(
                    "weightCol/baseMarginCol do not work with "
                    "use_external_storage=True (reference xgboost.py:87)."
                )
            import tempfile

            prec = self.getOrDefault(self.external_storage_precision)
            spill = os.path.join(
                tempfile.mkdtemp(prefix="sparkdl-xgb-spill-"), "X.npy"
            )
            np.save(spill, np.round(X, prec).astype(np.float32))
            X = np.load(spill, mmap_mode="r")

        n_classes = 0
        if self._is_classifier():
            labels = np.unique(y[~np.isnan(y)])
            n_classes = int(labels.size)
            expected = np.arange(n_classes, dtype=labels.dtype)
            if n_classes < 2 or not np.array_equal(labels, expected):
                raise ValueError(
                    "XgboostClassifier requires integer labels "
                    f"0..k-1 with k>=2; got label values {labels.tolist()}"
                )
        params = self._booster_params(n_classes)
        callbacks = (
            self.getOrDefault(self.callbacks)
            if self.isDefined(self.callbacks) else None
        )
        bst = _fit_booster(
            params, np.asarray(X), y, w, bm, X_val, y_val,
            self.getOrDefault(self.early_stopping_rounds),
            self.getOrDefault(self.verbose_eval),
            callbacks,
            self.getOrDefault(self.xgb_model),
            int(self.getOrDefault(self.num_workers)),
            bool(self.getOrDefault(self.force_repartition)),
        )
        model = self._model_class()(bst)
        self._copyValues(model)
        return model

    def _model_class(self):
        raise NotImplementedError

    # -- persistence (reference xgboost.py:117-122) -------------------------

    def _save_impl(self, path):
        with open(os.path.join(path, "estimator.json"), "w") as fh:
            json.dump(
                {"class": type(self).__name__,
                 "params": params_to_json(self)}, fh)

    @classmethod
    def _load_impl(cls, path):
        with open(os.path.join(path, "estimator.json")) as fh:
            payload = json.load(fh)
        inst = cls()
        params_from_json(inst, payload["params"])
        return inst


class _XgboostModel(Model, _XgboostParams, MLReadable, MLWritable):
    """Shared transform/persistence (real versions of reference
    ``xgboost.py:125-144``)."""

    def __init__(self, xgb_sklearn_model=None):
        super().__init__()
        self._xgb_model = xgb_sklearn_model

    def get_booster(self):
        """Return the trained :class:`sparkdl_tpu.xgboost.booster.Booster`
        (this runtime's stand-in for ``xgboost.core.Booster``, reference
        ``xgboost.py:130-134``)."""
        return self._xgb_model

    @property
    def feature_importances_(self):
        """Gain-based per-feature importances (xgboost sklearn parity)."""
        return self._xgb_model.feature_importances("gain")

    def _transform_pandas(self, pdf):
        """pandas -> pandas with prediction columns appended — the one
        inference body, run driver-side for pandas inputs and
        executor-side per partition for Spark inputs."""
        pdf = pdf.copy()
        X = extract_matrix(pdf, self.getFeaturesCol())
        margins = self._xgb_model.predict_margin(X)
        self._add_prediction_cols(pdf, margins)
        return pdf

    def _transform(self, dataset):
        from sparkdl_tpu.ml.dataframe import is_spark_df

        if is_spark_df(dataset):
            # Distributed inference: partitions stay executor-resident
            # (the reference's large-data contract, xgboost.py:81-97).
            try:
                from sparkdl_tpu.horovod.spark_backend import (
                    maybe_transform_on_spark,
                )
            except ImportError:
                pass
            else:
                out = maybe_transform_on_spark(
                    dataset, self._transform_broadcast,
                    self._prediction_schema())
                if out is not None:
                    return out
        pdf, spark_template = to_pandas(dataset)
        return to_output(self._transform_pandas(pdf), spark_template)

    def _add_prediction_cols(self, pdf, margins):
        raise NotImplementedError

    def _prediction_schema(self):
        """[(column, spark type)] appended by ``_add_prediction_cols``
        — the distributed transform builds its output schema from this
        instead of running a schema-inference job."""
        raise NotImplementedError

    def __getstate__(self):
        """Pickling (closure shipping, broadcast, persistence helpers)
        must never drag the context-bound Broadcast cache along: a
        pickled Broadcast re-registers into ITS context, which may be
        stopped — and the broadcast of this very model would recurse
        into the previous one."""
        state = dict(self.__dict__)
        state.pop("_bc", None)
        state.pop("_bc_sc_id", None)
        return state

    def _transform_broadcast(self, spark):
        """Broadcast of the inference closure (carrying this model's
        booster), cached per SparkContext: repeated transforms reuse
        ONE executor-resident model copy instead of leaking one per
        call. A context change (session restart) re-broadcasts and
        releases the stale copy. Keyed by applicationId — an id()
        could be reused by a new context allocated at a dead one's
        address."""
        import cloudpickle

        sc = spark.sparkContext
        key = getattr(sc, "applicationId", None) or id(sc)
        if self.__dict__.get("_bc_sc_id") != key:
            stale = self.__dict__.pop("_bc", None)
            self.__dict__.pop("_bc_sc_id", None)
            if stale is not None:
                try:
                    stale.unpersist()
                except Exception:  # context already gone
                    pass
            # cloudpickle BYTES, not the closure itself: Spark's
            # broadcast serializer is plain pickle, which rejects the
            # lambdas inside the Param machinery this model carries
            self._bc = sc.broadcast(
                cloudpickle.dumps(self._transform_pandas))
            self._bc_sc_id = key
        return self._bc

    def _save_impl(self, path):
        with open(os.path.join(path, "model.json"), "w") as fh:
            json.dump(
                {"class": type(self).__name__,
                 "params": params_to_json(self)}, fh)
        self._xgb_model.save(os.path.join(path, "booster"))

    @classmethod
    def _load_impl(cls, path):
        with open(os.path.join(path, "model.json")) as fh:
            payload = json.load(fh)
        inst = cls(_booster_mod.Booster.load(os.path.join(path, "booster")))
        params_from_json(inst, payload["params"])
        return inst


class XgboostRegressorModel(_XgboostModel):
    """
    The model returned by :func:`sparkdl.xgboost.XgboostRegressor.fit`
    (reference ``xgboost.py:147-153``).
    """

    def _is_classifier(self):
        return False

    def _add_prediction_cols(self, pdf, margins):
        pdf[self.getPredictionCol()] = margins[:, 0].astype(np.float64)

    def _prediction_schema(self):
        return [(self.getPredictionCol(), "double")]


class XgboostClassifierModel(_XgboostModel, HasProbabilityCol,
                             HasRawPredictionCol):
    """
    The model returned by :func:`sparkdl.xgboost.XgboostClassifier.fit`
    (reference ``xgboost.py:156-162``). ``rawPredictionCol`` always
    carries the predicted margins (the reference's ``output_margin``
    replacement, reference ``xgboost.py:274-276``).
    """

    def _is_classifier(self):
        return True

    def _add_prediction_cols(self, pdf, margins):
        if margins.shape[1] == 1:  # binary: margins for the pos class
            raw = np.concatenate([-margins, margins], axis=1)
            p1 = 1.0 / (1.0 + np.exp(-margins[:, 0]))
            proba = np.stack([1.0 - p1, p1], axis=1)
        else:
            raw = margins
            mm = margins - margins.max(axis=1, keepdims=True)
            e = np.exp(mm)
            proba = e / e.sum(axis=1, keepdims=True)
        pdf[self.getRawPredictionCol()] = list(raw.astype(np.float64))
        pdf[self.getProbabilityCol()] = list(proba.astype(np.float64))
        pdf[self.getPredictionCol()] = proba.argmax(axis=1).astype(np.float64)

    def _prediction_schema(self):
        return [(self.getRawPredictionCol(), "array<double>"),
                (self.getProbabilityCol(), "array<double>"),
                (self.getPredictionCol(), "double")]


class XgboostRegressor(_XgboostEstimator):
    """
    XgboostRegressor is an ML estimator with the surface of the
    reference's class of the same name (reference ``xgboost.py:165-
    244``): gradient-boosted regression usable in ML Pipelines and
    meta-algorithms, accepting booster hyper-parameters as constructor
    kwargs. Special params follow the renamed-param contract —
    ``weightCol`` (not sample_weight), ``validationIndicatorCol`` (not
    eval_set), ``baseMarginCol`` (not base_margin), ``use_gpu`` (not
    gpu_id; a no-op on this TPU runtime), ``missing`` with
    sparse-vector semantics.

    Training runs on the TPU-native histogram booster; with
    ``num_workers > 1`` it is distributed as a HorovodRunner gang with
    per-level histogram allreduce over ICI.
    """

    def _is_classifier(self):
        return False

    def _model_class(self):
        return XgboostRegressorModel


class XgboostClassifier(_XgboostEstimator, HasProbabilityCol,
                        HasRawPredictionCol):
    """
    XgboostClassifier is an ML estimator with the surface of the
    reference's class of the same name (reference ``xgboost.py:247-
    331``): gradient-boosted classification (binary or multiclass; the
    objective is inferred from the label cardinality unless set).
    ``rawPredictionCol`` always carries margins (the ``output_margin``
    replacement), ``probabilityCol`` the class probabilities. The
    renamed-param contract and distributed behavior match
    :class:`XgboostRegressor`.
    """

    def _is_classifier(self):
        return True

    def _model_class(self):
        return XgboostClassifierModel
