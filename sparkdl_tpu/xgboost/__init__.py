"""Gradient-boosted-tree estimators (reference
``sparkdl/xgboost/__init__.py:19-23`` public surface)."""

from sparkdl_tpu.xgboost.xgboost import (
    XgboostClassifier,
    XgboostClassifierModel,
    XgboostRegressor,
    XgboostRegressorModel,
)

__all__ = [
    "XgboostClassifier",
    "XgboostClassifierModel",
    "XgboostRegressor",
    "XgboostRegressorModel",
]
