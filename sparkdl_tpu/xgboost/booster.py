"""TPU-native gradient-boosted decision trees (the engine behind
``sparkdl_tpu.xgboost``).

The reference's estimators delegate to the XGBoost C++ library with
Rabit allreduce for distributed histogram reduction (reference
``xgboost.py:58-64``: one XGBoost worker per Spark task; SURVEY.md
§2.2). Rather than binding a CPU tree library, this is a from-scratch
histogram GBDT designed for XLA:

- **hist method** (the only tree_method, like XGBoost's ``hist``):
  features are quantile-binned to ``max_bins`` once; per-level node
  histograms are ``segment_sum`` reductions over static-shaped arrays,
  which XLA lowers to efficient scatter-adds.
- **Level-wise growth with static shapes**: a complete binary tree of
  depth ``max_depth`` in dense arrays — no Python recursion, no dynamic
  shapes; every jitted program is reused across trees and boosting
  rounds.
- **Distributed = per-level histogram allreduce**: the tree builder is
  split into jitted stages (histogram → split → route) with a
  host-side reduction hook between histogram and split. In a
  HorovodRunner gang the hook is ``hvd.allreduce`` — i.e. the Rabit
  ring is replaced by ``jax.lax.psum`` over ICI (BASELINE.json north
  star), and every worker deterministically builds the identical tree.
- **Second-order boosting** exactly as XGBoost: gain and leaf weights
  from (G, H) with ``reg_lambda``/``reg_alpha``/``gamma``/
  ``min_child_weight``; learned default direction for missing values.

Supported objectives: ``reg:squarederror``, ``binary:logistic``,
``multi:softprob``.
"""

import json
import os
from functools import partial

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------


def compute_bin_edges(X, max_bins, missing=np.nan):
    """Per-feature quantile bin edges, ignoring missing values."""
    n, f = X.shape
    edges = np.zeros((f, max_bins - 1), np.float32)
    for j in range(f):
        col = X[:, j]
        if np.isnan(missing):
            valid = col[~np.isnan(col)]
        else:
            valid = col[(col != missing) & ~np.isnan(col)]
        if valid.size == 0:
            continue
        qs = np.quantile(
            valid.astype(np.float64),
            np.linspace(0, 1, max_bins + 1)[1:-1],
        )
        edges[j] = qs.astype(np.float32)
    return edges


def bin_data(X, edges, missing=np.nan):
    """Map raw features to bin indices; missing → bin ``max_bins``
    (its own bin, so the builder can learn a default direction)."""
    n, f = X.shape
    max_bins = edges.shape[1] + 1
    out = np.empty((n, f), np.int32)
    for j in range(f):
        out[:, j] = np.searchsorted(edges[j], X[:, j], side="right")
    if np.isnan(missing):
        miss = np.isnan(X)
    else:
        miss = (X == missing) | np.isnan(X)
    out[miss] = max_bins
    return out


# ---------------------------------------------------------------------------
# Jitted tree-building stages (cached per static config)
# ---------------------------------------------------------------------------


def _hist_stage(binned, g, h, pos, level_start, *, nodes_d, n_bins_tot):
    """Per-(node, feature, bin) gradient/hessian histograms for one
    level. Rows already settled in an earlier leaf contribute zero."""
    import jax
    import jax.numpy as jnp

    node_local = pos - level_start
    active = (node_local >= 0) & (node_local < nodes_d)
    node_local = jnp.clip(node_local, 0, nodes_d - 1)
    gz = jnp.where(active, g, 0.0)
    hz = jnp.where(active, h, 0.0)

    def per_feature(bins_f):
        seg = node_local * n_bins_tot + bins_f
        hg = jax.ops.segment_sum(gz, seg, num_segments=nodes_d * n_bins_tot)
        hh = jax.ops.segment_sum(hz, seg, num_segments=nodes_d * n_bins_tot)
        return hg, hh

    hg, hh = jax.vmap(per_feature, in_axes=1)(binned)  # (F, nodes*B)
    f = binned.shape[1]
    hg = hg.reshape(f, nodes_d, n_bins_tot).transpose(1, 0, 2)
    hh = hh.reshape(f, nodes_d, n_bins_tot).transpose(1, 0, 2)
    return hg, hh


def _split_stage(hist_g, hist_h, feature_mask, lower=None, upper=None,
                 *, reg_lambda, reg_alpha, gamma, min_child_weight,
                 learning_rate, monotone=None):
    """Best (feature, threshold, missing-direction) per node, plus the
    node's would-be leaf weight. All candidates evaluated in parallel on
    the vector unit; no data-dependent control flow.

    Monotone constraints (xgboost's ``monotone_constraints``):
    ``monotone`` is a per-feature vector in {-1, 0, +1}; ``lower``/
    ``upper`` are the node's inherited weight bounds in RAW weight
    space (no learning-rate factor — lr > 0 preserves order, and raw
    bounds keep the math lr-free). Candidate child weights are clamped
    to the bounds, their gains recomputed FROM the clamped weights
    (xgboost's CalcGainGivenWeight — an unclamped gain would overstate
    splits whose optimum lies outside the bounds), and splits whose
    clamped child weights violate the feature's direction are
    rejected. The caller propagates mid bounds to the children from
    the returned per-node child weights; together with leaf clamping
    this makes the final forest monotone in the constrained
    features."""
    import jax.numpy as jnp

    nodes_d, f, n_bins_tot = hist_g.shape
    n_bins = n_bins_tot - 1  # last slot is the missing bin

    def soft(gs):
        return jnp.sign(gs) * jnp.maximum(jnp.abs(gs) - reg_alpha, 0.0)

    def score(gs, hs):
        return soft(gs) ** 2 / (hs + reg_lambda)
    # (best_gain is also surfaced so trees can report per-feature gain
    # importances, xgboost sklearn-API parity)

    miss_g = hist_g[..., n_bins]          # (nodes, F)
    miss_h = hist_h[..., n_bins]
    cg = jnp.cumsum(hist_g[..., :n_bins], axis=-1)  # (nodes, F, B)
    ch = jnp.cumsum(hist_h[..., :n_bins], axis=-1)
    g_tot = cg[..., -1] + miss_g          # (nodes, F) — same for all F
    h_tot = ch[..., -1] + miss_h
    # thresholds t = 0..B-2 → left = bins <= t
    gl = cg[..., :-1]                     # (nodes, F, B-1)
    hl = ch[..., :-1]
    parent = score(g_tot[..., :1, None], h_tot[..., :1, None])

    def raw_weight(gs, hs):
        # optimal leaf value in RAW space (no lr; lr scales at the end)
        return -soft(gs) / (hs + reg_lambda)

    def clamp(ws):
        if lower is None:
            return ws
        nd = ws.ndim - 1
        return jnp.clip(ws, lower[(...,) + (None,) * nd],
                        upper[(...,) + (None,) * nd])

    def score_given_weight(gs, hs, ws):
        # objective reduction achieved by leaf value ws (equals
        # score() at the unclamped optimum; smaller when bounds bite)
        return -(2.0 * gs * ws + (hs + reg_lambda) * ws * ws
                 + 2.0 * reg_alpha * jnp.abs(ws))

    def split_gain(gl_, hl_):
        # RAW loss improvement (xgboost's loss_chg); gamma is applied
        # only as the split-acceptance threshold below, so reported
        # gains match xgboost's importances under nonzero gamma.
        gr_ = g_tot[..., None] - gl_
        hr_ = h_tot[..., None] - hl_
        if monotone is None:
            gain = 0.5 * (score(gl_, hl_) + score(gr_, hr_) - parent)
        else:
            wl_ = clamp(raw_weight(gl_, hl_))
            wr_ = clamp(raw_weight(gr_, hr_))
            parent_w = clamp(raw_weight(g_tot[..., :1, None],
                                        h_tot[..., :1, None]))
            gain = 0.5 * (
                score_given_weight(gl_, hl_, wl_)
                + score_given_weight(gr_, hr_, wr_)
                - score_given_weight(g_tot[..., :1, None],
                                     h_tot[..., :1, None], parent_w)
            )
        ok = (hl_ >= min_child_weight) & (hr_ >= min_child_weight)
        if monotone is not None:
            c = jnp.asarray(monotone, jnp.int32)[None, :, None]
            ok = ok & ~((c > 0) & (wl_ > wr_)) & ~((c < 0) & (wl_ < wr_))
        return jnp.where(ok, gain, -jnp.inf)

    gain_mr = split_gain(gl, hl)                              # missing→right
    gl_ml = gl + miss_g[..., None]
    hl_ml = hl + miss_h[..., None]
    gain_ml = split_gain(gl_ml, hl_ml)
    gain = jnp.maximum(gain_mr, gain_ml)                      # (nodes,F,B-1)
    missing_left = gain_ml >= gain_mr
    gain = jnp.where(feature_mask[None, :, None], gain, -jnp.inf)

    flat = gain.reshape(nodes_d, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    best_feat = best // (n_bins - 1)
    best_thr = best % (n_bins - 1)
    best_ml = jnp.take_along_axis(
        missing_left.reshape(nodes_d, -1), best[:, None], axis=1
    )[:, 0]
    # Node's leaf weight if it does NOT split (also used at final level).
    raw_leaf = raw_weight(g_tot[:, 0], h_tot[:, 0])
    if lower is not None:
        raw_leaf = jnp.clip(raw_leaf, lower, upper)
    leaf_w = learning_rate * raw_leaf
    empty = h_tot[:, 0] <= 0.0
    leaf_w = jnp.where(empty, 0.0, leaf_w)
    do_split = best_gain > gamma

    # Chosen split's clamped RAW child weights, for the caller's child
    # bound propagation. Zeros when constraints are off.
    if monotone is not None:
        def pick(arr3):
            return jnp.take_along_axis(
                arr3.reshape(nodes_d, -1), best[:, None], axis=1
            )[:, 0]

        gl_best = jnp.where(best_ml, pick(gl_ml), pick(gl))
        hl_best = jnp.where(best_ml, pick(hl_ml), pick(hl))
        wl_best = raw_weight(gl_best, hl_best)
        wr_best = raw_weight(g_tot[:, 0] - gl_best,
                             h_tot[:, 0] - hl_best)
        if lower is not None:
            wl_best = jnp.clip(wl_best, lower, upper)
            wr_best = jnp.clip(wr_best, lower, upper)
    else:
        wl_best = jnp.zeros_like(leaf_w)
        wr_best = jnp.zeros_like(leaf_w)
    return (do_split, best_feat, best_thr, best_ml, leaf_w, best_gain,
            wl_best, wr_best)


def _route_stage(binned, pos, level_start, do_split, feat, thr,
                 missing_left, *, nodes_d, n_bins):
    """Advance each active row to its child node."""
    import jax.numpy as jnp

    node_local = pos - level_start
    active = (node_local >= 0) & (node_local < nodes_d)
    nl = jnp.clip(node_local, 0, nodes_d - 1)
    row_feat = jnp.take_along_axis(binned, feat[nl][:, None], axis=1)[:, 0]
    is_missing = row_feat == n_bins
    go_right = jnp.where(
        is_missing, ~missing_left[nl], row_feat > thr[nl]
    )
    child = 2 * pos + 1 + go_right.astype(jnp.int32)
    return jnp.where(active & do_split[nl], child, pos)


def _predict_stage(binned, feat, thr, missing_left, is_split, leaf_w,
                   *, max_depth, n_bins):
    """Vectorized descent of one tree for all rows."""
    import jax.numpy as jnp

    n = binned.shape[0]
    pos = jnp.zeros((n,), jnp.int32)
    for _ in range(max_depth):
        row_feat = jnp.take_along_axis(
            binned, feat[pos][:, None], axis=1
        )[:, 0]
        is_missing = row_feat == n_bins
        go_right = jnp.where(is_missing, ~missing_left[pos], row_feat > thr[pos])
        child = 2 * pos + 1 + go_right.astype(jnp.int32)
        pos = jnp.where(is_split[pos], child, pos)
    return leaf_w[pos]


def _monotone_child_bounds(lower, upper, wl, wr, constraint, do_split):
    """Child [lower, upper] RAW-weight bounds for the next level,
    given each node's chosen split (xgboost's bound propagation: a +1
    split caps the left subtree at mid and floors the right, mirrored
    for -1; unconstrained features pass bounds through)."""
    import jax.numpy as jnp

    mid = 0.5 * (wl + wr)
    pos = do_split & (constraint > 0)
    neg = do_split & (constraint < 0)
    l_lo = jnp.where(neg, jnp.maximum(lower, mid), lower)
    l_hi = jnp.where(pos, jnp.minimum(upper, mid), upper)
    r_lo = jnp.where(pos, jnp.maximum(lower, mid), lower)
    r_hi = jnp.where(neg, jnp.minimum(upper, mid), upper)
    interleave = lambda a, b: jnp.stack([a, b], axis=1).reshape(-1)
    return interleave(l_lo, r_lo), interleave(l_hi, r_hi)


def _build_tree_fused(binned, g, h, feature_mask, *, max_depth,
                      n_bins_tot, reg_lambda, reg_alpha, gamma,
                      min_child_weight, learning_rate, monotone=None):
    """Single-program tree builder: all levels (histogram → split →
    route) unrolled inside ONE trace, plus the tree's margin deltas.

    This is the single-process fast path: one XLA dispatch and one
    compile per (n, f, depth) config for the entire tree, instead of
    ~3 dispatches and 3 compiles per level — which matters doubly on
    remote-dispatch TPU setups. The distributed path keeps the staged
    per-level form because the histogram allreduce crosses the host.
    """
    import jax
    import jax.numpy as jnp

    n, f = binned.shape
    n_bins = n_bins_tot - 1
    n_nodes = 2 ** (max_depth + 1) - 1
    feat_arr = jnp.zeros((n_nodes,), jnp.int32)
    gain_arr = jnp.zeros((n_nodes,), jnp.float32)
    thr_arr = jnp.zeros((n_nodes,), jnp.int32)
    ml_arr = jnp.zeros((n_nodes,), bool)
    split_arr = jnp.zeros((n_nodes,), bool)
    leaf_arr = jnp.zeros((n_nodes,), jnp.float32)
    pos = jnp.zeros((n,), jnp.int32)

    lower = jnp.full((1,), -jnp.inf, jnp.float32)
    upper = jnp.full((1,), jnp.inf, jnp.float32)
    for d in range(max_depth + 1):
        nodes_d = 2 ** d
        level_start = nodes_d - 1
        hg, hh = _hist_stage(
            binned, g, h, pos, level_start,
            nodes_d=nodes_d, n_bins_tot=n_bins_tot,
        )
        do_split, bf, bt, bml, leaf_w, gains, wl, wr = _split_stage(
            hg, hh, feature_mask,
            lower if monotone is not None else None,
            upper if monotone is not None else None,
            reg_lambda=reg_lambda,
            reg_alpha=reg_alpha, gamma=gamma,
            min_child_weight=min_child_weight,
            learning_rate=learning_rate, monotone=monotone,
        )
        if d == max_depth:
            do_split = jnp.zeros_like(do_split)
        sl = slice(level_start, level_start + nodes_d)
        feat_arr = feat_arr.at[sl].set(bf)
        gain_arr = gain_arr.at[sl].set(
            jnp.where(do_split, jnp.maximum(gains, 0.0), 0.0)
        )
        thr_arr = thr_arr.at[sl].set(bt)
        ml_arr = ml_arr.at[sl].set(bml)
        split_arr = split_arr.at[sl].set(do_split)
        leaf_arr = leaf_arr.at[sl].set(jnp.where(do_split, 0.0, leaf_w))
        if d < max_depth:
            pos = _route_stage(
                binned, pos, level_start, do_split, bf, bt, bml,
                nodes_d=nodes_d, n_bins=n_bins,
            )
            if monotone is not None:
                c = jnp.asarray(monotone, jnp.int32)[bf]
                lower, upper = _monotone_child_bounds(
                    lower, upper, wl, wr, c, do_split
                )

    delta = _predict_stage(
        binned, feat_arr, thr_arr, ml_arr, split_arr, leaf_arr,
        max_depth=max_depth, n_bins=n_bins,
    )
    return feat_arr, thr_arr, ml_arr, split_arr, leaf_arr, gain_arr, delta


# ---------------------------------------------------------------------------
# Objectives / metrics
# ---------------------------------------------------------------------------


def _grad_hess(objective, margins, y, weights, n_classes):
    jnp = _jnp()
    if objective == "reg:squarederror":
        g = margins[:, 0] - y
        h = jnp.ones_like(g)
        gh = g[:, None], h[:, None]
    elif objective == "binary:logistic":
        p = 1.0 / (1.0 + jnp.exp(-margins[:, 0]))
        gh = (p - y)[:, None], (p * (1.0 - p))[:, None]
    elif objective == "multi:softprob":
        m = margins - margins.max(axis=1, keepdims=True)
        e = jnp.exp(m)
        p = e / e.sum(axis=1, keepdims=True)
        onehot = (y[:, None] == jnp.arange(n_classes)[None, :]).astype(p.dtype)
        gh = p - onehot, p * (1.0 - p)
    else:
        raise ValueError(f"Unsupported objective: {objective}")
    g, h = gh
    return g * weights[:, None], h * weights[:, None]


def _eval_metric(metric, margins, y, n_classes):
    m = np.asarray(margins)
    if metric == "rmse":
        return float(np.sqrt(np.mean((m[:, 0] - y) ** 2)))
    if metric == "logloss":
        p = 1.0 / (1.0 + np.exp(-m[:, 0]))
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
    if metric == "mlogloss":
        mm = m - m.max(axis=1, keepdims=True)
        p = np.exp(mm) / np.exp(mm).sum(axis=1, keepdims=True)
        p = np.clip(p[np.arange(len(y)), y.astype(int)], 1e-15, None)
        return float(-np.mean(np.log(p)))
    if metric == "error":
        if m.shape[1] == 1:
            pred = (m[:, 0] > 0).astype(int)
        else:
            pred = m.argmax(axis=1)
        return float(np.mean(pred != y))
    raise ValueError(f"Unsupported eval_metric: {metric}")


_DEFAULT_METRIC = {
    "reg:squarederror": "rmse",
    "binary:logistic": "logloss",
    "multi:softprob": "mlogloss",
}

# Compiled predict kernels, shared across Boosters and transform()
# calls (keyed by the static config; jax caches per input shape).
_PREDICT_FNS = {}


def _predict_fn(max_depth, n_bins):
    import jax

    key = (max_depth, n_bins)
    fn = _PREDICT_FNS.get(key)
    if fn is None:
        fn = jax.jit(partial(_predict_stage, max_depth=max_depth,
                             n_bins=n_bins))
        _PREDICT_FNS[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Booster
# ---------------------------------------------------------------------------


class Booster:
    """A trained forest: dense per-tree arrays + binning metadata.

    Plays the role of ``xgboost.core.Booster`` in the reference contract
    (``xgboost.py:130-134``): what ``model.get_booster()`` returns and
    what ``xgb_model`` warm-start consumes.
    """

    def __init__(self, params, edges, missing, trees, base_score,
                 n_classes, best_iteration=None, n_base_trees=0):
        self.params = dict(params)
        self.edges = edges
        self.missing = missing
        self.trees = trees  # list of dicts of np arrays, len = rounds*K
        self.base_score = base_score
        self.n_classes = n_classes
        # best_iteration counts boosting rounds of the LAST train()
        # call; n_base_trees is how many trees predate it (warm start),
        # which best-iteration truncation must keep.
        self.best_iteration = best_iteration
        self.n_base_trees = n_base_trees

    # -- persistence --------------------------------------------------------

    def save(self, path):
        os.makedirs(path, exist_ok=True)
        base = self.base_score
        if isinstance(base, np.ndarray):
            base = base.tolist()
        elif isinstance(base, (np.floating, np.integer)):
            base = float(base)
        meta = {
            "params": self.params,
            "missing": None if np.isnan(self.missing) else float(self.missing),
            "base_score": base,
            "n_classes": self.n_classes,
            "n_trees": len(self.trees),
            "best_iteration": self.best_iteration,
            "n_base_trees": self.n_base_trees,
        }

        def _np_safe(o):
            if isinstance(o, (np.floating, np.integer)):
                return o.item()
            if isinstance(o, np.ndarray):
                return o.tolist()
            raise TypeError(f"not JSON serializable: {type(o)}")

        with open(os.path.join(path, "booster.json"), "w") as fh:
            json.dump(meta, fh, default=_np_safe)
        arrays = {"edges": self.edges}
        for i, t in enumerate(self.trees):
            for k, v in t.items():
                arrays[f"t{i}_{k}"] = v
        np.savez_compressed(os.path.join(path, "trees.npz"), **arrays)

    @classmethod
    def load(cls, path):
        with open(os.path.join(path, "booster.json")) as fh:
            meta = json.load(fh)
        data = np.load(os.path.join(path, "trees.npz"))
        trees = []
        keys = ("feat", "thr", "missing_left", "is_split", "leaf_w")
        for i in range(meta["n_trees"]):
            t = {k: data[f"t{i}_{k}"] for k in keys}
            gk = f"t{i}_gain"
            t["gain"] = (data[gk] if gk in data
                         else np.zeros_like(t["leaf_w"]))
            trees.append(t)
        missing = np.nan if meta["missing"] is None else meta["missing"]
        base = meta["base_score"]
        if isinstance(base, list):
            base = np.asarray(base, np.float32)
        return cls(meta["params"], data["edges"], missing, trees, base,
                   meta["n_classes"], meta.get("best_iteration"),
                   meta.get("n_base_trees", 0))

    # -- inference ----------------------------------------------------------

    def predict_margin(self, X, iteration_range=None):
        X = np.asarray(X, np.float32)
        binned = bin_data(X, self.edges, self.missing)
        max_depth = int(self.params["max_depth"])
        n_bins = self.edges.shape[1] + 1
        k = max(self.n_classes, 1) if self.n_classes > 2 else 1
        margins = np.zeros((X.shape[0], k), np.float32) + self.base_score
        trees = self.trees
        if iteration_range is None and self.best_iteration is not None:
            # keep warm-start trees + the best rounds of the last fit
            trees = trees[: self.n_base_trees + (self.best_iteration + 1) * k]
        elif iteration_range is not None:
            trees = trees[iteration_range[0] * k : iteration_range[1] * k]
        fn = _predict_fn(max_depth, n_bins)
        for i, t in enumerate(trees):
            margins[:, i % k] += np.asarray(fn(
                binned, t["feat"], t["thr"], t["missing_left"],
                t["is_split"], t["leaf_w"],
            ))
        return margins

    def predict(self, X):
        m = self.predict_margin(X)
        obj = self.params.get("objective")
        if obj == "binary:logistic":
            return (1.0 / (1.0 + np.exp(-m[:, 0])) > 0.5).astype(np.int32)
        if obj == "multi:softprob":
            return m.argmax(axis=1).astype(np.int32)
        return m[:, 0]

    def feature_importances(self, importance_type="gain"):
        """Per-feature importances over the forest (xgboost sklearn-API
        semantics): ``gain`` = AVERAGE raw split gain per feature,
        ``total_gain`` = summed gains, ``weight`` = split counts — all
        normalized to sum to 1."""
        if importance_type not in ("gain", "total_gain", "weight"):
            raise ValueError(
                "importance_type must be 'gain', 'total_gain' or "
                f"'weight', got {importance_type!r}"
            )
        n_features = self.edges.shape[0]
        gain_sum = np.zeros((n_features,), np.float64)
        counts = np.zeros((n_features,), np.float64)
        for t in self.trees:
            feats = t["feat"][t["is_split"]]
            np.add.at(gain_sum, feats, t["gain"][t["is_split"]])
            np.add.at(counts, feats, 1.0)
        if importance_type == "weight":
            acc = counts
        elif importance_type == "total_gain":
            acc = gain_sum
        else:  # xgboost's 'gain': average gain per split
            acc = np.divide(
                gain_sum, counts, out=np.zeros_like(gain_sum),
                where=counts > 0,
            )
        total = acc.sum()
        return (acc / total if total > 0 else acc).astype(np.float32)

    def predict_proba(self, X):
        m = self.predict_margin(X)
        if self.params.get("objective") == "binary:logistic":
            p1 = 1.0 / (1.0 + np.exp(-m[:, 0]))
            return np.stack([1 - p1, p1], axis=1)
        mm = m - m.max(axis=1, keepdims=True)
        e = np.exp(mm)
        return e / e.sum(axis=1, keepdims=True)


def _parse_monotone(spec, n_features):
    """xgboost's monotone_constraints formats → int32 (n_features,)
    vector or None: "(1,-1,0)" string or list/tuple (length must equal
    n_features, as in xgboost), or a partial {feature_index: c} dict
    (unlisted features unconstrained; name-keyed dicts need a column
    order we don't have)."""
    if spec is None:
        return None
    if isinstance(spec, str):
        body = spec.strip().strip("()")
        spec = [int(s) for s in body.split(",") if s.strip()] if body \
            else []
    if isinstance(spec, dict):
        if not spec:
            return None
        if not all(isinstance(k, (int, np.integer)) for k in spec):
            raise ValueError(
                "monotone_constraints dicts must be keyed by feature "
                "index (column names are not tracked here)"
            )
        if not all(0 <= int(k) < n_features for k in spec):
            raise ValueError(
                f"monotone_constraints feature indices must be in "
                f"[0, {n_features}); got {sorted(spec)}"
            )
        out = np.zeros(n_features, np.int32)
        for idx, c in spec.items():
            out[int(idx)] = int(c)
        arr = out
    else:
        arr = np.asarray(spec, np.int32).reshape(-1)
        if arr.size == 0:
            return None
        if arr.size != n_features:
            raise ValueError(
                f"monotone_constraints has {arr.size} entries for "
                f"{n_features} features"
            )
    if not np.isin(arr, (-1, 0, 1)).all():
        raise ValueError(
            f"monotone_constraints values must be -1, 0, or 1; got "
            f"{sorted(set(arr.tolist()))}"
        )
    return arr if np.any(arr) else None


def train(params, X, y, *, sample_weight=None, base_margin=None,
          eval_set=None, early_stopping_rounds=None, hist_reduce=None,
          callbacks=None, verbose_eval=False, xgb_model=None):
    """Train a Booster.

    :param hist_reduce: optional ``f(np.ndarray) -> np.ndarray`` summing
        histograms across workers — in a HorovodRunner gang this is
        ``hvd.allreduce(op=Sum)``, replacing Rabit (reference
        ``xgboost.py:61``). Bin edges are made consistent across
        workers by averaging their quantiles through the same reducer.
    """
    import jax

    p = dict(params)
    objective = p.setdefault("objective", "reg:squarederror")
    n_estimators = int(p.pop("n_estimators", 100))
    max_depth = int(p.setdefault("max_depth", 6))
    learning_rate = float(p.pop("learning_rate", 0.3))
    reg_lambda = float(p.pop("reg_lambda", 1.0))
    reg_alpha = float(p.pop("reg_alpha", 0.0))
    gamma = float(p.pop("gamma", 0.0))
    min_child_weight = float(p.pop("min_child_weight", 1.0))
    subsample = float(p.pop("subsample", 1.0))
    colsample_bytree = float(p.pop("colsample_bytree", 1.0))
    max_bins = int(p.pop("max_bin", p.pop("max_bins", 256)))
    missing = p.pop("missing", np.nan)
    scale_pos_weight = float(p.pop("scale_pos_weight", 1.0))
    user_base_score = p.pop("base_score", None)
    seed = int(p.pop("random_state", p.pop("seed", 0)))
    monotone_spec = p.pop("monotone_constraints", None)
    n_classes = int(p.pop("num_class", 0))
    eval_metric = p.pop("eval_metric", None) or _DEFAULT_METRIC[objective]
    p["max_depth"] = max_depth

    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    n, f = X.shape
    monotone = _parse_monotone(monotone_spec, f)
    w = (np.ones(n, np.float32) if sample_weight is None
         else np.asarray(sample_weight, np.float32))
    if scale_pos_weight != 1.0:
        if objective == "binary:logistic":
            # xgboost semantics: positive-class instances weighted up
            w = np.where(y == 1.0, w * scale_pos_weight, w)
        else:
            import logging

            logging.getLogger("sparkdl.xgboost").warning(
                "scale_pos_weight only applies to binary:logistic; "
                "ignored for objective %r.", objective,
            )

    if xgb_model is not None:
        edges = xgb_model.edges
    else:
        edges = compute_bin_edges(X, max_bins, missing)
        if hist_reduce is not None:
            # Deterministic global edges: average worker quantiles (all
            # workers must agree or trees diverge).
            edges = hist_reduce(edges) / _reduce_count(hist_reduce)
    binned = np.asarray(bin_data(X, edges, missing))
    n_bins_tot = max_bins + 1

    k = n_classes if objective == "multi:softprob" else 1
    if k > 1 and n_classes < 2:
        raise ValueError("multi:softprob requires num_class >= 2")

    # base score (a warm start must keep the base its trees were fit
    # against — recomputing from the new labels would shift every
    # prediction by the difference)
    if xgb_model is not None:
        base_score = xgb_model.base_score
        base = np.asarray(base_score, np.float32).reshape(-1)
    elif user_base_score is not None:
        # user-provided base_score (xgboost semantics: a probability
        # for logistic objectives → logit margin; raw value otherwise)
        b = float(user_base_score)
        if objective == "binary:logistic":
            if not 0.0 < b < 1.0:
                raise ValueError(
                    f"base_score must be in (0, 1) for binary:logistic; "
                    f"got {b}"
                )
            b = float(np.log(b / (1.0 - b)))
        base_score = np.float32(b)
        base = np.full((max(k, 1),), base_score, np.float32)
        if k > 1:
            base_score = base
    elif objective == "reg:squarederror":
        ssum = np.array([np.sum(y * w), np.sum(w)], np.float64)
        if hist_reduce is not None:
            ssum = hist_reduce(ssum)
        base_score = np.float32(ssum[0] / max(ssum[1], 1e-12))
        base = np.full((1,), base_score, np.float32)
    else:
        base = np.zeros((max(k, 1),), np.float32)
        base_score = base if k > 1 else np.float32(0.0)

    trees = list(xgb_model.trees) if xgb_model is not None else []
    margins = np.zeros((n, max(k, 1)), np.float32) + base
    if base_margin is not None:
        margins += np.asarray(base_margin, np.float32).reshape(n, -1)
    if xgb_model is not None and trees and base_margin is None:
        # Continuation keeps ALL base trees, so the starting margins
        # must come from the FULL base forest — not the base model's
        # best_iteration truncation — or the new trees would be fit
        # against residuals inconsistent with prediction time.
        full_range = (0, len(trees) // max(k, 1))
        margins = xgb_model.predict_margin(X, iteration_range=full_range)

    n_base_trees = len(trees)

    # eval set (warm-start trees must contribute to the metric too)
    ev = None
    if eval_set:
        Xv, yv = eval_set[0]
        Xv = np.asarray(Xv, np.float32)
        yv = np.asarray(yv, np.float32)
        binned_v = np.asarray(bin_data(Xv, edges, missing))
        if xgb_model is not None and n_base_trees:
            margins_v = xgb_model.predict_margin(
                Xv, iteration_range=(0, n_base_trees // max(k, 1))
            ).astype(np.float32)
        else:
            margins_v = np.zeros((Xv.shape[0], max(k, 1)), np.float32) + base
        ev = (binned_v, yv, margins_v)

    # jitted stages, cached per (level, static config)
    hist_fns = {}
    route_fns = {}
    split_fn = jax.jit(partial(
        _split_stage, reg_lambda=reg_lambda, reg_alpha=reg_alpha,
        gamma=gamma, min_child_weight=min_child_weight,
        learning_rate=learning_rate, monotone=monotone,
    ))
    fused_fn = jax.jit(partial(
        _build_tree_fused, max_depth=max_depth, n_bins_tot=n_bins_tot,
        reg_lambda=reg_lambda, reg_alpha=reg_alpha, gamma=gamma,
        min_child_weight=min_child_weight, learning_rate=learning_rate,
        monotone=monotone,
    ))
    predict_fn = jax.jit(partial(
        _predict_stage, max_depth=max_depth, n_bins=max_bins
    ))
    grad_fn = jax.jit(partial(_grad_hess, objective, n_classes=max(k, 1)))

    rng = np.random.RandomState(seed)
    n_nodes = 2 ** (max_depth + 1) - 1
    best_score, best_iter, since_best = np.inf, 0, 0

    for rnd in range(n_estimators):
        g_all, h_all = grad_fn(margins, y, w)
        g_all = np.asarray(g_all)
        h_all = np.asarray(h_all)
        # row subsample + feature subsample (deterministic across the
        # gang: every worker uses the same seed sequence)
        row_mask = (
            (rng.rand(n) < subsample).astype(np.float32)
            if subsample < 1.0 else None
        )
        feature_mask = np.ones((f,), bool)
        if colsample_bytree < 1.0:
            keep = max(1, int(round(colsample_bytree * f)))
            # Dedicated per-round RNG: every worker must pick the SAME
            # features regardless of local row count (row_mask draws
            # consume worker-dependent amounts of the main stream).
            frng = np.random.RandomState(seed * 100003 + rnd)
            feature_mask = np.zeros((f,), bool)
            feature_mask[frng.choice(f, keep, replace=False)] = True

        for cls_i in range(max(k, 1)):
            g = g_all[:, cls_i]
            h = h_all[:, cls_i]
            if row_mask is not None:
                g, h = g * row_mask, h * row_mask
            if hist_reduce is None:
                # Single-process fast path: the whole tree (all levels
                # + margin delta) is ONE jitted program.
                bf, bt, bml, bsp, blw, bg, delta = fused_fn(
                    binned, g, h, feature_mask
                )
                tree = {
                    "feat": np.asarray(bf),
                    "thr": np.asarray(bt),
                    "missing_left": np.asarray(bml),
                    "is_split": np.asarray(bsp),
                    "leaf_w": np.asarray(blw),
                    "gain": np.asarray(bg),
                }
                delta = np.asarray(delta)
            else:
                tree = {
                    "feat": np.zeros(n_nodes, np.int32),
                    "thr": np.zeros(n_nodes, np.int32),
                    "missing_left": np.zeros(n_nodes, bool),
                    "is_split": np.zeros(n_nodes, bool),
                    "leaf_w": np.zeros(n_nodes, np.float32),
                    "gain": np.zeros(n_nodes, np.float32),
                }
                pos = np.zeros((n,), np.int32)
                lo_d = np.full((1,), -np.inf, np.float32)
                hi_d = np.full((1,), np.inf, np.float32)
                for d in range(max_depth + 1):
                    nodes_d = 2 ** d
                    level_start = nodes_d - 1
                    if d not in hist_fns:
                        hist_fns[d] = jax.jit(partial(
                            _hist_stage, nodes_d=nodes_d,
                            n_bins_tot=n_bins_tot,
                        ))
                        route_fns[d] = jax.jit(partial(
                            _route_stage, nodes_d=nodes_d, n_bins=max_bins
                        ))
                    hg, hh = hist_fns[d](binned, g, h, pos, level_start)
                    # THE distributed step: one allreduce per level, on
                    # (nodes, F, bins+1) histograms — Rabit → ICI.
                    # (Bounds need no reduction: they derive from the
                    # already-reduced histograms, identically everywhere.)
                    stacked = np.stack([np.asarray(hg), np.asarray(hh)])
                    stacked = hist_reduce(stacked)
                    hg, hh = stacked[0], stacked[1]
                    do_split, bf, bt, bml, leaf_w, gains, wl, wr = \
                        split_fn(
                            hg, hh, feature_mask,
                            lo_d if monotone is not None else None,
                            hi_d if monotone is not None else None,
                        )
                    do_split = np.asarray(do_split)
                    if monotone is not None and d < max_depth:
                        import jax.numpy as jnp

                        c = monotone[np.asarray(bf)]
                        lo_d, hi_d = (
                            np.asarray(b) for b in _monotone_child_bounds(
                                jnp.asarray(lo_d), jnp.asarray(hi_d),
                                wl, wr, jnp.asarray(c),
                                jnp.asarray(do_split),
                            )
                        )
                    if d == max_depth:
                        do_split = np.zeros_like(do_split)
                    sl = slice(level_start, level_start + nodes_d)
                    tree["feat"][sl] = np.asarray(bf)
                    tree["gain"][sl] = np.where(
                        do_split, np.maximum(np.asarray(gains), 0.0), 0.0
                    )
                    tree["thr"][sl] = np.asarray(bt)
                    tree["missing_left"][sl] = np.asarray(bml)
                    tree["is_split"][sl] = do_split
                    tree["leaf_w"][sl] = np.where(
                        do_split, 0.0, np.asarray(leaf_w)
                    )
                    if d < max_depth and do_split.any():
                        pos = np.asarray(route_fns[d](
                            binned, pos, level_start,
                            do_split, bf, bt, bml,
                        ))
                    elif not do_split.any():
                        break
                delta = np.asarray(predict_fn(
                    binned, tree["feat"], tree["thr"],
                    tree["missing_left"], tree["is_split"], tree["leaf_w"],
                ))
            # shared tail for both paths
            trees.append(tree)
            margins[:, cls_i] += delta
            if ev is not None:
                ev[2][:, cls_i] += np.asarray(predict_fn(
                    ev[0], tree["feat"], tree["thr"], tree["missing_left"],
                    tree["is_split"], tree["leaf_w"],
                ))

        if callbacks:
            for cb in callbacks:
                try:
                    cb(rnd, margins)
                except TypeError:
                    cb(rnd)
        if ev is not None:
            score = _eval_metric(eval_metric, ev[2], ev[1], max(k, 1))
            if verbose_eval:
                print(f"[{rnd}] validation-{eval_metric}: {score:.6f}")
            if score < best_score - 1e-12:
                best_score, best_iter, since_best = score, rnd, 0
            else:
                since_best += 1
                if (early_stopping_rounds
                        and since_best >= early_stopping_rounds):
                    break

    booster = Booster(
        {**p, "objective": objective}, edges, missing, trees,
        base_score if k <= 1 else base, max(n_classes, k),
        best_iteration=(best_iter if ev is not None
                        and early_stopping_rounds else None),
        n_base_trees=n_base_trees,
    )
    return booster


def _reduce_count(hist_reduce):
    """Number of workers participating in hist_reduce (sum of ones)."""
    return float(hist_reduce(np.ones((1,), np.float64))[0])
