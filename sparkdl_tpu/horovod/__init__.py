"""Worker-side utilities for HorovodRunner jobs.

Parity with reference ``sparkdl/horovod/__init__.py``, whose single
public symbol ``log_to_driver`` is a ``NotImplementedError`` stub
(reference ``sparkdl/horovod/__init__.py:20-25``). Here it is
implemented for real on top of the control plane
(:mod:`sparkdl_tpu.horovod.control_plane`): inside a HorovodRunner
worker the message travels over the worker→driver TCP channel and the
driver prints it to stdout; outside any job (e.g. local ``np=-1`` mode,
where driver == worker) it is printed directly.
"""

MAX_LOG_MESSAGE_LENGTH = 4000  # reference sparkdl/horovod/__init__.py:23


def log_to_driver(message):
    """
    Send a log message (string type) to driver side, and driver will print
    log to stdout. If message length is greater than 4000, it will be
    truncated. (Contract: reference ``sparkdl/horovod/__init__.py:20-25``.)
    """
    if not isinstance(message, str):
        message = str(message)
    if len(message) > MAX_LOG_MESSAGE_LENGTH:
        message = message[:MAX_LOG_MESSAGE_LENGTH]
    from sparkdl_tpu.horovod.control_plane import get_worker_client

    client = get_worker_client()
    if client is not None:
        client.send_user_log(message)
    else:
        # Local mode: the current process IS the driver.
        print(message, flush=True)


__all__ = ["log_to_driver"]
