"""Worker-side utilities for HorovodRunner jobs.

Parity with reference ``sparkdl/horovod/__init__.py``, whose single
public symbol ``log_to_driver`` is a ``NotImplementedError`` stub
(reference ``sparkdl/horovod/__init__.py:20-25``). Here it is
implemented for real on top of the control plane
(:mod:`sparkdl_tpu.horovod.control_plane`): inside a HorovodRunner
worker the message travels over the worker→driver TCP channel and the
driver prints it to stdout; outside any job (e.g. local ``np=-1`` mode,
where driver == worker) it is printed directly.
"""

import collections
import json as _json
import os as _os

MAX_LOG_MESSAGE_LENGTH = 4000  # reference sparkdl/horovod/__init__.py:23


RestartContext = collections.namedtuple(
    "RestartContext",
    ["attempt", "resume_step", "source_axes", "target_axes"],
    defaults=[None, None],
)
_resume_instant_emitted = False  # one gang.resume marker per process
RestartContext.__doc__ = """The gang supervisor's restart context.

``attempt``: how many times this gang has been relaunched (0 on the
first launch — unmodified mains can ignore the context entirely).
``resume_step``: the latest :class:`~sparkdl_tpu.utils.checkpoint.
TrainCheckpointer` step committed under
``SPARKDL_TPU_GANG_RESUME_DIR`` when this attempt launched, or None
when no checkpoint exists (start from scratch).
``source_axes`` / ``target_axes``: on an elastic relaunch
(``SPARKDL_TPU_GANG_RELAUNCH_NP``), the mesh axis sizes the resume
checkpoint was laid out on and the axes ``shrink_mesh`` derived for
the new world — mains rebuild the surviving mesh from
``target_axes`` (e.g. via
:func:`sparkdl_tpu.parallel.mesh.make_mesh_from_axes`) and pass it to
``TrainCheckpointer.restore(..., target_mesh=...)``; both are None
outside an elastic relaunch. See ``docs/fault_tolerance.rst`` for the
resume contract."""


def _axes_env(name):
    raw = _os.environ.get(name)
    if not raw:
        return None
    try:
        doc = _json.loads(raw)
        return {str(k): int(v) for k, v in doc.items()}
    except (ValueError, TypeError, AttributeError):
        return None


def restart_context():
    """The supervisor's restart context for this worker process.

    Checkpoint-aware training mains resume where the previous attempt
    left off::

        ctx = restart_context()
        start = 0
        if ctx.resume_step is not None:
            state = ckpt.restore(ctx.resume_step, target=state)
            start = ctx.resume_step + 1
        for step in range(start, total_steps):
            ...

    Outside a supervised relaunch (first attempt, plain gangs, local
    ``np=-1`` mode) this returns ``RestartContext(0, None)``, so
    calling it unconditionally is always safe.
    """
    from sparkdl_tpu.horovod.supervisor import (
        RESHARD_SOURCE_AXES_ENV,
        RESHARD_TARGET_AXES_ENV,
        RESTART_ATTEMPT_ENV,
        RESUME_STEP_ENV,
    )

    global _resume_instant_emitted

    attempt = int(_os.environ.get(RESTART_ATTEMPT_ENV, "0"))
    step = _os.environ.get(RESUME_STEP_ENV)
    source_axes = _axes_env(RESHARD_SOURCE_AXES_ENV)
    target_axes = _axes_env(RESHARD_TARGET_AXES_ENV)
    if attempt > 0 and not _resume_instant_emitted:
        # The "resumed" beat of the gang timeline: a relaunched worker
        # reading its restart context is the moment recovery actually
        # happened (inert unless telemetry is on). Emitted ONCE per
        # process — mains may legitimately poll restart_context()
        # every step, and the story must stay one marker, not a wall.
        _resume_instant_emitted = True
        from sparkdl_tpu import observe

        observe.instant(
            "gang.resume", cat="supervisor", attempt=attempt,
            resume_step=int(step) if step is not None else None,
            source_axes=source_axes, target_axes=target_axes,
        )
    return RestartContext(
        attempt, int(step) if step is not None else None,
        source_axes, target_axes,
    )


def log_to_driver(message):
    """
    Send a log message (string type) to driver side, and driver will print
    log to stdout. If message length is greater than 4000, it will be
    truncated. (Contract: reference ``sparkdl/horovod/__init__.py:20-25``.)
    """
    if not isinstance(message, str):
        message = str(message)
    if len(message) > MAX_LOG_MESSAGE_LENGTH:
        message = message[:MAX_LOG_MESSAGE_LENGTH]
    from sparkdl_tpu.horovod.control_plane import get_worker_client

    client = get_worker_client()
    if client is not None:
        client.send_user_log(message)
    else:
        # Local mode: the current process IS the driver.
        print(message, flush=True)


__all__ = ["log_to_driver", "restart_context", "RestartContext"]
