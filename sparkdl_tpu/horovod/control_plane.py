"""Worker→driver control plane for HorovodRunner gangs.

The reference defers this entire subsystem to Databricks Runtime and only
fixes its observable behavior: a worker→driver string log channel with
4000-char truncation (reference ``sparkdl/horovod/__init__.py:20-25``),
a log routing policy keyed on ``driver_log_verbosity`` (reference
``runner_base.py:62-72``), and cloudpickled rank-0 return-value shipping
(reference ``runner_base.py:93-95``). This module implements that
control plane for real: a threaded TCP server on the driver and a
framed-message client in each worker.

Design notes (TPU-first): the *data plane* — gradients, parameters,
collectives — never touches this channel; it rides XLA collectives over
ICI/DCN inside jitted programs (see :mod:`sparkdl_tpu.hvd`). The control
plane only carries low-rate strings and the one-shot result blob, so a
simple length-prefixed TCP protocol is sufficient and keeps worker step
time unaffected (contract: "all" verbosity must not stall training,
reference ``runner_base.py:65-68`` — log sends here are fire-and-forget
writes to a socket buffer from the logging thread).

Frame format: ``u32 length | u8 type | u32 rank | payload`` (big endian).
JSON payloads for control messages; raw cloudpickle bytes for RESULT.
"""

import hashlib
import hmac
import json
import os
import secrets as _secrets
import socket
import struct
import threading
import time

# Message types
MSG_READY = 1
MSG_LOG = 2
MSG_USERLOG = 3
MSG_RESULT = 4
MSG_EXC = 5
MSG_BYE = 6
MSG_AUTH = 7
MSG_RESULT_PART = 8   # chunk of an oversized RESULT (rank 0 only)
MSG_RESULT_END = 9    # terminates a chunked RESULT
MSG_TELEMETRY = 10    # observe: batched metric snapshot + timeline events
MSG_HEARTBEAT = 11    # observe.health: per-rank liveness beacon
MSG_DUMP_REQ = 12     # driver→worker: send an all-thread stack dump
MSG_STACK_DUMP = 13   # worker→driver: the faulthandler dump text
MSG_PROFILE_REQ = 14  # driver→worker: capture a perf-forensics window
MSG_PROFILE_DONE = 15  # worker→driver: capture finished (report meta)

_HEADER = struct.Struct(">IBI")  # length (of type+rank+payload), type, rank

# Frame names for the chaos harness's drop/delay selectors
# (SPARKDL_TPU_CHAOS_CP_DROP names frames by these strings).
_MSG_NAMES = {
    MSG_READY: "READY", MSG_LOG: "LOG", MSG_USERLOG: "USERLOG",
    MSG_RESULT: "RESULT", MSG_EXC: "EXC", MSG_BYE: "BYE",
    MSG_AUTH: "AUTH", MSG_RESULT_PART: "RESULT", MSG_RESULT_END: "RESULT",
    MSG_TELEMETRY: "TELEMETRY", MSG_HEARTBEAT: "HEARTBEAT",
    MSG_STACK_DUMP: "STACK_DUMP", MSG_PROFILE_REQ: "PROFILE_REQ",
    MSG_PROFILE_DONE: "PROFILE_DONE",
}

CONTROL_ADDR_ENV = "SPARKDL_TPU_CONTROL_ADDR"
RANK_ENV = "SPARKDL_TPU_RANK"
CONTROL_SECRET_ENV = "SPARKDL_TPU_CONTROL_SECRET"

# The driver cloudpickle.loads() the RESULT payload, so an attacker who
# can deliver frames can execute code on the driver. Every connection
# must therefore open with an AUTH frame proving knowledge of the
# per-job secret (distributed to workers via the job env, never over
# the wire). A frame-length cap bounds allocation from untrusted peers.
# Threat model: peers WITHOUT the job secret. Gang workers hold the
# shared secret and are trusted — any of them could derive another
# rank's token; the per-connection rank pinning below catches bugs and
# misrouted frames, not a malicious worker.
MAX_FRAME = 64 << 20

# RESULTs bigger than one frame (e.g. returned model weights) ship as
# MSG_RESULT_PART chunks + MSG_RESULT_END, reassembled on the driver
# up to a separate (authenticated, rank-0-only) total cap.
RESULT_CHUNK = 32 << 20
MAX_RESULT_TOTAL = int(
    os.environ.get("SPARKDL_TPU_MAX_RESULT_BYTES", str(4 << 30))
)


def auth_token(secret, rank):
    """Per-rank connection credential: HMAC-SHA256 over the rank so the
    raw job secret never crosses the wire."""
    return hmac.new(
        secret.encode("utf-8"),
        b"sparkdl-tpu-auth-v1" + struct.pack(">I", rank),
        hashlib.sha256,
    ).digest()


def auth_frame(secret, rank):
    """The complete wire frame a client must send first on connect."""
    token = auth_token(secret, rank)
    return _HEADER.pack(len(token) + 5, MSG_AUTH, rank) + token


_AUTH_FRAME_LEN = len(auth_frame("", 0))  # fixed size: header + HMAC-SHA256

# Guard against a runaway worker flooding the driver (backpressure
# contract, reference runner_base.py:65-68): log text is truncated by
# the sender BEFORE JSON-encoding (truncating the encoded frame would
# produce invalid JSON and poison the connection).
MAX_LOG_TEXT = 64 << 10


def routable_host_ip():
    """Best-effort routable IP of this host (UDP-connect trick —
    ``gethostbyname(gethostname())`` resolves to 127.0.1.1 on stock
    Debian-style /etc/hosts, which would point remote workers at their
    own loopback)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))  # no packets sent
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class ControlPlaneServer:
    """Driver-side server: merges worker logs, routes them per the
    verbosity policy, and collects the rank-0 result.

    Log routing (reference ``runner_base.py:62-72``): every worker LOG
    line is merged into ``log_path`` (the analogue of "merged into the
    first executor's stderr"); with ``verbosity="all"`` each line is
    additionally streamed to the driver's stdout; with the default
    ``"log_callback_only"`` only USERLOG messages (sent via
    ``log_to_driver``) are printed.
    """

    def __init__(self, num_workers, verbosity="log_callback_only", log_path=None,
                 bind_host="127.0.0.1", advertise_host=None, secret=None,
                 telemetry=None, health=None):
        self.num_workers = num_workers
        self.verbosity = verbosity
        # Optional observability sink (sparkdl_tpu.observe.aggregate.
        # GangTelemetry): TELEMETRY frames are decoded and handed to
        # it; without one they are dropped (telemetry is opt-in).
        self._telemetry = telemetry
        # Optional hang detector (sparkdl_tpu.observe.health.
        # HangDetector): HEARTBEAT frames feed it; without one they
        # are dropped (health is part of the same telemetry opt-in).
        self._health = health
        # rank -> the connection carrying that rank's GUARANTEED
        # control socket (recorded on READY/HEARTBEAT — the native log
        # sender's extra connections only ever carry LOG and have no
        # reader on the worker side, so a driver→worker dump request
        # must ride the main socket the watchdog reads).
        self._conns = {}
        self._stack_dumps = {}  # rank -> [dump text, ...]
        self._profile_reports = {}  # rank -> [report meta dict, ...]
        # Optional observer for PROFILE_DONE frames (the forensics
        # manager clears its in-flight latch here); called OUTSIDE the
        # server lock with (rank, report_meta_dict).
        self.on_profile_done = None
        # Per-job shared secret; the launcher ships it to workers via
        # CONTROL_SECRET_ENV. Auto-generated so no caller can forget it.
        self.secret = secret or _secrets.token_hex(32)
        self.log_path = log_path
        self._log_file = open(log_path, "a", buffering=1) if log_path else None
        self._lock = threading.Lock()
        self._ready = set()
        self._done = set()
        self._result = None
        self._result_rank = None
        self._result_parts = []
        self._result_parts_bytes = 0
        self._result_overflow = False
        self._exceptions = {}  # rank -> traceback string
        self._exit_codes = {}
        self._ready_cond = threading.Condition(self._lock)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind_host, 0))
        self._srv.listen(max(num_workers, 8))
        port = self._srv.getsockname()[1]
        if advertise_host is None:
            # When bound to all interfaces (cluster mode), advertise a
            # routable address — loopback would point remote workers at
            # themselves.
            advertise_host = (
                routable_host_ip() if bind_host == "0.0.0.0" else bind_host
            )
        self.address = f"{advertise_host}:{port}"
        self._closed = False
        self._threads = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="sparkdl-tpu-control-accept", daemon=True
        )
        self._accept_thread.start()

    # -- server internals ---------------------------------------------------

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="sparkdl-tpu-control-conn", daemon=True,
            )
            t.start()
            # _threads is read by wait_drained() from the driver
            # thread while this accept thread appends: share it under
            # the lock, and prune finished handlers so a chatty gang
            # (reconnects, per-attempt clients) cannot grow the list
            # for the life of the server.
            with self._lock:
                self._threads = [
                    x for x in self._threads if x.is_alive()
                ]
                self._threads.append(t)

    def _log_server_event(self, text):
        with self._lock:
            if self._log_file is not None:
                self._log_file.write(f"[control-plane] {text}\n")

    def _serve_conn(self, conn):
        auth_rank = None  # rank proven by the AUTH handshake
        auth_len = _AUTH_FRAME_LEN - _HEADER.size
        try:
            while True:
                head = _recv_exact(conn, _HEADER.size)
                if head is None:
                    return
                length, mtype, rank = _HEADER.unpack(head)
                if auth_rank is None and length - 5 != auth_len:
                    # Pre-auth, the ONLY legal frame is the fixed-size
                    # AUTH frame — an unauthenticated peer must not be
                    # able to make us buffer anything bigger.
                    self._log_server_event(
                        f"pre-auth frame with length {length}; closing"
                    )
                    return
                if length < 5 or length - 5 > MAX_FRAME:
                    # Bounded allocation from untrusted peers: drop the
                    # connection rather than trust a u32 length.
                    self._log_server_event(
                        f"oversized frame ({length} bytes) from rank "
                        f"{rank}; closing connection"
                    )
                    return
                payload = _recv_exact(conn, length - 5)
                if payload is None:
                    return
                if auth_rank is None:
                    # First frame MUST be a valid AUTH; anything else —
                    # including a bad token — closes the connection
                    # before a single byte reaches the handlers.
                    if mtype != MSG_AUTH or not hmac.compare_digest(
                        payload, auth_token(self.secret, rank)
                    ):
                        self._log_server_event(
                            f"unauthenticated connection (first frame "
                            f"type {mtype}, claimed rank {rank}); closing"
                        )
                        return
                    auth_rank = rank
                    continue
                if mtype == MSG_AUTH:
                    continue  # re-auth is a no-op
                if rank != auth_rank:
                    # The per-rank HMAC binds the connection to ONE
                    # rank; a frame claiming another rank is a protocol
                    # violation (a bug or misrouted frame — see the
                    # threat-model note on MAX_FRAME: this does not
                    # defend against a malicious secret-holding worker).
                    self._log_server_event(
                        f"rank-{auth_rank} connection sent a frame "
                        f"claiming rank {rank}; closing"
                    )
                    return
                if mtype in (MSG_READY, MSG_HEARTBEAT):
                    # This connection is the rank's guaranteed control
                    # socket (its worker runs the watchdog reader on
                    # it) — the channel driver→worker dump requests
                    # ride. Native log connections never send these.
                    with self._lock:
                        self._conns[rank] = conn
                try:
                    self._handle(mtype, rank, payload)
                except Exception:
                    # A malformed frame must not kill the connection —
                    # READY/RESULT/BYE from this rank still need to
                    # arrive. Log and keep serving.
                    import traceback

                    with self._lock:
                        if self._log_file is not None:
                            self._log_file.write(
                                f"[control-plane] bad frame from rank {rank}:\n"
                                f"{traceback.format_exc()}\n"
                            )
        except OSError:
            pass
        finally:
            conn.close()

    def _handle(self, mtype, rank, payload):
        if mtype == MSG_READY:
            with self._ready_cond:
                self._ready.add(rank)
                self._ready_cond.notify_all()
        elif mtype == MSG_LOG:
            msg = json.loads(payload.decode("utf-8", "replace"))
            line = msg.get("text", "")
            stream = msg.get("stream", "stdout")
            with self._lock:
                if self._log_file is not None:
                    self._log_file.write(f"[rank {rank} {stream}] {line}\n")
            if self.verbosity == "all":
                print(f"[{rank}] {line}", flush=True)
        elif mtype == MSG_USERLOG:
            msg = json.loads(payload.decode("utf-8", "replace"))
            # log_to_driver contract: driver prints to stdout
            # (reference sparkdl/horovod/__init__.py:20-25).
            print(msg.get("text", ""), flush=True)
            with self._lock:
                if self._log_file is not None:
                    self._log_file.write(f"[rank {rank} log_to_driver] {msg.get('text', '')}\n")
        elif mtype in (MSG_RESULT, MSG_RESULT_PART, MSG_RESULT_END):
            if rank != 0:
                # The contract returns rank 0's value only (reference
                # runner_base.py:93-95); a RESULT from any other rank is
                # a protocol violation, not data.
                self._log_server_event(
                    f"ignoring RESULT from rank {rank} (only rank 0 may "
                    "return the job value)"
                )
                return
            if mtype == MSG_RESULT:
                with self._lock:
                    self._result = payload
                    self._result_rank = rank
            elif mtype == MSG_RESULT_PART:
                with self._lock:
                    if self._result_overflow:
                        return
                    self._result_parts.append(payload)
                    self._result_parts_bytes += len(payload)
                    if self._result_parts_bytes > MAX_RESULT_TOTAL:
                        # Bound driver memory even for the trusted path;
                        # the job then surfaces "no result" with this
                        # line in the job log explaining why.
                        self._result_overflow = True
                        self._result_parts = []
                        self._result_parts_bytes = 0
                if self._result_overflow:
                    self._log_server_event(
                        "chunked RESULT exceeded "
                        f"{MAX_RESULT_TOTAL} bytes; discarded (raise "
                        "SPARKDL_TPU_MAX_RESULT_BYTES if the return "
                        "value is legitimately this large)"
                    )
            else:  # MSG_RESULT_END
                with self._lock:
                    if not self._result_overflow:
                        self._result = b"".join(self._result_parts)
                        self._result_rank = rank
                    self._result_parts = []
                    self._result_parts_bytes = 0
        elif mtype == MSG_TELEMETRY:
            if self._telemetry is not None:
                # ingest() shape-checks and raises on malformed frames;
                # the per-frame handler above logs and keeps serving,
                # so bad telemetry can never poison READY/RESULT/BYE.
                self._telemetry.ingest(
                    rank, json.loads(payload.decode("utf-8", "replace"))
                )
        elif mtype == MSG_HEARTBEAT:
            if self._health is not None:
                self._health.observe_beat(
                    rank, json.loads(payload.decode("utf-8", "replace"))
                )
        elif mtype == MSG_STACK_DUMP:
            msg = json.loads(payload.decode("utf-8", "replace"))
            dump = str(msg.get("dump", ""))
            with self._lock:
                self._stack_dumps.setdefault(rank, []).append(dump)
                if self._log_file is not None:
                    self._log_file.write(
                        f"[rank {rank} STACK DUMP "
                        f"({msg.get('reason', 'requested')})]\n{dump}\n"
                    )
            if self._telemetry is not None:
                self._telemetry.add_stack_dump(
                    rank, dump, reason=msg.get("reason")
                )
            if self._health is not None:
                self._health.note_stack_dump(rank)
        elif mtype == MSG_PROFILE_DONE:
            msg = json.loads(payload.decode("utf-8", "replace"))
            if not isinstance(msg, dict):
                msg = {}
            with self._lock:
                self._profile_reports.setdefault(rank, []).append(msg)
                if self._log_file is not None:
                    self._log_file.write(
                        f"[rank {rank} PROFILE DONE "
                        f"({msg.get('reason', 'requested')}) "
                        f"{msg.get('report') or ''}]\n"
                    )
            cb = self.on_profile_done
            if cb is not None:
                # outside the lock: the forensics manager takes its own
                cb(rank, msg)
        elif mtype == MSG_EXC:
            msg = json.loads(payload.decode("utf-8", "replace"))
            with self._lock:
                self._exceptions[rank] = msg.get("traceback", "")
                if self._log_file is not None:
                    self._log_file.write(f"[rank {rank} EXCEPTION]\n{msg.get('traceback', '')}\n")
        elif mtype == MSG_BYE:
            msg = json.loads(payload.decode("utf-8", "replace"))
            with self._ready_cond:
                self._done.add(rank)
                self._exit_codes[rank] = msg.get("exit_code", 0)
                self._ready_cond.notify_all()

    # -- driver-facing API --------------------------------------------------

    def wait_ready(self, timeout):
        """Gang barrier: wait until all workers report READY.

        Fail-fast semantics per the contract "np tasks starting all
        together" / fail if slots unavailable (reference
        ``runner_base.py:54-58``): returns False on timeout.
        """
        deadline = time.monotonic() + timeout
        with self._ready_cond:
            while len(self._ready) < self.num_workers:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._ready_cond.wait(remaining)
        return True

    def wait_drained(self, timeout=5.0):
        """Join connection handlers so every frame already on the wire
        is processed. Workers' sockets hit EOF when their processes
        exit, and TCP delivers all buffered bytes before EOF — so once
        the handler threads finish, no log line can arrive late (the
        tail-of-job guarantee behind the 'all'-verbosity contract)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads)
        # join OUTSIDE the lock: handlers take it to record results,
        # and a join-under-lock would deadlock the drain.
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def request_dump(self, rank, reason="stall"):
        """Ask ``rank`` for an all-thread stack dump (hang/straggler
        diagnosis). Sent down the rank's guaranteed control socket,
        where the worker's driver-watchdog reader answers with a
        ``STACK_DUMP`` frame. Returns False when the rank has no
        registered connection (never beat/READY'd) or the send fails —
        a diagnosis request must never raise into the monitor loop."""
        with self._lock:
            conn = self._conns.get(rank)
        if conn is None:
            return False
        payload = json.dumps({"reason": reason}).encode("utf-8")
        frame = _HEADER.pack(len(payload) + 5, MSG_DUMP_REQ, rank) + payload
        try:
            conn.sendall(frame)
        except OSError:
            return False
        return True

    def request_profile(self, rank, reason="alert", rule=None,
                        steps=None):
        """Ask ``rank`` to capture a perf-forensics evidence window
        (xprof trace + uncapped attribution rows + memory snapshot)
        into its job dir. Same transport contract as
        :meth:`request_dump`: the guaranteed control socket, where the
        worker's framed watchdog dispatches it to the registered
        capture service. Returns False (never raises) when the rank
        has no registered connection or the send fails."""
        with self._lock:
            conn = self._conns.get(rank)
        if conn is None:
            return False
        req = {"reason": reason}
        if rule is not None:
            req["rule"] = rule
        if steps is not None:
            req["steps"] = int(steps)
        payload = json.dumps(req).encode("utf-8")
        frame = _HEADER.pack(
            len(payload) + 5, MSG_PROFILE_REQ, rank) + payload
        try:
            conn.sendall(frame)
        except OSError:
            return False
        return True

    def profile_reports(self, rank=None):
        """PROFILE_DONE report metadata: ``{rank: [meta, ...]}``, or
        the list for one rank."""
        with self._lock:
            if rank is not None:
                return list(self._profile_reports.get(rank, ()))
            return {r: list(d)
                    for r, d in self._profile_reports.items()}

    def stack_dumps(self, rank=None):
        """Collected stack-dump texts: ``{rank: [dump, ...]}``, or the
        list for one rank."""
        with self._lock:
            if rank is not None:
                return list(self._stack_dumps.get(rank, ()))
            return {r: list(d) for r, d in self._stack_dumps.items()}

    def ready_count(self):
        with self._lock:
            return len(self._ready)

    def done_count(self):
        with self._lock:
            return len(self._done)

    @property
    def exceptions(self):
        with self._lock:
            return dict(self._exceptions)

    @property
    def result_bytes(self):
        with self._lock:
            return self._result

    def close(self):
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            if self._log_file is not None:
                self._log_file.close()
                self._log_file = None


class ControlPlaneClient:
    """Worker-side client for the driver control plane.

    Control messages (READY/RESULT/EXC/BYE) go over a blocking Python
    socket — they must arrive. Log traffic (LOG/USERLOG) prefers the
    native C++ transport (:mod:`sparkdl_tpu.native`): a bounded
    drop-oldest ring drained off-thread, so log volume can never stall
    the training thread (reference ``runner_base.py:65-68``). Set
    ``SPARKDL_TPU_NATIVE_LOGS=0`` to force the Python path.
    """

    def __init__(self, address, rank, secret=None):
        host, port = address.rsplit(":", 1)
        self.rank = rank
        secret = secret or os.environ.get(CONTROL_SECRET_ENV)
        if not secret:
            raise RuntimeError(
                "control-plane client needs the per-job secret "
                f"({CONTROL_SECRET_ENV} unset): refusing to open an "
                "unauthenticated channel to the driver"
            )
        self._auth = auth_frame(secret, rank)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._sock.settimeout(None)
        self._sock.sendall(self._auth)
        # Detect a dead driver HOST too (power-off/partition sends no
        # FIN): aggressive TCP keepalive makes the watchdog's recv fail
        # within ~1 minute instead of blocking forever.
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPIDLE, 30)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPINTVL, 10)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_KEEPCNT, 3)
        except (OSError, AttributeError):
            pass  # non-Linux: keepalive is best-effort
        self._lock = threading.Lock()
        self._closing = False
        # Perf-forensics capture hook (sparkdl_tpu.observe.capture):
        # None unless a capture service registered — the watchdog's
        # PROFILE_REQ dispatch is inert with telemetry off (the
        # zero-overhead latch extends to forensics).
        self._profile_handler = None
        self._native = None
        if os.environ.get("SPARKDL_TPU_NATIVE_LOGS", "1") != "0":
            try:
                from sparkdl_tpu.native import NativeLogSender

                # The native sender opens its own TCP connection, so it
                # carries the same auth preamble on every (re)connect.
                self._native = NativeLogSender(
                    host, int(port), rank, preamble=self._auth
                )
            except (RuntimeError, OSError):
                self._native = None

    def _send(self, mtype, payload):
        # Fault-injection hook (inert without SPARKDL_TPU_CHAOS_* env):
        # the chaos harness can delay or drop control frames to
        # simulate a flaky control plane — a dropped READY stalls the
        # gang barrier, a dropped RESULT exercises the lost-result
        # path. The native log ring is not hooked (logs are droppable
        # by design).
        from sparkdl_tpu.utils.chaos import control_frame_fate

        fate = control_frame_fate(_MSG_NAMES.get(mtype, str(mtype)))
        if fate == "drop":
            return
        if fate:
            time.sleep(fate)
        frame = _HEADER.pack(len(payload) + 5, mtype, self.rank) + payload
        with self._lock:
            try:
                self._sock.sendall(frame)
            except OSError:
                pass  # driver went away; worker will be reaped by the launcher

    def _send_json(self, mtype, obj):
        self._send(mtype, json.dumps(obj).encode("utf-8"))

    def send_ready(self):
        self._send(MSG_READY, b"")

    def send_log(self, stream, text):
        # High-volume tee'd stdout/stderr rides the native drop-oldest
        # ring (never blocks training).
        payload = json.dumps(
            {"stream": stream, "text": text[:MAX_LOG_TEXT]}
        ).encode("utf-8")
        native = self._native
        if native is not None:
            native.send(MSG_LOG, payload)
        else:
            self._send(MSG_LOG, payload)

    def send_user_log(self, text):
        # log_to_driver is low-rate and EXPLICIT — it takes the
        # guaranteed control socket, never the droppable ring
        # (reference contract: the driver prints it,
        # sparkdl/horovod/__init__.py:20-25).
        self._send_json(MSG_USERLOG, {"text": text[:MAX_LOG_TEXT]})

    def send_telemetry(self, payload_obj):
        # Observability flushes (sparkdl_tpu.observe): low-rate batched
        # snapshots, so they take the guaranteed control socket like
        # log_to_driver — never the droppable native ring (a lost
        # final flush would hide exactly the events a postmortem
        # needs). Backpressure contract unchanged: the flusher batches
        # on an interval, so volume stays bounded regardless of how
        # hot the instrumented paths run.
        self._send_json(MSG_TELEMETRY, payload_obj)

    def send_heartbeat(self, payload_obj):
        # Gang-health beacon (sparkdl_tpu.observe.health): tiny JSON at
        # SPARKDL_TPU_HEARTBEAT_S rate on the guaranteed control
        # socket — the whole point is that it keeps flowing while the
        # training thread is wedged, so it must never ride the
        # droppable native ring.
        self._send_json(MSG_HEARTBEAT, payload_obj)

    def send_result(self, pickled_bytes):
        # One frame when it fits; otherwise chunk under the frame cap
        # (large returned values — e.g. model weights — are legitimate,
        # reference runner_base.py:93-95 puts no size bound on them).
        if len(pickled_bytes) <= RESULT_CHUNK:
            self._send(MSG_RESULT, pickled_bytes)
            return
        view = memoryview(pickled_bytes)
        for off in range(0, len(view), RESULT_CHUNK):
            self._send(MSG_RESULT_PART, bytes(view[off:off + RESULT_CHUNK]))
        self._send(MSG_RESULT_END, b"")

    def send_exception(self, tb_text):
        # Tracebacks can embed huge reprs; keep the tail (the raise site).
        if len(tb_text) > 4 * MAX_LOG_TEXT:
            tb_text = "...[truncated]...\n" + tb_text[-4 * MAX_LOG_TEXT:]
        self._send_json(MSG_EXC, {"traceback": tb_text})

    def send_bye(self, exit_code):
        # Drain buffered logs before announcing exit so the job log is
        # complete for clean shutdowns (drops only happen under flood).
        if self._native is not None:
            self._native.flush(timeout_ms=5000)
        self._send_json(MSG_BYE, {"exit_code": exit_code})

    def _answer_dump_request(self, payload):
        """Ship a faulthandler all-thread stack dump back to the
        driver. Runs on the WATCHDOG thread — which is exactly why it
        works: the training thread may be wedged in a collective or a
        host callback, and faulthandler reads every thread's frames
        without needing any of them to cooperate."""
        try:
            reason = json.loads(payload.decode("utf-8", "replace")).get(
                "reason", "requested")
        except ValueError:
            reason = "requested"
        from sparkdl_tpu.observe.health import dump_all_threads

        try:
            dump = dump_all_threads()
        except Exception:
            import traceback

            dump = ("<faulthandler dump failed>\n"
                    + traceback.format_exc())
        self._send_json(MSG_STACK_DUMP, {"reason": reason, "dump": dump})

    def set_profile_handler(self, handler):
        """Register the worker-side capture service's entry point for
        driver ``PROFILE_REQ`` frames (``handler(request_dict)``,
        called on the watchdog thread — it must delegate the capture
        itself to its own thread, a capture spans many steps of wall
        time and the watchdog is the driver-death detector). ``None``
        unregisters."""
        self._profile_handler = handler

    def send_profile_done(self, report_meta):
        """Answer a ``PROFILE_REQ``: JSON metadata about the finished
        (or failed) capture — report filename, trace dir, reason/rule,
        error. Rides the guaranteed control socket like
        ``STACK_DUMP``."""
        self._send_json(MSG_PROFILE_DONE, report_meta)

    def _dispatch_profile_request(self, payload):
        """Hand one PROFILE_REQ to the registered capture service;
        without one (telemetry off, or no service started) the frame
        is dropped — never an error, never any work."""
        handler = self._profile_handler
        if handler is None:
            return
        try:
            req = json.loads(payload.decode("utf-8", "replace"))
        except ValueError:
            req = {}
        if not isinstance(req, dict):
            req = {}
        try:
            handler(req)
        except Exception:
            # the watchdog must keep watching no matter what the
            # capture service does
            pass

    def start_driver_watchdog(self, grace_seconds=10.0):
        """Exit this worker when the driver disappears; answer its
        hang-diagnosis requests meanwhile.

        The only driver→worker traffic is the occasional framed
        ``DUMP_REQ`` (the hang detector asking a stalled rank for its
        stacks), so the watchdog reads frames: a complete frame is
        dispatched, EOF/reset means the driver process died (including
        SIGKILL, which the launcher's reaper can't mitigate). Orphaned
        workers would otherwise run forever, holding devices and
        distributed-runtime leases (observed: a killed driver left
        gang workers pinning the TPU claim).
        """

        def watch():
            while True:
                try:
                    head = _recv_exact(self._sock, _HEADER.size)
                    if head is not None:
                        length, mtype, _rank = _HEADER.unpack(head)
                        if 5 <= length and length - 5 <= MAX_FRAME:
                            payload = _recv_exact(self._sock, length - 5)
                            if payload is not None:
                                if mtype == MSG_DUMP_REQ:
                                    self._answer_dump_request(payload)
                                elif mtype == MSG_PROFILE_REQ:
                                    self._dispatch_profile_request(
                                        payload)
                                continue  # keep watching
                        # unframeable driver bytes: treat like a reset
                    head = None
                except OSError:
                    head = None
                if head is None:
                    break
            if self._closing:
                # Our own close() raced the recv — normal teardown of a
                # finished worker, NOT a dead driver.
                return
            import sys
            import time

            sys.stderr.write(
                "sparkdl-tpu worker: driver connection lost; exiting "
                f"in {grace_seconds:.0f}s\n"
            )
            sys.stderr.flush()
            time.sleep(grace_seconds)
            if not self._closing:
                os._exit(83)

        t = threading.Thread(
            target=watch, name="sparkdl-tpu-driver-watchdog", daemon=True
        )
        t.start()

    def close(self):
        # Mark BEFORE closing the socket: the driver watchdog must read
        # this as voluntary teardown, not driver death.
        self._closing = True
        # Detach first so racing send_log calls see None (and the
        # sender's own lock makes a send that already grabbed the
        # reference safe against the close).
        native, self._native = self._native, None
        if native is not None:
            native.close()
        try:
            self._sock.close()
        except OSError:
            pass


_worker_client = None
_worker_client_lock = threading.Lock()


def get_worker_client():
    """Return the process-wide control-plane client, or None when this
    process is not a HorovodRunner worker (then driver == worker and
    ``log_to_driver`` prints directly)."""
    global _worker_client
    with _worker_client_lock:
        if _worker_client is None:
            addr = os.environ.get(CONTROL_ADDR_ENV)
            if not addr:
                return None
            rank = int(os.environ.get(RANK_ENV, "0"))
            _worker_client = ControlPlaneClient(addr, rank)
        return _worker_client
