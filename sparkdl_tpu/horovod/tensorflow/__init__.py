# TensorFlow integration namespace for HorovodRunner jobs.
# Parity with reference sparkdl/horovod/tensorflow/__init__.py (an empty
# namespace package).
