"""Keras LogCallback: streams training progress to the driver notebook.

Real implementation of the reference stub
``sparkdl/horovod/tensorflow/keras.py:16-34`` (all of whose methods
raise NotImplementedError): a ``keras.callbacks.Callback`` whose
epoch/batch hooks format compact progress lines and ship them over the
worker→driver channel (:func:`sparkdl_tpu.horovod.log_to_driver`), which
is the only log path that surfaces under the default
``driver_log_verbosity="log_callback_only"`` policy (reference
``runner_base.py:68-72``).
"""

import time

from tensorflow import keras

from sparkdl_tpu.horovod import log_to_driver

__all__ = ["LogCallback"]


def _fmt_logs(logs):
    if not logs:
        return ""
    return " - ".join(
        f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
        for k, v in logs.items()
    )


class LogCallback(keras.callbacks.Callback):
    """
    A simple HorovodRunner log callback that streams event logs to
    notebook cell output. (Contract: reference ``keras.py:16-25``.)
    """

    def __init__(self, per_batch_log=False):
        """
        :param per_batch_log: whether to output logs per batch, default: False.
        """
        super().__init__()
        self.per_batch_log = per_batch_log
        self._epoch_start = None
        self._epoch = None

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._epoch_start = time.time()
        log_to_driver(f"Epoch {epoch} begin at {time.strftime('%Y-%m-%d %H:%M:%S')}")

    def on_batch_end(self, batch, logs=None):
        if self.per_batch_log:
            msg = _fmt_logs(logs)
            log_to_driver(f"Epoch {self._epoch} batch {batch}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        dt = time.time() - (self._epoch_start or time.time())
        msg = _fmt_logs(logs)
        log_to_driver(f"Epoch {epoch} end ({dt:.1f}s): {msg}")
