"""Keras LogCallback: streams training progress to the driver notebook.

Real implementation of the reference stub
``sparkdl/horovod/tensorflow/keras.py:16-34`` (all of whose methods
raise NotImplementedError): a ``keras.callbacks.Callback`` whose
epoch/batch hooks format compact progress lines and ship them over the
worker→driver channel (:func:`sparkdl_tpu.horovod.log_to_driver`), which
is the only log path that surfaces under the default
``driver_log_verbosity="log_callback_only"`` policy (reference
``runner_base.py:68-72``).
"""

import time

from tensorflow import keras

from sparkdl_tpu import observe
from sparkdl_tpu.horovod import log_to_driver

__all__ = ["LogCallback"]


def _fmt_logs(logs):
    if not logs:
        return ""
    return " - ".join(
        f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
        for k, v in logs.items()
    )


def _numeric_logs(logs):
    out = {}
    for k, v in (logs or {}).items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue  # non-scalar entries stay log-line-only
    return out


def _emit(scope, logs, **extra):
    """Mirror Keras progress into the observe layer: each numeric log
    value becomes a ``keras_<metric>`` gauge (labeled with the hook
    that produced it) so epoch/batch loss is scrapeable gang-wide, not
    just readable in the notebook. The log LINES are untouched — this
    rides next to ``log_to_driver``, never replaces it — the whole
    emit is a no-op when telemetry is off, and any emit failure is
    swallowed: metric NAMES here come from user code (a model metric
    could collide with a registry name of another kind), and telemetry
    must never take down the training it observes."""
    if not observe.enabled():
        return
    for k, v in _numeric_logs(logs).items():
        # Guard PER metric: one colliding name (user metric vs an
        # already-registered kind) must cost one series, not silence
        # every metric that iterates after it.
        try:
            observe.set_gauge(f"keras_{k}", v, scope=scope)
            observe.inc("keras_metric_updates_total", scope=scope)
        except Exception:
            continue


class LogCallback(keras.callbacks.Callback):
    """
    A simple HorovodRunner log callback that streams event logs to
    notebook cell output. (Contract: reference ``keras.py:16-25``.)
    """

    def __init__(self, per_batch_log=False):
        """
        :param per_batch_log: whether to output logs per batch, default: False.
        """
        super().__init__()
        self.per_batch_log = per_batch_log
        self._epoch_start = None
        self._epoch = None

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._epoch_start = time.time()
        log_to_driver(f"Epoch {epoch} begin at {time.strftime('%Y-%m-%d %H:%M:%S')}")
        observe.instant("keras.epoch_begin", cat="keras", epoch=epoch)

    def on_batch_end(self, batch, logs=None):
        if self.per_batch_log:
            msg = _fmt_logs(logs)
            log_to_driver(f"Epoch {self._epoch} batch {batch}: {msg}")
        # Batch metrics flow to observe regardless of per_batch_log:
        # the log-line knob exists because lines are noisy, but gauges
        # overwrite in place — scrape cost is constant.
        _emit("batch", logs)

    def on_epoch_end(self, epoch, logs=None):
        dt = time.time() - (self._epoch_start or time.time())
        msg = _fmt_logs(logs)
        log_to_driver(f"Epoch {epoch} end ({dt:.1f}s): {msg}")
        _emit("epoch", logs)
        if observe.enabled():
            try:
                observe.observe_value("keras_epoch_seconds", dt)
                # User metric names ride NESTED under "metrics": a
                # metric literally named "epoch" or "seconds" must not
                # collide with the instant's own keywords.
                observe.instant("keras.epoch_end", cat="keras",
                                epoch=epoch, seconds=round(dt, 3),
                                metrics=_numeric_logs(logs))
            except Exception:
                pass
