"""Autonomous elasticity: capacity-watching grow-back and train/serve
chip yield/reclaim on one pod (ISSUE 16).

PR 15 proved the *mechanism* — kill → relaunch at a smaller np →
resharded restore — but the grow leg stayed an operator action
(export ``SPARKDL_TPU_GANG_RELAUNCH_NP``, start a fresh run). This
module closes the loop so the runner, not the user, owns the cluster
lifecycle:

1. **Capacity watcher** (:func:`probe_capacity`): a pluggable probe of
   how many chips the pod can offer right now — an env override for
   tests and chaos (``SPARKDL_TPU_ELASTIC_CAPACITY``), a re-read-every-
   poll file override (``..._CAPACITY_FILE`` — chaos flips it mid-run),
   the ``/dev/accel*`` device count on TPU hosts, or the launcher's
   local slot table. ``auto`` picks the first configured source; a
   configured-but-unreadable override reports *unknown* rather than
   falling through to a fantasy number.
2. **Debounced grow-back**: a capacity surplus must hold for
   ``SPARKDL_TPU_ELASTIC_DEBOUNCE_S`` before the controller even
   considers growing — a flapping probe (chips blinking in and out
   during a pod repair) must never thrash the gang shrink↔grow.
3. **Ledger-driven np selection** (:func:`choose_np`): the target np
   comes from ``history.jsonl`` throughput-per-chip medians (the same
   ``observe.compare`` median discipline the perf gate uses), so the
   gang never grows into a configuration the ledger proves slower per
   chip. An unprofitable or infeasible grow raises the typed
   :class:`ElasticGrowRefused` — the same refuse-don't-crash posture as
   the reshard pre-flight.
4. **Checkpoint-boundary resize**: a planned resize is emitted only
   after the newest committed :class:`TrainCheckpointer` step advances
   past the decision point (bounded by ``..._CKPT_WAIT_S``), so the
   relaunch resumes from a step the resized gang has actually
   persisted. The relaunch itself rides the proven PR 15 path —
   reshard pre-flight, source/target axes in the restart context,
   resharded restore, warm compile cache.
5. **Chip-budget arbiter** (``SPARKDL_TPU_ELASTIC_ARBITER``): when the
   alert engine's serving-pressure rules (``queue_depth_growth``, the
   ``server_ttft`` p99 rule) fire on a colocated fleet, training
   *yields* chips — the gang shrinks through the same elastic path and
   the fleet scales up — and *reclaims* them when the demand signal
   stays quiet for ``..._ARBITER_CLEAR_S``. Every grow/yield/reclaim
   decision lands as a typed timeline instant, a
   ``gang_elastic_transitions_total{direction,reason}`` counter, and a
   line in the run dir's ``elastic.json`` decision log.

Zero-overhead contract: nothing here runs unless ``SPARKDL_TPU_ELASTIC``
is truthy — :func:`maybe_make_controller` returns None and the
launcher's monitor loop pays one ``is not None`` test per tick.
"""

import glob
import logging
import os
import threading
import time

from sparkdl_tpu import observe

logger = logging.getLogger("HorovodRunner")

ELASTIC_ENV = "SPARKDL_TPU_ELASTIC"
PROBE_ENV = "SPARKDL_TPU_ELASTIC_PROBE"
CAPACITY_ENV = "SPARKDL_TPU_ELASTIC_CAPACITY"
CAPACITY_FILE_ENV = "SPARKDL_TPU_ELASTIC_CAPACITY_FILE"
CHECK_S_ENV = "SPARKDL_TPU_ELASTIC_CHECK_S"
DEBOUNCE_S_ENV = "SPARKDL_TPU_ELASTIC_DEBOUNCE_S"
MARGIN_ENV = "SPARKDL_TPU_ELASTIC_MARGIN"
CKPT_WAIT_S_ENV = "SPARKDL_TPU_ELASTIC_CKPT_WAIT_S"
MAX_NP_ENV = "SPARKDL_TPU_ELASTIC_MAX_NP"
MIN_NP_ENV = "SPARKDL_TPU_ELASTIC_MIN_NP"
ARBITER_ENV = "SPARKDL_TPU_ELASTIC_ARBITER"
ARBITER_RULES_ENV = "SPARKDL_TPU_ELASTIC_ARBITER_RULES"
ARBITER_CHIPS_ENV = "SPARKDL_TPU_ELASTIC_ARBITER_CHIPS"
ARBITER_CLEAR_S_ENV = "SPARKDL_TPU_ELASTIC_ARBITER_CLEAR_S"
# Same literal as supervisor.RESUME_DIR_ENV (kept as a plain string so
# import order between the two modules stays free).
RESUME_DIR_ENV = "SPARKDL_TPU_GANG_RESUME_DIR"

DEVICE_GLOB = "/dev/accel*"
ELASTIC_SCHEMA = "sparkdl_tpu.horovod.elastic/1"

# Ledger metric names accepted as throughput (higher = better), in
# preference order, then step-time names inverted to a rate.
_RATE_METRICS = ("steps_per_s", "examples_per_s", "tokens_per_s",
                 "throughput")
_STEP_TIME_METRICS = ("step_time_s", "train_step_seconds_mean")
# Top-level ledger-record keys naming the world size the record was
# measured at (history_record(..., extra={"np": N}) merges top-level).
_NP_KEYS = ("np", "world", "world_size", "num_workers")


def _truthy(raw):
    return (raw or "").strip().lower() not in ("", "0", "false", "off")


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return None
    mid = n // 2
    if n % 2:
        return xs[mid]
    return 0.5 * (xs[mid - 1] + xs[mid])


def probe_capacity(env=None):
    """How many chips the pod can offer right now, or None (unknown).

    Probe order under ``SPARKDL_TPU_ELASTIC_PROBE=auto`` (default):
    the ``..._CAPACITY`` env int if set, else the ``..._CAPACITY_FILE``
    contents if a path is configured (re-read every call — chaos and
    tests flip it mid-run), else the ``/dev/accel*`` device count when
    any exist, else the launcher's local slot table. A configured
    override that fails to parse reports None — *unknown*, never a
    fallthrough to a different source's fantasy number.
    """
    env = os.environ if env is None else env
    mode = (env.get(PROBE_ENV) or "auto").strip().lower()

    if mode in ("env", "auto"):
        raw = env.get(CAPACITY_ENV)
        if raw is not None and raw.strip():
            try:
                return int(raw)
            except ValueError:
                logger.warning("ignoring unparsable %s=%r",
                               CAPACITY_ENV, raw)
                return None
        if mode == "env":
            return None

    if mode in ("file", "auto"):
        path = env.get(CAPACITY_FILE_ENV)
        if path:
            try:
                with open(path) as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                return None
        if mode == "file":
            return None

    if mode in ("devices", "auto"):
        n = len(glob.glob(DEVICE_GLOB))
        if n or mode == "devices":
            return n or None

    if mode in ("slots", "auto"):
        try:
            from sparkdl_tpu.horovod.launcher import available_slots

            return available_slots()
        except Exception:
            return None

    return None


class ElasticGrowRefused(RuntimeError):
    """A grow was refused — infeasible (``reason="no_checkpoint"``: no
    committed step to resume the resized gang from) or unprofitable
    (``reason="unprofitable"``: the ledger's throughput-per-chip
    medians prove every measured candidate slower per chip than where
    the gang already is). Carries ``findings`` naming each rejected
    candidate — the same typed-refusal posture as
    :class:`~sparkdl_tpu.analysis.comms.ReshardPreflightError`."""

    def __init__(self, message, *, findings=(), reason="unprofitable"):
        super().__init__(message)
        self.findings = list(findings)
        self.reason = reason


def _per_chip_throughput(history):
    """{np: median throughput-per-chip} from ledger records that carry
    a world size and a throughput (or invertible step-time) metric."""
    by_np = {}
    for entry in history or ():
        if not isinstance(entry, dict):
            continue
        np_v = None
        for key in _NP_KEYS:
            v = entry.get(key)
            if isinstance(v, (int, float)) and int(v) >= 1:
                np_v = int(v)
                break
        if np_v is None:
            continue
        metrics = entry.get("metrics")
        if not isinstance(metrics, dict):
            continue
        val = None
        for name in _RATE_METRICS:
            val = _metric_value(metrics.get(name))
            if val is not None:
                break
        if val is None:
            for name in _STEP_TIME_METRICS:
                t = _metric_value(metrics.get(name))
                if t is not None and t > 0:
                    val = 1.0 / t
                    break
        if val is None or val <= 0:
            continue
        by_np.setdefault(np_v, []).append(val / np_v)
    return {n: _median(vals) for n, vals in by_np.items()}


def _metric_value(m):
    """Median-over-samples when the record carries them (>=3), else the
    point value — observe.compare's _effective_value discipline."""
    if not isinstance(m, dict):
        return None
    try:
        from sparkdl_tpu.observe.compare import _effective_value

        v, _src = _effective_value(m)
    except Exception:
        v = m.get("value")
    return float(v) if isinstance(v, (int, float)) else None


def choose_np(current_np, available_np, history=None, *, margin=None,
              max_np=None):
    """Target np for a grow from ``current_np`` given ``available_np``
    chips: the largest candidate the ledger does not prove slower per
    chip. Returns ``current_np`` when there is no surplus ("stay").
    Raises :class:`ElasticGrowRefused` when every measured candidate's
    throughput-per-chip median falls below ``margin`` x the current
    np's — growing into a provably worse configuration is the one
    move this policy exists to forbid. Candidates the ledger has never
    measured are allowed (nothing provable — the preflight posture).
    """
    current_np = int(current_np)
    cap = int(available_np)
    if max_np:
        cap = min(cap, int(max_np))
    if cap <= current_np:
        return current_np
    if margin is None:
        margin = float(os.environ.get(MARGIN_ENV) or "0.8")
    if history is None:
        from sparkdl_tpu.observe.perf import read_history

        history = read_history()
    per_chip = _per_chip_throughput(history)
    cur = per_chip.get(current_np)
    if cur is None or cur <= 0:
        # No ledger evidence about where we are now: nothing provable,
        # grow to the full surplus.
        return cap
    findings = []
    for target in range(cap, current_np, -1):
        pc = per_chip.get(target)
        if pc is None:
            return target
        if pc >= margin * cur:
            return target
        findings.append(
            f"np={target}: ledger median {pc:.4g}/chip < "
            f"{margin:.2f} x np={current_np}'s {cur:.4g}/chip")
    raise ElasticGrowRefused(
        f"grow from np={current_np} toward np={cap} refused: every "
        "measured candidate is slower per chip than the gang's "
        "current configuration (ledger medians)",
        findings=findings, reason="unprofitable")


def check_grow(current_np, available_np, *, resume_dir=None,
               latest_step=None, history=None, margin=None,
               max_np=None):
    """Feasibility + profitability gate for an autonomous grow. Raises
    the typed :class:`ElasticGrowRefused` when the grow is infeasible
    (no checkpoint to resume the resized gang from) or unprofitable
    (:func:`choose_np`'s ledger verdict); returns the chosen target np
    otherwise."""
    step = None
    if callable(latest_step):
        try:
            step = latest_step()
        except Exception:
            step = None
    elif latest_step is not None:
        step = latest_step
    elif resume_dir:
        from sparkdl_tpu.utils.checkpoint import latest_complete_step

        step = latest_complete_step(resume_dir)
    if not resume_dir:
        raise ElasticGrowRefused(
            "grow refused: no checkpoint directory configured "
            f"({RESUME_DIR_ENV} unset) — a resized gang would restart "
            "from step 0", reason="no_checkpoint",
            findings=[f"{RESUME_DIR_ENV} unset"])
    if step is None:
        raise ElasticGrowRefused(
            f"grow refused: no committed checkpoint under {resume_dir} "
            "yet — nothing for the resized gang to resume from",
            reason="no_checkpoint",
            findings=[f"no committed step under {resume_dir}"])
    return choose_np(current_np, available_np, history,
                     margin=margin, max_np=max_np)


class ElasticController:
    """One per supervised launch (like :class:`GangTelemetry`): watches
    capacity and serving demand across attempts, plans resizes at
    checkpoint boundaries, and answers the supervisor's what-np-next
    question on every relaunch.

    Driver-thread contract: :meth:`poll` runs in the launcher's
    monitor loop; :meth:`relaunch_target` and :meth:`note_attempt` run
    between attempts on the same thread; :meth:`status` is read from
    /statusz HTTP threads — hence the lock.
    """

    def __init__(self, current_np=None, *, alerts=None, env=None,
                 probe=None, clock=time.monotonic, latest_step=None,
                 resume_dir=None):
        env_map = os.environ if env is None else env
        self.check_s = float(env_map.get(CHECK_S_ENV) or "2.0")
        self.debounce_s = float(env_map.get(DEBOUNCE_S_ENV) or "10.0")
        self.margin = float(env_map.get(MARGIN_ENV) or "0.8")
        self.ckpt_wait_s = float(env_map.get(CKPT_WAIT_S_ENV) or "60")
        self.max_np = int(env_map.get(MAX_NP_ENV) or 0) or None
        self.min_np = max(1, int(env_map.get(MIN_NP_ENV) or "1"))
        self.arbiter = _truthy(env_map.get(ARBITER_ENV))
        self.arbiter_rules = tuple(
            r.strip() for r in
            (env_map.get(ARBITER_RULES_ENV)
             or "queue_depth_growth,server_ttft").split(",")
            if r.strip())
        self.arbiter_chips = max(1, int(env_map.get(ARBITER_CHIPS_ENV)
                                        or "1"))
        self.clear_s = float(env_map.get(ARBITER_CLEAR_S_ENV) or "30")
        self.resume_dir = (resume_dir if resume_dir is not None
                           else (env_map.get(RESUME_DIR_ENV) or None))

        self.current_np = int(current_np) if current_np else None
        self.available_np = None
        self._alerts = alerts
        self._probe = probe or (lambda: probe_capacity(env))
        self._clock = clock
        self._latest_step_fn = latest_step
        self._lock = threading.Lock()
        self._next_check = 0.0
        self._surplus_since = None
        self._refused_at = None      # capacity the ledger said no to
        self._pending = None         # planned resize awaiting a ckpt
        self._clamp_reason = None
        self._decisions = []
        self._transitions = {}       # "direction:reason" -> count
        self._demand_seen = 0        # arbiter-rule alert records seen
        self._quiet_since = None
        self._yielded = 0            # chips currently ceded to serving
        self._pre_yield_np = None
        self._fleet_base = None      # fleet replicas before scale-up

    # ---- probes -----------------------------------------------------

    def _latest_step(self):
        if self._latest_step_fn is not None:
            try:
                return self._latest_step_fn()
            except Exception:
                return None
        if not self.resume_dir:
            return None
        try:
            from sparkdl_tpu.utils.checkpoint import (
                latest_complete_step,
            )

            return latest_complete_step(self.resume_dir)
        except Exception:
            return None

    def _fleet_queue_depth(self):
        try:
            from sparkdl_tpu.observe.statusz import fleet_status

            rows = fleet_status()
        except Exception:
            return None
        if not rows:
            return None
        return sum(int(r.get("queue_depth") or 0) for r in rows)

    # ---- the monitor-loop tick --------------------------------------

    def poll(self, now=None):
        """One watcher tick (throttled to ``check_s``). Returns a
        resize request dict — ``{"direction", "target_np", "reason",
        "resume_step"}`` — when a planned resize has reached its
        checkpoint boundary and the launcher should recycle the gang
        NOW, else None."""
        now = self._clock() if now is None else now
        if now < self._next_check:
            return None
        self._next_check = now + self.check_s
        cap = self._probe()
        with self._lock:
            self.available_np = cap
            req = self._ripen_pending(now)
            if req is None and self._pending is None:
                plan = self._arbiter_plan(now)
                if plan is not None:
                    self._plan(plan, now)
                else:
                    self._grow_watch(now, cap)
        # The colocated-fleet resize joins retired worker threads
        # (FleetFrontend.scale_to blocks for seconds): it must run
        # after the lock is released, or every status()/
        # relaunch_target() caller on other threads queues behind it.
        if req is not None:
            if req["direction"] == "yield":
                self._scale_fleet(grow=True)
            elif req["direction"] == "reclaim":
                self._scale_fleet(grow=False)
        return req

    def _ripen_pending(self, now):
        pend = self._pending
        if pend is None or pend.get("emitted"):
            return None
        step = self._latest_step()
        decided = pend.get("decided_step")
        ready = step is not None and (decided is None or step > decided)
        if not ready:
            if now - pend["planned_at"] < self.ckpt_wait_s:
                return None
            if step is None:
                # The wait expired with no checkpoint ever committed: a
                # resize would restart the run from scratch. Cancel.
                if pend["direction"] == "yield":
                    self._yielded = 0
                self._record(direction=pend["direction"],
                             outcome="cancelled", reason="no_checkpoint",
                             from_np=self.current_np,
                             to_np=pend["target_np"])
                observe.instant(
                    "elastic.cancelled", cat="elastic",
                    direction=pend["direction"], reason="no_checkpoint",
                    target_np=pend["target_np"])
                self._pending = None
                return None
            # Wait bounded: resume from the newest committed step even
            # though it predates the decision.
        pend["emitted"] = True
        pend["resume_step"] = step
        self._record(direction=pend["direction"], outcome="resize",
                     reason=pend["reason"], from_np=self.current_np,
                     to_np=pend["target_np"], resume_step=step)
        observe.instant(
            "elastic.decision", cat="elastic",
            direction=pend["direction"], reason=pend["reason"],
            from_np=self.current_np, target_np=pend["target_np"],
            resume_step=step)
        logger.info(
            "elastic %s: recycling the gang np %s -> %s (%s), resuming "
            "from step %s", pend["direction"], self.current_np,
            pend["target_np"], pend["reason"], step)
        # The matching fleet resize happens in poll(), OUTSIDE the
        # controller lock — scale_to joins worker threads.
        return {"direction": pend["direction"],
                "target_np": pend["target_np"],
                "reason": pend["reason"], "resume_step": step}

    def _arbiter_plan(self, now):
        if not self.arbiter:
            return None
        demand, rule = False, None
        if self._alerts is not None:
            try:
                recs = [r for r in self._alerts.records()
                        if r.get("rule") in self.arbiter_rules]
            except Exception:
                recs = []
            if len(recs) > self._demand_seen:
                self._demand_seen = len(recs)
                demand = True
                rule = recs[-1].get("rule")
        depth = self._fleet_queue_depth()
        if demand or (depth is not None and depth > 0):
            self._quiet_since = None
        elif self._quiet_since is None:
            self._quiet_since = now
        cur = self.current_np
        if (demand and not self._yielded and cur is not None
                and cur > self.min_np):
            target = max(self.min_np, cur - self.arbiter_chips)
            if target < cur:
                self._yielded = cur - target
                self._pre_yield_np = cur
                return {"direction": "yield",
                        "reason": rule or "serving_alert",
                        "target_np": target}
        if (self._yielded and cur is not None
                and self._quiet_since is not None
                and now - self._quiet_since >= self.clear_s):
            target = self._pre_yield_np or (cur + self._yielded)
            if self.available_np is not None:
                target = min(target, self.available_np)
            if target > cur:
                self._yielded = 0
                return {"direction": "reclaim",
                        "reason": "alerts_clear", "target_np": target}
        return None

    def _grow_watch(self, now, cap):
        cur = self.current_np
        if cap is None or cur is None or self._yielded:
            self._surplus_since = None
            return
        if cap <= cur:
            # No surplus (or a dip mid-debounce): the clock restarts
            # from zero on the next surplus — the anti-thrash rule.
            self._surplus_since = None
            if self._refused_at is not None and cap != self._refused_at:
                self._refused_at = None
            return
        if self._surplus_since is None:
            self._surplus_since = now
            return
        if now - self._surplus_since < self.debounce_s:
            return
        if self._refused_at == cap:
            return  # the ledger's verdict will not change mid-run
        try:
            target = check_grow(
                cur, cap, resume_dir=self.resume_dir,
                latest_step=self._latest_step, margin=self.margin,
                max_np=self.max_np)
        except ElasticGrowRefused as e:
            if e.reason == "unprofitable":
                self._refused_at = cap
            self._record(direction="grow", outcome="refused",
                         reason=e.reason, from_np=cur, to_np=cap)
            observe.instant(
                "elastic.grow_refused", cat="elastic", current_np=cur,
                available_np=cap, reason=e.reason,
                problems=[str(f) for f in e.findings[:4]])
            logger.warning("elastic grow toward np=%d refused: %s",
                           cap, e)
            return
        if target > cur:
            self._plan({"direction": "grow",
                        "reason": "capacity_returned",
                        "target_np": target}, now)

    def _plan(self, req, now):
        req = dict(req)
        req["planned_at"] = now
        req["decided_step"] = self._latest_step()
        req["emitted"] = False
        self._pending = req
        observe.instant(
            "elastic.planned", cat="elastic", direction=req["direction"],
            reason=req["reason"], from_np=self.current_np,
            target_np=req["target_np"])
        logger.info(
            "elastic %s planned: np %s -> %s (%s); waiting for the "
            "next checkpoint boundary", req["direction"],
            self.current_np, req["target_np"], req["reason"])

    def _scale_fleet(self, grow):
        """Move the chips the other way on a colocated serving fleet:
        yield scales the fleet up by the yielded chips, reclaim scales
        it back to its pre-yield size. Best-effort — a fleet that
        cannot resize must not take down the training relaunch."""
        try:
            from sparkdl_tpu.observe.statusz import live_fleets

            fleets = live_fleets()
        except Exception:
            fleets = []
        for fleet in fleets[:1]:
            try:
                if grow:
                    self._fleet_base = fleet.replica_count()
                    target = self._fleet_base + (
                        self._yielded or self.arbiter_chips)
                else:
                    target = self._fleet_base or max(
                        1, fleet.replica_count() - self.arbiter_chips)
                fleet.scale_to(target)
                observe.instant(
                    "elastic.fleet_scale", cat="elastic",
                    replicas=target,
                    direction="up" if grow else "down")
            except Exception:
                logger.warning("elastic fleet scale failed",
                               exc_info=True)

    # ---- the supervisor's relaunch questions ------------------------

    def relaunch_target(self):
        """The np the next relaunch should use, or None (keep the
        configured np). A planned resize that reached its checkpoint
        boundary wins; otherwise the controller preserves the current
        world across unplanned relaunches, clamped down to the probed
        capacity — a gang must never relaunch wider than the pod."""
        with self._lock:
            pend = self._pending
            if pend is not None and pend.get("emitted"):
                return int(pend["target_np"])
            cur = self.current_np
            if cur is None:
                return None
            cap = self._probe()
            if cap is not None:
                self.available_np = cap
            target = cur
            if cap is not None and cap < cur:
                target = max(self.min_np, cap)
            if target != cur:
                self._clamp_reason = "capacity"
            return target

    def cancel_pending(self, reason):
        """Drop a planned resize (e.g. the reshard pre-flight refused
        its target): the relaunch proceeds at the current np."""
        with self._lock:
            pend, self._pending = self._pending, None
            if pend is None:
                return
            if pend["direction"] == "yield":
                self._yielded = 0
            self._record(direction=pend["direction"],
                         outcome="cancelled", reason=reason,
                         from_np=self.current_np,
                         to_np=pend.get("target_np"))
            observe.instant(
                "elastic.cancelled", cat="elastic",
                direction=pend["direction"], reason=reason,
                target_np=pend.get("target_np"))

    def note_attempt(self, num_workers):
        """Launcher hook: the resolved world size of the attempt that
        is about to spawn. World changes land the transition counter +
        instant; a consumed plan is cleared; the debounce clock
        restarts (a fresh attempt re-decides from scratch)."""
        with self._lock:
            prev = self.current_np
            world = int(num_workers)
            self.current_np = world
            pend, self._pending = self._pending, None
            self._surplus_since = None
            clamp, self._clamp_reason = self._clamp_reason, None
            if prev is None or world == prev:
                return
            if (pend is not None and pend.get("emitted")
                    and int(pend["target_np"]) == world):
                direction, reason = pend["direction"], pend["reason"]
            else:
                direction = "shrink" if world < prev else "grow"
                reason = clamp or "relaunch"
            key = f"{direction}:{reason}"
            self._transitions[key] = self._transitions.get(key, 0) + 1
            observe.inc("gang_elastic_transitions_total",
                        direction=direction, reason=reason)
            observe.instant(
                "elastic.transition", cat="elastic", direction=direction,
                reason=reason, from_np=prev, to_np=world)
            self._record(direction=direction, outcome="transition",
                         reason=reason, from_np=prev, to_np=world)
            logger.info("elastic transition: np %d -> %d (%s, %s)",
                        prev, world, direction, reason)

    # ---- introspection ----------------------------------------------

    def _record(self, **kw):
        kw["ts"] = time.time()
        self._decisions.append(kw)
        del self._decisions[:-200]  # keep the newest 200

    def status(self):
        """The /statusz "elastic" section: current vs available chips
        plus the newest decisions."""
        with self._lock:
            pend = self._pending
            return {
                "enabled": True,
                "arbiter": self.arbiter,
                "current_np": self.current_np,
                "available_np": self.available_np,
                "yielded_chips": self._yielded,
                "pending": (None if pend is None else {
                    "direction": pend["direction"],
                    "target_np": pend["target_np"],
                    "reason": pend["reason"],
                }),
                "transitions": dict(self._transitions),
                "decisions": list(self._decisions)[-8:],
            }

    def report(self):
        """The run dir's ``elastic.json`` decision log."""
        with self._lock:
            return {
                "schema": ELASTIC_SCHEMA,
                "enabled": True,
                "arbiter": self.arbiter,
                "current_np": self.current_np,
                "available_np": self.available_np,
                "yielded_chips": self._yielded,
                "transitions": dict(self._transitions),
                "decisions": list(self._decisions),
            }


# One active controller per driver process (mirrors the launcher's
# single supervised gang at a time); the supervisor consults it for
# relaunch targets without threading it through every signature.
_active = None


def set_active_controller(controller):
    global _active
    _active = controller


def active_controller():
    return _active


def _reset_for_tests():
    global _active
    _active = None


def maybe_make_controller(current_np=None, *, alerts=None, env=None):
    """The zero-overhead latch: None unless ``SPARKDL_TPU_ELASTIC`` is
    truthy — the monitor loop's ``is not None`` test is the whole cost
    of the feature when it is off."""
    env_map = os.environ if env is None else env
    if not _truthy(env_map.get(ELASTIC_ENV)):
        return None
    return ElasticController(current_np, alerts=alerts, env=env)
