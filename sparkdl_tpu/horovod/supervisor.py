"""Gang supervision: preemption-aware retry with checkpoint resume.

The launcher's gangs are fail-fast by design (reference
``runner_base.py:54-58``): any rank dying kills the whole job. Real
TPU pods, however, get preempted, lose hosts, and hit transient
rendezvous failures — and for those, throwing the run away is the
wrong answer when :class:`~sparkdl_tpu.utils.checkpoint.
TrainCheckpointer` already persists every step. This module wraps
``_launch_gang_once`` with the production recovery loop (the spirit of
Horovod's elastic mode, Sergeev & Del Balso 2018, restricted to
gang-relaunch granularity — one jax world per attempt, no membership
changes mid-run):

1. **Classify** each failure as *transient* (worker SIGKILL/
   preemption, rendezvous timeout, control-plane connection reset,
   port clash) or *permanent* (user-code exception, slot exhaustion,
   bad arguments). Permanent failures surface immediately — more
   restarts cannot create slots or fix user code.
2. **Relaunch** transient failures under exponential backoff with
   jitter (thundering-herd safety when many drivers share a
   control plane), up to a retry budget.
3. **Resume**: each relaunch ships a restart context to the workers —
   attempt number and, when a checkpoint directory is configured, the
   latest committed :class:`TrainCheckpointer` step — via env vars
   read by :func:`sparkdl_tpu.horovod.restart_context`. Unmodified
   mains keep working (the context is additive); checkpoint-aware
   mains restart where they left off.
4. **Exhaust loudly**: when the budget runs out,
   :class:`GangRetryBudgetExhausted` names every attempt with its
   classified cause — nothing is swallowed.

Knobs (all env-driven so ``HorovodRunner.run``'s locked signature is
untouched; see ``docs/fault_tolerance.rst``):

- ``SPARKDL_TPU_GANG_MAX_RETRIES``: relaunch budget for transient
  failures (default 0 — supervision off; ``SPARKDL_TPU_MAX_RESTARTS``
  is honored as a legacy alias).
- ``SPARKDL_TPU_GANG_BACKOFF_BASE`` / ``..._FACTOR`` / ``..._MAX``:
  exponential backoff schedule in seconds (defaults 1 / 2 / 60).
- ``SPARKDL_TPU_GANG_BACKOFF_JITTER``: jitter fraction added on top
  of each delay (default 0.5 — up to +50%).
- ``SPARKDL_TPU_GANG_RESUME_DIR``: TrainCheckpointer root whose
  latest committed step is shipped as the resume point.
- ``SPARKDL_TPU_TRANSIENT_PATTERNS``: ``;``-separated extra
  signatures (case-insensitive substring match against worker
  tracebacks) an operator can add for an interconnect whose
  infrastructure errors this module does not know yet.
- ``SPARKDL_TPU_GANG_RELAUNCH_NP``: target world size for the next
  relaunch (the elastic-shrink knob — a preempted pod coming back
  smaller). Before any relaunch with this set, the supervisor runs
  the static reshard pre-flight
  (:func:`sparkdl_tpu.analysis.comms.check_relaunch_np`) against the
  sharding tree the driver registered via
  :func:`sparkdl_tpu.analysis.register_gang_sharding`: an infeasible
  target — indivisible param dim, fractional-host mesh, restore
  high-water over the HBM budget — raises a typed
  :class:`~sparkdl_tpu.analysis.comms.ReshardPreflightError` naming
  the failing param/axis *before* the backoff sleep, instead of an
  OOM (or a sharding crash) mid-restore on the chips. Feasible
  targets are shipped to the relaunched workers through the same env
  var. With no registered tree the relaunch proceeds unchecked
  (nothing provable).
- ``SPARKDL_TPU_COMPILE_CACHE_DIR`` (read by the launcher/worker, not
  here, but load-bearing for this loop): the warm-start compile cache
  (:mod:`sparkdl_tpu.parallel.compile`). It rides the inherited
  environment into every relaunched attempt, so a replacement rank
  deserializes its step executable instead of re-paying the XLA
  compile — the difference between a resume measured in seconds and
  one measured in minutes at Llama scale.
"""

import dataclasses
import json
import logging
import os
import random
import re
import time

logger = logging.getLogger("HorovodRunner")

GANG_MAX_RETRIES_ENV = "SPARKDL_TPU_GANG_MAX_RETRIES"
LEGACY_MAX_RESTARTS_ENV = "SPARKDL_TPU_MAX_RESTARTS"
BACKOFF_BASE_ENV = "SPARKDL_TPU_GANG_BACKOFF_BASE"
BACKOFF_FACTOR_ENV = "SPARKDL_TPU_GANG_BACKOFF_FACTOR"
BACKOFF_MAX_ENV = "SPARKDL_TPU_GANG_BACKOFF_MAX"
BACKOFF_JITTER_ENV = "SPARKDL_TPU_GANG_BACKOFF_JITTER"
RESUME_DIR_ENV = "SPARKDL_TPU_GANG_RESUME_DIR"
EXTRA_PATTERNS_ENV = "SPARKDL_TPU_TRANSIENT_PATTERNS"
# Elastic-relaunch target np. Same literal as
# sparkdl_tpu.analysis.comms.RELAUNCH_NP_ENV (kept as a plain string
# here so this module never imports the analysis package at import
# time); tests pin the two spellings together.
RELAUNCH_NP_ENV = "SPARKDL_TPU_GANG_RELAUNCH_NP"

# The restart context workers read back via
# sparkdl_tpu.horovod.restart_context(). Shipped per-attempt through
# the worker env (never mutated in the driver's own os.environ — two
# concurrent supervised gangs in one driver must not see each other's
# attempt counters).
RESTART_ATTEMPT_ENV = "SPARKDL_TPU_RESTART_ATTEMPT"
RESUME_STEP_ENV = "SPARKDL_TPU_RESUME_STEP"
# Elastic relaunch mesh contract (JSON axis-size dicts): the recorded
# source mesh axes of the resume checkpoint and the target axes
# shrink_mesh derived for RELAUNCH_NP — shipped so relaunched worker
# mains rebuild the shrunken (or regrown) mesh without guessing.
RESHARD_SOURCE_AXES_ENV = "SPARKDL_TPU_RESHARD_SOURCE_AXES"
RESHARD_TARGET_AXES_ENV = "SPARKDL_TPU_RESHARD_TARGET_AXES"

# World size of every launch attempt in this driver process, in order
# (the launcher records each resolved gang size). Feeds the /statusz
# supervisor section so a shrunken gang is visible in mission control:
# current attempt's world vs the previous attempt's. The parallel
# stamps list (wall-clock start of each attempt) feeds the chip-hour
# utilization view; kept separate so tests that monkeypatch
# _attempt_worlds alone keep working.
_attempt_worlds = []
_attempt_stamps = []


def record_attempt_world(num_workers):
    """Launcher hook: one resolved gang size per launch attempt."""
    _attempt_worlds.append(int(num_workers))
    _attempt_stamps.append(time.time())


def attempt_world_sizes():
    """World sizes of this driver's launch attempts, oldest first."""
    return list(_attempt_worlds)


def attempt_chip_hours(now=None):
    """Chip-hours per attempt (world size x attempt wall duration):
    the /statusz utilization ledger of what an elastic run actually
    spent. The last attempt is priced up to ``now``. Attempts whose
    start stamp is unknown (tests monkeypatching _attempt_worlds)
    price as None rather than guessing."""
    now = time.time() if now is None else now
    out = []
    for i, world in enumerate(_attempt_worlds):
        t0 = _attempt_stamps[i] if i < len(_attempt_stamps) else None
        if t0 is None:
            out.append({"attempt": i + 1, "world": world,
                        "chip_hours": None})
            continue
        t1 = (_attempt_stamps[i + 1]
              if i + 1 < len(_attempt_stamps) else now)
        out.append({
            "attempt": i + 1, "world": world,
            "chip_hours": round(world * max(0.0, t1 - t0) / 3600.0, 6),
        })
    return out

TRANSIENT = "transient"
PERMANENT = "permanent"

# Infrastructure signatures in worker tracebacks (case-insensitive
# substring match). An EXC frame matching one of these is the gang
# runtime failing, not the user's main: the rank observing a peer's
# preemption raises a connection/collective error of its own, and
# classifying that as "user code" would veto the retry the preempted
# gang exists to get. Extend via SPARKDL_TPU_TRANSIENT_PATTERNS.
TRANSIENT_SIGNATURES = (
    "connection reset",
    "connection closed",
    "connection refused",
    "connection aborted",
    "broken pipe",
    "socket closed",
    "address already in use",       # coordinator/control-plane port clash
    "deadline_exceeded",
    "deadline exceeded",
    "unavailable:",                 # grpc status prefix
    "failed to connect",
    "coordination service",         # jax.distributed heartbeats
    "heartbeat",
    "barrier timed out",
    "rendezvous",
    "gloo",                         # CPU-rig collective runtime
    "preempt",
)


class GangFailure(RuntimeError):
    """A launched gang failed. Carries the structured evidence the
    supervisor classifies on: ``kind`` (``"rendezvous_timeout"``,
    ``"worker_death"``, ``"start_failure"``, ``"no_result"``,
    ``"hang"``), per-rank ``exit_codes`` (negative = killed by that
    signal), ``exceptions`` (rank → traceback text from EXC frames),
    and for hangs the detector's ``hang_verdict``
    (``straggler``/``deadlock``). Subclasses RuntimeError so
    pre-supervisor callers keep working."""

    def __init__(self, message, *, kind="unknown", exit_codes=None,
                 exceptions=None, hang_verdict=None):
        super().__init__(message)
        self.kind = kind
        self.exit_codes = list(exit_codes or [])
        self.exceptions = dict(exceptions or {})
        self.hang_verdict = hang_verdict


@dataclasses.dataclass
class AttemptRecord:
    """One launch attempt, as named in the exhaustion error."""
    number: int
    verdict: str       # TRANSIENT | PERMANENT
    cause: str

    def __str__(self):
        return f"attempt {self.number}: {self.verdict} — {self.cause}"


class GangRetryBudgetExhausted(RuntimeError):
    """Every relaunch in the budget failed transiently. The message
    names every attempt with its classified cause — the loud final
    word the acceptance contract requires."""

    def __init__(self, attempts, budget):
        self.attempts = list(attempts)
        self.budget = budget
        lines = "\n".join(f"  {a}" for a in self.attempts)
        super().__init__(
            f"HorovodRunner gang failed {len(self.attempts)} time(s); "
            f"retry budget ({budget} relaunch(es)) exhausted. "
            f"Attempt log:\n{lines}"
        )


def _extra_patterns():
    raw = os.environ.get(EXTRA_PATTERNS_ENV, "")
    return tuple(p.strip().lower() for p in raw.split(";") if p.strip())


def _terminal_block(tb_text):
    """The traceback's final exception message: from the last
    unindented non-header line (``SomeError: message``) to the end, so
    multi-line messages are kept. Frame lines (``File "/u/gloo.py"``)
    and source echoes are excluded — a user file PATH or source line
    mentioning 'gloo'/'rendezvous' must never read as infrastructure."""
    lines = tb_text.rstrip().splitlines()
    start = 0
    for i, ln in enumerate(lines):
        if (ln and not ln[0].isspace()
                and not ln.startswith("Traceback (")
                and not ln.startswith("During handling")
                and not ln.startswith("The above exception")):
            start = i
    return "\n".join(lines[start:])


def _is_infra_traceback(tb_text):
    """True when a worker's EXC frame is the distributed runtime
    failing (connection/collective/rendezvous errors), not user code.
    Checked against the TERMINAL exception block only — type line plus
    its message — never against file paths or source lines, so user
    code that merely lives near infrastructure vocabulary stays
    classified as user code (and is never retried)."""
    if not tb_text.strip():
        return False
    term = _terminal_block(tb_text)
    if re.match(
        r"(\w+\.)*(Connection(Reset|Refused|Aborted)?Error|"
        r"BrokenPipeError|TimeoutError|socket\.timeout)\b",
        term,
    ):
        return True
    low = term.lower()
    return any(
        sig in low for sig in TRANSIENT_SIGNATURES + _extra_patterns()
    )


def classify_failure(exc):
    """(verdict, cause): *permanent* failures are never retried.

    Taxonomy (ISSUE: preemption-aware supervision):

    - Typed launcher errors (slot exhaustion/probe/wait, remote
      transport) and bad arguments → permanent; the launcher already
      documents why each cannot self-heal.
    - A worker EXC frame that is NOT an infrastructure error →
      permanent: user code raised, and rerunning user bugs burns pod
      hours to reproduce them.
    - Rendezvous timeouts, lost results, ranks killed by signals
      (SIGKILL is what preemption looks like from the driver),
      detector-declared gang hangs (``kind="hang"`` — the HANG
      cause), and infrastructure-only EXC frames → transient.
    - Anything else (e.g. a worker exiting 1 with no traceback — a
      bootstrap crash such as an import error) → permanent: retrying
      what we cannot name would hide real breakage.
    """
    # Local import: launcher imports this module at call time too, and
    # a module-level circular import would order-lock the two.
    from sparkdl_tpu.horovod.launcher import (
        RemoteTransportError,
        SlotExhaustionError,
        SlotProbeError,
        SlotWaitTimeout,
    )

    if isinstance(exc, (SlotExhaustionError, SlotProbeError,
                        SlotWaitTimeout, RemoteTransportError)):
        return PERMANENT, f"{type(exc).__name__} (cannot self-heal)"
    if isinstance(exc, (ValueError, TypeError)):
        return PERMANENT, f"bad arguments ({type(exc).__name__})"
    if isinstance(exc, GangFailure):
        if exc.kind == "elastic_resize":
            # Not a failure at all: the elastic controller asked the
            # launcher to recycle the gang at a new np after a
            # checkpoint boundary (capacity returned, or the chip
            # arbiter moved chips between training and serving). The
            # relaunch is the whole point — transient by construction,
            # and the supervise loop charges it zero retry budget and
            # zero backoff. Checked FIRST for the same reason as hang:
            # the launcher's own kill makes the exit codes look
            # signal-killed.
            return TRANSIENT, (
                f"ELASTIC ({getattr(exc, 'elastic_direction', 'resize')}"
                f") — planned resize to "
                f"np={getattr(exc, 'elastic_target', '?')}; relaunching "
                "from checkpoint"
            )
        if exc.kind == "hang":
            # The hang detector declared the gang wedged (one rank
            # stuck in a collective, a stalled host callback...) and
            # the launcher already captured stack dumps and killed the
            # workers. From the outside this is preemption-shaped: the
            # run state is intact in the checkpoint, a relaunch
            # resumes it — classify transient under the HANG cause.
            # Checked FIRST: the launcher's own kill makes the exit
            # codes look signal-killed, and a mid-kill EXC frame must
            # not re-classify a diagnosed hang as user code.
            return TRANSIENT, (
                f"HANG ({exc.hang_verdict or 'hung'}) — gang made no "
                "progress; stack dumps captured, relaunching from "
                "checkpoint"
            )
        user_ranks = [
            r for r, tb in sorted(exc.exceptions.items())
            if not _is_infra_traceback(tb)
        ]
        if user_ranks:
            return PERMANENT, (
                f"user-code exception on rank(s) {user_ranks}"
            )
        if exc.kind == "rendezvous_timeout":
            return TRANSIENT, "gang rendezvous timed out"
        if exc.kind == "no_result":
            return TRANSIENT, "rank 0 result lost on the control plane"
        killed = [
            (r, -c) for r, c in enumerate(exc.exit_codes) if c and c < 0
        ]
        if killed:
            return TRANSIENT, (
                "rank(s) killed by signal "
                + ", ".join(f"{r} (sig {s})" for r, s in killed)
                + " — preemption-like"
            )
        if exc.exceptions:  # all infra tracebacks, no signal deaths
            return TRANSIENT, (
                "infrastructure failure on rank(s) "
                f"{sorted(exc.exceptions)}"
            )
        return PERMANENT, (
            f"unclassified worker failure (kind={exc.kind}, exit codes "
            f"{exc.exit_codes}) — not retried blindly"
        )
    return PERMANENT, f"unclassified {type(exc).__name__} (not retried)"


@dataclasses.dataclass
class RetryPolicy:
    """Relaunch budget + backoff schedule + resume source."""
    max_retries: int = 0
    backoff_base: float = 1.0
    backoff_factor: float = 2.0
    backoff_max: float = 60.0
    jitter: float = 0.5
    resume_dir: str = None

    @classmethod
    def from_env(cls, env=None):
        env = os.environ if env is None else env
        retries = env.get(GANG_MAX_RETRIES_ENV)
        if retries is None:
            # Legacy knob: same budget, but under the new policy only
            # TRANSIENT failures consume it (retrying a user exception
            # was always a bug amplifier).
            retries = env.get(LEGACY_MAX_RESTARTS_ENV, "0")
        return cls(
            max_retries=int(retries),
            backoff_base=float(env.get(BACKOFF_BASE_ENV, "1.0")),
            backoff_factor=float(env.get(BACKOFF_FACTOR_ENV, "2.0")),
            backoff_max=float(env.get(BACKOFF_MAX_ENV, "60.0")),
            jitter=float(env.get(BACKOFF_JITTER_ENV, "0.5")),
            resume_dir=env.get(RESUME_DIR_ENV) or None,
        )

    def backoff(self, attempt, _random=random.random):
        """Delay before relaunch #``attempt`` (1-based): capped
        exponential plus up to ``jitter`` fraction on top."""
        base = min(
            self.backoff_base * self.backoff_factor ** (attempt - 1),
            self.backoff_max,
        )
        return base * (1.0 + self.jitter * _random())


def _resume_step(policy):
    if not policy.resume_dir:
        return None
    from sparkdl_tpu.utils.checkpoint import latest_complete_step

    return latest_complete_step(policy.resume_dir)


def _relaunch_np_target():
    """The elastic-relaunch target np, or None (keep the configured
    np). The operator's env knob always wins; with it unset, the
    active :class:`~sparkdl_tpu.horovod.elastic.ElasticController` (if
    any) answers — a planned resize's target, or the current world
    clamped to the probed capacity. Unparsable operator input is
    logged, never fatal: a typo must not take down an otherwise-
    recoverable gang."""
    raw = os.environ.get(RELAUNCH_NP_ENV)
    if raw:
        try:
            return int(raw)
        except ValueError:
            logger.warning(
                "ignoring unparsable %s=%r (want an integer np)",
                RELAUNCH_NP_ENV, raw,
            )
            return None
    from sparkdl_tpu.horovod import elastic

    ctrl = elastic.active_controller()
    if ctrl is not None:
        try:
            return ctrl.relaunch_target()
        except Exception:
            logger.warning("elastic relaunch-target probe failed",
                           exc_info=True)
    return None


def _reshard_preflight(target_np):
    """Feasibility-gate an elastic relaunch at ``target_np`` BEFORE the
    backoff sleep: an infeasible shrink raises the typed
    ``ReshardPreflightError`` (naming the failing param/axis) here on
    the driver, where it costs a log line — not mid-restore on the
    chips, where it costs the pod an OOM. Returns the ReshardPlan, or
    None when no sharding tree was registered (nothing provable; the
    relaunch proceeds unchecked)."""
    from sparkdl_tpu import observe
    from sparkdl_tpu.analysis.comms import (
        ReshardPreflightError,
        check_relaunch_np,
    )

    try:
        plan = check_relaunch_np(target_np)
    except ReshardPreflightError as e:
        observe.instant(
            "gang.reshard_refused", cat="supervisor",
            target_np=target_np,
            problems=[str(f) for f in e.findings[:4]],
        )
        logger.error(
            "elastic relaunch at np=%d refused by the reshard "
            "pre-flight; not relaunching: %s", target_np, e,
        )
        raise
    if plan is not None:
        observe.instant(
            "gang.reshard_preflight", cat="supervisor",
            target_np=target_np, feasible=True,
            restore_high_water_bytes=plan.restore_high_water_bytes,
        )
        logger.info(
            "elastic relaunch at np=%d cleared the reshard pre-flight "
            "(target mesh %s, restore high-water %.2f GiB)",
            target_np, plan.target_axes,
            plan.restore_high_water_bytes / 2**30,
        )
    return plan


def _reshard_axes(policy, target_np, resume_step):
    """(source_axes, target_axes) for an elastic relaunch's restart
    context: source from the registered gang sharding when the driver
    registered one, else from the resume checkpoint's sharding-tree
    sidecar (jax-free — readable on the driver between relaunches);
    target derived via ``shrink_mesh``. ``(None, None)`` when no
    source mesh is knowable — workers then fall back to their own
    world-size defaults."""
    from sparkdl_tpu.analysis.comms import (
        registered_gang_sharding,
        shrink_mesh,
    )

    src = None
    reg = registered_gang_sharding()
    if reg is not None:
        src = dict(reg["source_axes"])
    if not src and policy.resume_dir and resume_step is not None:
        from sparkdl_tpu.utils.checkpoint import (
            load_sharding_tree,
            sidecar_mesh_axes,
        )

        doc = load_sharding_tree(policy.resume_dir, resume_step)
        if doc is not None:
            src = sidecar_mesh_axes(doc)
    if not src:
        return None, None
    tgt, _reason = shrink_mesh(src, int(target_np))
    return src, tgt


def supervise(launch, policy, _sleep=time.sleep):
    """Run ``launch(extra_env)`` under the retry policy.

    ``launch`` is called with the env delta to merge into every
    worker's environment (the restart context); it must raise on
    failure and return the gang result on success. The first attempt
    ships no context (unmodified mains see attempt 0 / no resume
    step); each relaunch ships the incremented attempt number and the
    newest committed checkpoint step.
    """
    from sparkdl_tpu import observe
    from sparkdl_tpu.utils import locksan

    # Opt-in lock-order sanitizer (SPARKDL_TPU_CONCUR_SAN=1): installed
    # before the supervisor spins up control plane / elastic threads so
    # every lock they construct is instrumented from birth.
    locksan.maybe_install()

    attempts = []
    attempt = 1
    budget_used = 0  # only UNPLANNED transient failures consume budget
    del _attempt_worlds[:]  # fresh story per supervised launch
    del _attempt_stamps[:]
    while True:
        extra_env = {}
        if attempt > 1:
            extra_env[RESTART_ATTEMPT_ENV] = str(attempt - 1)
            step = _resume_step(policy)
            if step is not None:
                extra_env[RESUME_STEP_ENV] = str(step)
            target_np = _relaunch_np_target()
            if target_np is not None:
                # Cleared by _reshard_preflight before the backoff
                # that led here; shipped so the relaunched workers see
                # the elastic target — the launcher resizes the gang
                # to it, and the axes pair below tells worker mains
                # the exact mesh to rebuild (recorded source layout +
                # shrink_mesh-derived target).
                extra_env[RELAUNCH_NP_ENV] = str(target_np)
                src_axes, tgt_axes = _reshard_axes(
                    policy, target_np, step)
                if src_axes:
                    extra_env[RESHARD_SOURCE_AXES_ENV] = json.dumps(
                        src_axes, sort_keys=True)
                if tgt_axes:
                    extra_env[RESHARD_TARGET_AXES_ENV] = json.dumps(
                        tgt_axes, sort_keys=True)
        observe.inc("gang_attempts_total")
        observe.instant("gang.attempt", cat="supervisor", attempt=attempt)
        try:
            return launch(extra_env)
        except Exception as e:
            verdict, cause = classify_failure(e)
            planned = getattr(e, "kind", None) == "elastic_resize"
            attempts.append(AttemptRecord(attempt, verdict, cause))
            first_line = (str(e).splitlines() or ["<no message>"])[0]
            if planned:
                # A controller-requested resize, not a failure: no
                # failure instant/counter, no budget charge, no
                # backoff — the checkpoint-boundary wait already
                # happened before the launcher recycled the gang.
                observe.instant(
                    "gang.resize", cat="supervisor", attempt=attempt,
                    cause=cause,
                    direction=getattr(e, "elastic_direction", None),
                    target_np=getattr(e, "elastic_target", None),
                )
                logger.info(
                    "HorovodRunner gang recycling for a planned "
                    "elastic resize (attempt %d: %s)", attempt, cause,
                )
            else:
                # Every AttemptRecord lands on the gang timeline with
                # its classify_failure verdict — the "classified
                # transient" beat of a chaos run's story — and in the
                # metric view (gang_failures_total by verdict,
                # alertable).
                observe.instant(
                    "gang.failure", cat="supervisor", attempt=attempt,
                    verdict=verdict, cause=cause,
                    kind=getattr(e, "kind", type(e).__name__),
                )
                observe.inc("gang_failures_total", verdict=verdict)
            if verdict == PERMANENT:
                logger.error(
                    "HorovodRunner gang failed permanently on attempt "
                    "%d (%s); not retrying: %s",
                    attempt, cause, first_line,
                )
                raise
            if not planned:
                budget_used += 1
                if budget_used > policy.max_retries:
                    if policy.max_retries > 0:
                        raise GangRetryBudgetExhausted(
                            attempts, policy.max_retries
                        ) from e
                    raise  # supervision off: surface untouched
            target_np = _relaunch_np_target()
            if target_np is not None:
                # Elastic relaunch: feasibility-check the resized
                # mesh BEFORE paying the backoff sleep — an
                # infeasible target raises the typed refusal here.
                # A controller-planned target that fails pre-flight
                # is cancelled instead (the relaunch proceeds at the
                # current np); only the operator's explicit env
                # target escalates the refusal.
                try:
                    _reshard_preflight(target_np)
                except Exception:
                    if os.environ.get(RELAUNCH_NP_ENV):
                        raise
                    from sparkdl_tpu.horovod import elastic

                    ctrl = elastic.active_controller()
                    if ctrl is None:
                        raise
                    ctrl.cancel_pending("reshard_preflight_refused")
            if planned:
                delay = 0.0
            else:
                delay = policy.backoff(budget_used)
            # Recomputed at the top of the next iteration too (listdir
            # is cheap); shown here so the operator sees the resume
            # point BEFORE the backoff sleep, not after.
            resume = _resume_step(policy)
            from sparkdl_tpu.parallel.compile import (
                COMPILE_CACHE_DIR_ENV,
            )

            warm = os.environ.get(COMPILE_CACHE_DIR_ENV)
            if not planned:
                logger.warning(
                    "HorovodRunner gang failed transiently (attempt "
                    "%d, retry %d/%d: %s); relaunching in %.1fs%s%s: "
                    "%s",
                    attempt, budget_used, policy.max_retries, cause,
                    delay,
                    "" if resume is None
                    else f" (will resume from step {resume})",
                    "" if not warm else " (compile cache warm)",
                    first_line,
                )
            observe.inc("gang_restarts_total")
            if delay > 0:
                with observe.span("gang.backoff", cat="supervisor",
                                  attempt=attempt,
                                  delay_s=round(delay, 3),
                                  resume_step=resume):
                    _sleep(delay)
            attempt += 1
