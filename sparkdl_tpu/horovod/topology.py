"""Gang topology: rank placement across hosts and TPU pod-slice env.

The reference's slot model spans the cluster — "each process will take
an available task slot ... on the task nodes" (reference
``runner_base.py:44-45``, ``:54-55``) — so a gang is a HOSTS x SLOTS
grid, not a flat local list. This module owns that mapping:

- :func:`parse_hosts` reads an mpirun-style host spec
  (``"host1:4,host2:4"``, the launcher's ``SPARKDL_TPU_HOSTS`` env).
- :class:`Placement` maps global rank -> (host index, local_rank,
  local_size) with hosts filled in order, and derives the per-process
  env a worker needs: the horovod-side LOCAL_* values plus the TPU
  runtime's pod-slice variables (``TPU_PROCESS_BOUNDS``,
  ``TPU_CHIPS_PER_PROCESS_BOUNDS``, ``CLOUD_TPU_TASK_ID``,
  ``TPU_PROCESS_ADDRESSES``) so ``jax.distributed.initialize`` on a
  real v4/v5 pod slice sees one process per chip laid out on the ICI
  mesh.

Single-host gangs (the launcher's default) are the 1-host special case;
the Spark barrier backend derives its Placement from the barrier task
infos instead of an env spec (executors already know their hosts).
"""

import functools
import os

HOSTS_ENV = "SPARKDL_TPU_HOSTS"
TPU_PORT_BASE = 8476  # libtpu's default inter-process port


def parse_hosts(spec):
    """``"h1:4,h2:4"`` -> ``[("h1", 4), ("h2", 4)]``; a bare host means
    one slot. Raises ValueError on malformed entries."""
    hosts = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, sep, slots = entry.partition(":")
        if not host:
            raise ValueError(f"empty host in host spec {spec!r}")
        try:
            n = int(slots) if sep else 1
        except ValueError:
            raise ValueError(
                f"bad slot count {slots!r} for host {host!r} in {spec!r}"
            )
        if n < 1:
            raise ValueError(f"host {host!r} has {n} slots in {spec!r}")
        hosts.append((host, n))
    if not hosts:
        raise ValueError(f"no hosts in host spec {spec!r}")
    return hosts


class Placement:
    """Rank layout over ``[(host, slots), ...]``, hosts filled in
    order: rank 0..s0-1 on host 0, the next s1 on host 1, ..."""

    def __init__(self, hosts):
        self.hosts = list(hosts)
        self.total_slots = sum(n for _, n in self.hosts)
        self._host_of = []
        self._local_of = []
        for hi, (_, n) in enumerate(self.hosts):
            for li in range(n):
                self._host_of.append(hi)
                self._local_of.append(li)

    @classmethod
    def from_env(cls, environ=os.environ):
        """Placement from SPARKDL_TPU_HOSTS, or None when unset (the
        single-host default)."""
        spec = environ.get(HOSTS_ENV)
        return cls(parse_hosts(spec)) if spec else None

    @classmethod
    def single_host(cls, slots, host="localhost"):
        return cls([(host, slots)])

    def host_index(self, rank):
        return self._host_of[rank]

    def host(self, rank):
        return self.hosts[self._host_of[rank]][0]

    def local_rank(self, rank):
        return self._local_of[rank]

    def local_size(self, rank):
        return self.hosts[self._host_of[rank]][1]

    def env_for_rank(self, rank, *, tpu=False):
        """The per-process env for ``rank``: horovod LOCAL_* values,
        plus TPU pod-slice layout when ``tpu`` (one process per chip;
        process grid = hosts x slots-per-host on the ICI mesh)."""
        if not 0 <= rank < self.total_slots:
            raise ValueError(
                f"rank {rank} outside gang of {self.total_slots}"
            )
        env = {
            "SPARKDL_TPU_LOCAL_RANK": str(self.local_rank(rank)),
            "SPARKDL_TPU_LOCAL_SIZE": str(self.local_size(rank)),
        }
        if tpu and self.total_slots > 1:
            # One task <-> one chip (reference runner_base.py:44-45,
            # GPU -> TPU): restrict each worker to its own chip.
            env["TPU_VISIBLE_DEVICES"] = str(self.local_rank(rank))
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
            if len(self.hosts) == 1:
                # Single host: isolated single-chip runtimes; the gang
                # coordinates via jax.distributed only (matches the
                # launcher's long-standing behavior on multi-chip VMs).
                env.setdefault("TPU_PROCESS_BOUNDS", "1,1,1")
                return env
            slots = self.hosts[0][1]
            if any(n != slots for _, n in self.hosts):
                raise ValueError(
                    "TPU pod slices need a uniform chips-per-host "
                    f"layout; got {self.hosts}"
                )
            # Pod slice: one process per chip, process grid tiled
            # linearly (hosts-major). Same-host processes get distinct
            # ports (base + local_rank). Larger 2D/3D slice shapes
            # should export TPU_PROCESS_BOUNDS themselves; this linear
            # spec covers the common N-host x M-chip rows.
            n_hosts = len(self.hosts)
            env.update({
                "TPU_PROCESS_BOUNDS": f"{n_hosts * slots},1,1",
                "CLOUD_TPU_TASK_ID": str(rank),
                "TPU_PROCESS_PORT": str(
                    TPU_PORT_BASE + self.local_rank(rank)
                ),
                # Loopback aliases in the spec must be rewritten to a
                # routable address here: a remote rank dialing
                # "localhost" for its driver-host peers connects to
                # ITSELF and the mesh init hangs.
                "TPU_PROCESS_ADDRESSES": ",".join(
                    f"{_addressable(self.host(r))}"
                    f":{TPU_PORT_BASE + self.local_rank(r)}"
                    for r in range(self.total_slots)
                ),
            })
        return env


@functools.lru_cache(maxsize=64)
def _addressable(host):
    """A form of ``host`` that PEER machines can dial: loopback
    aliases become this machine's routable IP; anything else (a DNS
    name, a NIC address) passes through. Cached: the peer list is
    rebuilt per rank, and a multi-NIC driver whose default route
    flaps mid-launch must not hand different ranks different peer
    addresses. If no routable address can be determined at all, the
    alias passes through unchanged — same-host peers still work, and
    remote peers fail with a connect error naming the address rather
    than a raw resolver traceback at env-construction time."""
    if host in ("localhost", "127.0.0.1", "::1"):
        from sparkdl_tpu.horovod.control_plane import routable_host_ip

        try:
            return routable_host_ip()
        except OSError:
            return host
    return host


@functools.lru_cache(maxsize=256)
def is_local_host(host):
    """True when ``host`` names THIS machine: loopback, our hostname /
    fqdn, or an address that resolves onto one of this host's own
    addresses. Used by the launcher to decide local ``Popen`` vs the
    remote-exec transport — a multi-host spec must never silently
    collapse onto one machine.

    Cached: the launcher asks per rank, and repeating blocking DNS
    lookups inside the start-timeout window is waste — worse, a flaky
    resolver answering differently between two calls could wire the
    gang for remote transport yet Popen a rank locally."""
    import socket

    if host in ("localhost", "127.0.0.1", "::1"):
        return True
    names = {socket.gethostname()}
    try:
        names.add(socket.getfqdn())
    except OSError:
        pass
    if host in names:
        return True
    try:
        host_ips = {ai[4][0] for ai in socket.getaddrinfo(host, None)}
    except OSError:
        # Unresolvable names are NOT local: better to fail loudly in
        # the remote transport than to quietly launch locally.
        return False
    if any(ip.startswith("127.") or ip == "::1" for ip in host_ips):
        return True
    local_ips = set()
    for n in names:
        try:
            local_ips |= {ai[4][0] for ai in socket.getaddrinfo(n, None)}
        except OSError:
            pass
    # Hostname resolution alone misses NIC addresses on stock
    # Debian-style /etc/hosts (hostname -> 127.0.1.1): a spec naming
    # this driver by its real IP must still classify as local.
    try:
        from sparkdl_tpu.horovod.control_plane import routable_host_ip

        local_ips.add(routable_host_ip())
    except OSError:
        pass
    return bool(host_ips & local_ips)


def placement_from_task_hosts(host_of_rank):
    """Placement for an ALREADY-SCHEDULED gang (Spark barrier mode):
    ``host_of_rank[r]`` is the host executing rank r. Local ranks are
    assigned by order of appearance within each host, so they are
    stable across the gang regardless of scheduling interleave."""
    seen = {}
    locals_ = []
    for h in host_of_rank:
        locals_.append(seen.get(h, 0))
        seen[h] = locals_[-1] + 1
    p = Placement([(h, n) for h, n in seen.items()])
    # Override the order-derived tables: scheduled gangs may interleave
    # hosts, e.g. ranks [h0, h1, h0, h1].
    p._host_of = [list(seen).index(h) for h in host_of_rank]
    p._local_of = locals_
    return p
