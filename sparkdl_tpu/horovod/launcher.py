"""Gang launcher: the real implementation of the distributed modes the
reference only documents (``runner_base.py:48-61``).

Responsibilities (each clause cites the contract it implements):

- serialize ``(main, kwargs)`` with cloudpickle and ship to workers
  (reference ``runner_base.py:82-83``); warn on large payloads
  (reference ``runner_base.py:90-91``).
- resolve task slots and fail fast if ``np`` exceeds them (reference
  ``runner_base.py:56-58``); ``np == 0`` uses all slots with a
  deprecation warning (reference ``README.md:57-61``).
- start all workers together — a gang (reference ``runner_base.py:
  54-55``): every worker must rendezvous (READY) within the start
  timeout or the whole gang is killed.
- bind one task to one TPU chip — the TPU replacement for the
  reference's one-GPU-per-slot rule (reference ``runner_base.py:44-45``)
  — via ``TPU_VISIBLE_DEVICES`` when multiple workers share a host.
- route worker logs per ``driver_log_verbosity`` and return rank 0's
  cloudpickled result (reference ``runner_base.py:62-72``, ``:93-95``).

Cluster topology is pluggable: the default backend gang-launches local
processes (one per slot); a Spark barrier-mode backend is selected
automatically when pyspark is importable (see
:mod:`sparkdl_tpu.horovod.spark_backend`).
"""

import logging
import os
import socket
import subprocess
import sys
import tempfile
import time

from sparkdl_tpu.horovod.topology import HOSTS_ENV
from sparkdl_tpu.hvd._state import COORD_ENV

COORD_PORT_ENV = "SPARKDL_TPU_COORDINATOR_PORT"
# Warm-start compilation: when the driver sets this env, every worker
# env carries it (local Popen children inherit it via _worker_env's
# base_env copy; remote ranks ride the SPARKDL_TPU_* forward), every
# supervised relaunch re-ships it, and _worker.py points JAX's
# persistent compile cache at it before backend init. The module is
# import-light (jax only inside functions), so the launcher can take
# the constant from its canonical home.
from sparkdl_tpu.parallel.compile import COMPILE_CACHE_DIR_ENV

logger = logging.getLogger("HorovodRunner")


class SlotExhaustionError(RuntimeError):
    """np exceeds TOTAL task slots (reference runner_base.py:56-58).
    Never retried — more restarts cannot create slots."""


class SlotProbeError(RuntimeError):
    """Slot discovery itself failed (e.g. the device-count subprocess
    died on a wedged accelerator). Surfaced instead of guessing a count:
    an optimistic guess turns into a misleading "only N slots" error
    at launch time. Never retried — the relaunch loop would just re-run
    the same 120s probe against the same wedged backend."""


class SlotWaitTimeout(RuntimeError):
    """Gave up waiting for busy slots to free. Never retried — a
    relaunch would silently wait the full period again right after
    telling the user it gave up."""

START_TIMEOUT_ENV = "SPARKDL_TPU_START_TIMEOUT"
REMOTE_SHELL_ENV = "SPARKDL_TPU_REMOTE_SHELL"
REMOTE_PYTHON_ENV = "SPARKDL_TPU_REMOTE_PYTHON"
NUM_SLOTS_ENV = "SPARKDL_TPU_NUM_SLOTS"
WORKER_PLATFORM_ENV = "SPARKDL_TPU_WORKER_PLATFORM"
SLOT_WAIT_TIMEOUT_ENV = "SPARKDL_TPU_SLOT_WAIT_TIMEOUT"
SLOT_DIR_ENV = "SPARKDL_TPU_SLOT_DIR"
DEFAULT_START_TIMEOUT = 300.0
DEFAULT_SLOT_WAIT_TIMEOUT = 600.0
LARGE_PAYLOAD_BYTES = 10 << 20


def _free_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _probe_local_device_count(platform):
    """Count local accelerator devices WITHOUT initializing a backend in
    the driver process (a driver that claims the TPU would starve its
    own workers — the analogue of the reference's driver-has-no-GPU
    assumption, ``runner_base.py:44-45``)."""
    if platform == "cpu":
        return os.cpu_count() or 1
    code = (
        "import jax\n"
        + (f"jax.config.update('jax_platforms', {platform!r})\n" if platform else "")
        + "print(jax.local_device_count())\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120,
        )
        return int(out.stdout.strip().splitlines()[-1])
    except subprocess.TimeoutExpired:
        raise SlotProbeError(
            "slot discovery timed out after 120s probing local "
            "accelerator devices — the backend may be wedged (set "
            f"{NUM_SLOTS_ENV} to bypass discovery)"
        )
    except Exception as e:
        detail = ""
        if isinstance(e, (ValueError, IndexError)) and "out" in locals():
            # Parse failure AFTER the probe ran: its stderr says why.
            detail = f"; probe stderr tail: {out.stderr.strip()[-400:]}"
        raise SlotProbeError(
            f"slot discovery failed ({type(e).__name__}: {e}){detail} "
            f"(set {NUM_SLOTS_ENV} to bypass discovery)"
        )


def available_slots():
    """Total task slots: override via SPARKDL_TPU_NUM_SLOTS, else the
    number of local accelerator chips (CPU rigs: cores). Raises
    :class:`SlotProbeError` when discovery itself fails."""
    override = os.environ.get(NUM_SLOTS_ENV)
    if override:
        return int(override)
    return _probe_local_device_count(os.environ.get(WORKER_PLATFORM_ENV))


# -- slot registry ----------------------------------------------------------
#
# The contract distinguishes BUSY slots from MISSING slots: a job whose
# np fits the cluster total "will wait until np task slots are available
# to launch the job", and only fails when np exceeds the total
# (reference runner_base.py:56-58). Concurrent gangs on one host
# coordinate through a claim-file registry: each gang atomically claims
# its slot count under an flock'd directory, and claims of dead
# processes are reaped so a crashed driver never leaks slots.


def _slot_dir():
    d = os.environ.get(SLOT_DIR_ENV) or os.path.join(
        tempfile.gettempdir(), "sparkdl-tpu-slots"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else


def _busy_slots_locked(d):
    """Sum live claims in the registry (caller holds the lock); reaps
    claims whose owner process is gone."""
    busy = 0
    for name in os.listdir(d):
        if not name.endswith(".claim"):
            continue
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                pid_s, count_s = f.read().split()
            if _pid_alive(int(pid_s)):
                busy += int(count_s)
            else:
                os.unlink(path)  # stale: owner died without release
        except (OSError, ValueError):
            try:
                os.unlink(path)
            except OSError:
                pass
    return busy


class SlotClaim:
    def __init__(self, path):
        self._path = path

    def release(self):
        try:
            os.unlink(self._path)
        except OSError:
            pass


def claim_slots(n, total, timeout=None):
    """Claim ``n`` of ``total`` host slots, waiting while they are busy.

    Wait-until-available semantics (reference runner_base.py:56-58):
    blocks while other live gangs hold slots, raising only on timeout
    (``SPARKDL_TPU_SLOT_WAIT_TIMEOUT``, default 600s). The total-vs-np
    fail-fast check happens in ``_resolve_num_workers`` before this.
    """
    import fcntl
    import uuid

    if timeout is None:
        timeout = float(
            os.environ.get(SLOT_WAIT_TIMEOUT_ENV, DEFAULT_SLOT_WAIT_TIMEOUT)
        )
    d = _slot_dir()
    lock_path = os.path.join(d, ".lock")
    deadline = time.monotonic() + timeout
    logged_waiting = False
    while True:
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            busy = _busy_slots_locked(d)
            if total - busy >= n:
                path = os.path.join(d, f"{uuid.uuid4().hex}.claim")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(f"{os.getpid()} {n}")
                os.replace(tmp, path)
                return SlotClaim(path)
        if time.monotonic() > deadline:
            raise SlotWaitTimeout(
                f"HorovodRunner waited {timeout:.0f}s for {n} of {total} "
                f"task slots ({busy} busy in other jobs) without success; "
                "giving up. Increase "
                f"{SLOT_WAIT_TIMEOUT_ENV} or stop the competing jobs."
            )
        if not logged_waiting:
            logger.info(
                "HorovodRunner: %d/%d task slots busy; waiting for %d "
                "to free up (contract: wait while busy, fail only when "
                "np exceeds the cluster total).", busy, total, n,
            )
            logged_waiting = True
        time.sleep(0.2)


def _resolve_num_workers(np_arg, placement=None):
    """Returns (num_workers, mode, total_slots); total_slots is None in
    local mode (oversubscription allowed, no slot accounting). With a
    hosts spec (``placement``), the cluster total is the spec's
    declared slot count — the slots live on the task NODES (reference
    runner_base.py:44-45), so probing only this machine's chips would
    wrongly fail any np that exceeds the local count. The spec is
    TRUSTED, deliberately: cross-checking its local entry against real
    chips would re-introduce the 120s probe subprocess this path
    exists to avoid, so a spec overstating a host's slots fails at
    device-bind time instead (with that rank's log naming the chip).
    Without a spec, the one local probe here is reused for the slot
    claim — probing again at claim time would double the 120s-budget
    subprocess and open a TOCTOU window where a flaky probe shrinks
    the total below np."""
    if np_arg <= -2:
        # Local mode: spawn -np subprocesses on this host (reference
        # runner_base.py:48-53). No slot check: CPU oversubscription is
        # explicitly allowed there.
        return -np_arg, "local", None
    slots = (placement.total_slots if placement is not None
             else available_slots())
    if np_arg == 0:
        # deprecation warning lives in _launch_gang_once (fires once,
        # before backend dispatch)
        return slots, "cluster", slots
    if np_arg > slots:
        # np exceeds the cluster TOTAL: fail fast, never wait
        # (reference runner_base.py:56-58).
        if placement is not None:
            # NUM_SLOTS_ENV is not consulted on this path — pointing
            # users at it would send them in a circle.
            raise SlotExhaustionError(
                f"HorovodRunner requested np={np_arg} task slots but "
                f"the {HOSTS_ENV} spec declares only {slots} in "
                f"total; the job fails fast rather than wait (add "
                f"hosts/slots to {HOSTS_ENV})."
            )
        raise SlotExhaustionError(
            f"HorovodRunner requested np={np_arg} task slots but the host "
            f"has only {slots} in total; the job fails fast rather than "
            "wait (set SPARKDL_TPU_NUM_SLOTS to override slot discovery)."
        )
    return np_arg, "cluster", slots


def _worker_env(base_env, *, rank, size, coordinator, control_addr,
                control_secret, payload_path, job_dir, platform,
                placement=None):
    from sparkdl_tpu.horovod.topology import Placement

    env = dict(base_env)
    env.update({
        "SPARKDL_TPU_RANK": str(rank),
        "SPARKDL_TPU_SIZE": str(size),
        "SPARKDL_TPU_COORDINATOR": coordinator,
        "SPARKDL_TPU_CONTROL_ADDR": control_addr,
        # Per-job credential for the control plane: the driver
        # cloudpickle-loads the RESULT frame, so only processes holding
        # this secret may speak to it (env never crosses the network).
        "SPARKDL_TPU_CONTROL_SECRET": control_secret,
        "SPARKDL_TPU_PAYLOAD": payload_path,
        "SPARKDL_TPU_JOB_DIR": job_dir,
    })
    # Topology: SPARKDL_TPU_HOSTS defines a hosts x slots grid
    # (reference runner_base.py:44-45, :54-55 — slots live on task
    # NODES); default is the single-host gang. The hosts-spec path also
    # computes the TPU pod-slice env so externally-placed workers (one
    # per chip across a slice) come up on the ICI mesh.
    if placement is None:
        placement = Placement.from_env(base_env)
    if placement is None:
        placement = Placement.single_host(size)
    for k, v in placement.env_for_rank(rank, tpu=platform == "tpu").items():
        if (k in ("TPU_PROCESS_BOUNDS", "TPU_CHIPS_PER_PROCESS_BOUNDS")
                and base_env.get(k)):
            # An operator-exported slice layout (e.g. a 2D "2,2,1"
            # grid) overrides the linear default.
            continue
        env[k] = v
    if platform:
        env["SPARKDL_TPU_FORCE_PLATFORM"] = platform
    # The driver's XLA_FLAGS (e.g. a forced 8-device host platform in
    # test rigs) must not leak into workers: each worker is one rank on
    # its own device(s).
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        kept = [
            f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        env["XLA_FLAGS"] = " ".join(kept)
    return env


# -- remote exec transport --------------------------------------------------
#
# A hosts spec naming machines other than this one (reference
# runner_base.py:54-55 — slots live "on the task nodes") launches those
# ranks through a remote shell, mpirun-style: ``ssh <host> env K=V ...
# python -m sparkdl_tpu.horovod._worker`` with the rank's payload piped
# over the connection's stdin (SPARKDL_TPU_PAYLOAD=-). Assumes a
# homogeneous cluster: same python (override SPARKDL_TPU_REMOTE_PYTHON)
# and same package layout (PYTHONPATH is forwarded). There is NO silent
# fallback: if the transport is disabled or unavailable, the launch
# fails with a typed error instead of oversubscribing this host.


class RemoteTransportError(RuntimeError):
    """A multi-host placement cannot be honored: the remote-exec
    transport is disabled or no remote shell is available. Raised
    instead of silently launching every rank locally."""


def _resolve_remote_shell():
    """The remote-exec command tokens (``["ssh", "-o", ...]``), or
    raises. ``SPARKDL_TPU_REMOTE_SHELL`` overrides (a test rig points
    it at a fake ssh; ``none`` disables remote exec entirely)."""
    import shlex
    import shutil

    spec = os.environ.get(REMOTE_SHELL_ENV)
    # empty/whitespace = the common way to "unset" a var: fall through
    # to ssh detection rather than exec-ing the hostname as a program
    if spec is not None and spec.strip():
        if spec.strip().lower() == "none":
            raise RemoteTransportError(
                f"{REMOTE_SHELL_ENV}=none disables remote exec"
            )
        return shlex.split(spec)
    if shutil.which("ssh") is None:
        raise RemoteTransportError(
            "no `ssh` on PATH and no SPARKDL_TPU_REMOTE_SHELL override"
        )
    # BatchMode: a gang launch must fail fast, never sit at a password
    # prompt inside the start timeout.
    return ["ssh", "-o", "BatchMode=yes"]


def _remote_worker_cmd(shell_tokens, host, env, base_env, remote_python):
    """Build the remote launch argv. Only the env DELTA the launcher
    computed (gang wiring, TPU layout) plus PYTHONPATH crosses the
    wire — the rest of this machine's environment is not meaningful on
    the task node. Values are shell-quoted: ssh hands the command line
    to the remote shell."""
    import shlex

    # Forward (a) the whole gang-config namespace — matching on the
    # env DELTA alone silently drops vars whose computed value equals
    # the driver's own env, e.g. an operator-pinned
    # SPARKDL_TPU_COORDINATOR or exported TPU_PROCESS_BOUNDS — and
    # (b) anything else the launcher computed fresh for this rank.
    fwd = {
        k: v for k, v in env.items()
        if (k.startswith(("SPARKDL_TPU_", "TPU_"))
            or k == "CLOUD_TPU_TASK_ID"
            or base_env.get(k) != v)
        and k != "XLA_FLAGS"
    }
    if base_env.get("PYTHONPATH"):
        fwd.setdefault("PYTHONPATH", base_env["PYTHONPATH"])
    # The payload file lives on the driver; the remote worker reads it
    # from stdin (ssh forwards our stdin pipe).
    fwd["SPARKDL_TPU_PAYLOAD"] = "-"
    # The control-plane credential must NEVER ride the command line —
    # argv is world-readable in /proc on both machines (and often
    # logged by sshd) while the control plane listens beyond loopback
    # for exactly these gangs. It rides stdin instead: first line of
    # the boot stream, ahead of the payload.
    fwd["SPARKDL_TPU_CONTROL_SECRET"] = "stdin"
    # The driver's job dir path is meaningless remotely; the worker
    # mkdirs its own copy for the per-rank log.
    return (
        list(shell_tokens)
        + [host, "env"]
        + [f"{k}={shlex.quote(v)}" for k, v in sorted(fwd.items())]
        + [remote_python, "-m", "sparkdl_tpu.horovod._worker"]
    )


def _tail(path, n=40):
    try:
        with open(path, "r", errors="replace") as f:
            return "".join(f.readlines()[-n:])
    except OSError:
        return ""


def launch_gang(np, main, kwargs, driver_log_verbosity, per_rank_kwargs=None):
    """Launch a gang of workers and return rank 0's result.

    Recovery model (SURVEY.md §5.3): gangs are fail-fast, not elastic —
    the recovery story is supervised relaunch
    (:mod:`sparkdl_tpu.horovod.supervisor`). Set
    ``SPARKDL_TPU_GANG_MAX_RETRIES=N`` (legacy alias
    ``SPARKDL_TPU_MAX_RESTARTS``) to relaunch a gang whose failure
    classifies as *transient* — preemption-style signal deaths,
    rendezvous timeouts, control-plane resets — up to N times under
    exponential backoff (fresh job dir, fresh rendezvous), shipping a
    restart context (attempt number + latest checkpoint step from
    ``SPARKDL_TPU_GANG_RESUME_DIR``) to the relaunched workers.
    *Permanent* failures — user-code exceptions, slot exhaustion, bad
    arguments — are never retried.

    :param per_rank_kwargs: optional list (len = gang size) of dicts
        merged into ``kwargs`` for each rank and serialized into that
        rank's own payload — so rank-private data (e.g. a dataset
        shard) is shipped only to its worker instead of to the whole
        gang.
    """
    from sparkdl_tpu import observe
    from sparkdl_tpu.horovod.supervisor import RetryPolicy, supervise

    # Opt-in pre-flight lint (SPARKDL_TPU_PREFLIGHT_LINT=1): analyze
    # the payload and any registered jitted/lowered train step on the
    # driver and refuse to launch on ERROR findings — BEFORE the
    # supervisor loop, slot claims, payload serialization, or any
    # worker spawn. A graph bug is permanent; retrying it under
    # backoff would burn the whole retry budget on chip-hours.
    from sparkdl_tpu.analysis.preflight import (
        preflight_lint,
        take_comms_reports,
        take_fixit_reports,
    )

    preflight_lint(main, kwargs, per_rank_kwargs=per_rank_kwargs)
    # The pre-flight also priced every registered compiled module's
    # collectives (the static comms budget). Collected here so the
    # telemetry run dir carries comms_report.json next to the measured
    # collective_bytes_total — observe.doctor renders the two side by
    # side (predicted-vs-measured is the analyzer's own e2e gate).
    comms_reports = take_comms_reports()
    # With SPARKDL_TPU_PREFLIGHT_FIX=1 the pre-flight also ran the
    # verified fix engine over every registered callable step (auto-
    # donation et al, each applied fix carrying its four proofs).
    # Drained the same way so the run dir carries fixit_report.json
    # next to comms_report.json — observe.doctor renders the fixit
    # table from it.
    fixit_reports = take_fixit_reports()

    # Opt-in telemetry (SPARKDL_TPU_TELEMETRY_DIR): ONE aggregator per
    # launch_gang call spans every supervised attempt, so a chaos run's
    # kill → classify → backoff → resume lands in one merged timeline.
    # Artifacts are written in the finally — a gang that exhausts its
    # retry budget leaves its telemetry behind for the postmortem.
    telemetry = None
    alert_engine = None
    forensics = None
    if observe.enabled():
        from sparkdl_tpu.observe.aggregate import GangTelemetry

        telemetry = GangTelemetry()
        if comms_reports:
            telemetry.add_comms_reports(comms_reports)
        if fixit_reports:
            telemetry.add_fixit_reports(fixit_reports)
        # Streaming alert engine (ISSUE 14; SPARKDL_TPU_ALERTS): ONE
        # engine spans every supervised attempt, like the telemetry
        # aggregator — an elastic gang that resizes between attempts
        # keeps its alert history while the per-rank state is rebuilt
        # via set_world() per attempt (observe/alerts.py).
        from sparkdl_tpu.observe.alerts import maybe_make_engine

        alert_engine = maybe_make_engine(telemetry)
        # Perf forensics (ISSUE 20): alert-triggered / on-demand
        # capture orchestration + regression_report.json. One manager
        # spans attempts like the alert engine; each attempt rebinds
        # it to its control plane (bind_server). The ON_ALERT knob
        # gates only the alert hook — manual /capturez works on any
        # telemetry-on gang.
        from sparkdl_tpu.observe.forensics import maybe_make_forensics

        forensics = maybe_make_forensics(
            telemetry, alert_engine=alert_engine)
    # Autonomous elasticity (ISSUE 16; SPARKDL_TPU_ELASTIC): the
    # capacity watcher / chip-budget arbiter also spans every attempt.
    # It is consulted by the supervisor for relaunch targets via the
    # module-level active-controller registration, and polled in the
    # monitor loop below for planned (checkpoint-boundary) resizes.
    from sparkdl_tpu.horovod.elastic import (
        maybe_make_controller,
        set_active_controller,
    )

    controller = maybe_make_controller(alerts=alert_engine)
    if controller is not None:
        set_active_controller(controller)
    try:
        return supervise(
            lambda extra_env: _launch_gang_once(
                np, main, kwargs, driver_log_verbosity, per_rank_kwargs,
                extra_env=extra_env, telemetry=telemetry,
                alert_engine=alert_engine, controller=controller,
                forensics=forensics,
            ),
            RetryPolicy.from_env(),
        )
    finally:
        if controller is not None:
            set_active_controller(None)
        if telemetry is not None and alert_engine is not None:
            # The report is attached even when nothing fired: a clean
            # run's alerts.json proves the rules were evaluated (the
            # false-positive guard is auditable).
            try:
                telemetry.add_alert_report(alert_engine.report())
            except Exception:
                logger.warning("alert report attach failed",
                               exc_info=True)
        if telemetry is not None and controller is not None:
            # The elastic decision log — every grow/yield/reclaim with
            # its reason — lands in the run dir's elastic.json.
            try:
                telemetry.add_elastic_report(controller.report())
            except Exception:
                logger.warning("elastic report attach failed",
                               exc_info=True)
        # Guard the dir re-read too: the write must NEVER mask the
        # gang's own result/exception, even if the env vanished
        # mid-run (tests) or the dir is unwritable.
        if telemetry is not None and observe.telemetry_dir():
            try:
                paths = telemetry.write(observe.new_run_dir())
            except Exception as e:
                # Catch-all, deliberately: an unwritable dir OR a
                # malformed frame that slipped past ingest's shape
                # check and only detonates in the merge math must
                # never replace the gang's own result/exception.
                logger.warning("telemetry write under %s failed: %s",
                               observe.telemetry_dir(), e)
            else:
                logger.info("gang telemetry written: %s",
                            ", ".join(sorted(paths.values())))


def _launch_gang_once(np, main, kwargs, driver_log_verbosity,
                      per_rank_kwargs=None, extra_env=None,
                      telemetry=None, alert_engine=None,
                      controller=None, forensics=None):
    import cloudpickle

    from sparkdl_tpu import observe
    from sparkdl_tpu.horovod.control_plane import ControlPlaneServer
    from sparkdl_tpu.horovod.supervisor import GangFailure
    from sparkdl_tpu.horovod.topology import Placement, is_local_host

    if np == 0:
        # warned HERE, once, whichever backend ends up hosting the gang
        logger.warning(
            "HorovodRunner(np=0) is deprecated (reference README.md:"
            "57-61); using all available task slots."
        )
    if per_rank_kwargs is not None and np > 0 and len(per_rank_kwargs) != np:
        raise ValueError(
            f"per_rank_kwargs has {len(per_rank_kwargs)} entries for a "
            f"gang of {np}"
        )

    # Spark barrier-mode backend when a real Spark cluster is attached
    # (reference runner_base.py:54-61: "the 2nd spark job started by
    # HorovodRunner"). Tried BEFORE any local slot resolution: cluster
    # slots live on the EXECUTORS (reference runner_base.py:44-45), so
    # probing the driver machine's chips first would wrongly fail any
    # np that exceeds the driver's own count — a 1-core driver in
    # front of a 64-slot cluster is normal. per_rank_kwargs opts OUT:
    # the caller pre-sharded rank-private payloads for a process gang,
    # and the barrier job would silently drop them (the Spark
    # partition-resident path ships data per-partition instead).
    if np >= 0 and per_rank_kwargs is None:
        try:
            from sparkdl_tpu.horovod.spark_backend import maybe_launch_on_spark
        except ImportError:
            pass
        else:
            spark_result = maybe_launch_on_spark(
                np, main, kwargs, driver_log_verbosity
            )
            if spark_result is not None:
                return spark_result.value

    spec_placement = Placement.from_env(os.environ)
    num_workers, mode, total_slots = _resolve_num_workers(np, spec_placement)
    # Elastic relaunch (SPARKDL_TPU_GANG_RELAUNCH_NP): the supervisor
    # cleared this target through the reshard pre-flight and shipped it
    # in the restart context — the relaunched gang is RESIZED to it,
    # not just told about it. Cluster mode re-resolves so slot
    # accounting (and the np-exceeds-total fail-fast) follows the new
    # world; local mode spawns exactly that many subprocesses.
    from sparkdl_tpu.horovod.supervisor import (
        RELAUNCH_NP_ENV,
        record_attempt_world,
    )

    relaunch_np = int((extra_env or {}).get(RELAUNCH_NP_ENV) or 0)
    if relaunch_np and relaunch_np != num_workers:
        if mode == "local":
            num_workers = relaunch_np
        else:
            num_workers, mode, total_slots = _resolve_num_workers(
                relaunch_np, spec_placement)
        logger.info(
            "elastic relaunch: gang world resized to np=%d "
            "(%s mode)", num_workers, mode,
        )
    if per_rank_kwargs is not None and len(per_rank_kwargs) != num_workers:
        raise ValueError(
            f"per_rank_kwargs has {len(per_rank_kwargs)} entries for a "
            f"gang of {num_workers}"
        )
    record_attempt_world(num_workers)
    if controller is not None:
        # World-size transitions (shrink/grow/yield/reclaim) are
        # counted here, where the resolved size of the attempt is
        # known; a consumed resize plan is cleared.
        controller.note_attempt(num_workers)

    # Remote-transport availability is knowable NOW — before the slot
    # claim (which can wait minutes for busy slots) and before any
    # payload serialization. Fail-fast philosophy: a CLUSTER gang
    # whose RANKS land on other machines engages the remote transport
    # or dies here, typed. Silently Popen-ing every rank locally would
    # oversubscribe this host's chips while TPU_PROCESS_ADDRESSES
    # points at machines never contacted. Derived from the launched
    # ranks, not the whole spec: np=4 against "localhost:4,nodeB:4"
    # fills only localhost and needs no transport (and must keep the
    # control plane on loopback). LOCAL mode (np<=-2, "spawn -np
    # subprocesses on this host", reference runner_base.py:48-53) is
    # exempt by definition — a hosts spec there is the topology
    # SIMULATION rig (placement env without placement).
    gang_placement = spec_placement or Placement.single_host(num_workers)
    remote_hosts = [] if mode == "local" else sorted({
        gang_placement.host(r) for r in range(num_workers)
        if not is_local_host(gang_placement.host(r))
    })
    remote_shell = remote_python = None
    if remote_hosts:
        try:
            remote_shell = _resolve_remote_shell()
        except RemoteTransportError as e:
            raise RemoteTransportError(
                f"hosts spec places ranks on remote host(s) "
                f"{remote_hosts}, but remote exec is unavailable "
                f"({e}). Refusing to launch the whole gang on this "
                "host — that would oversubscribe its chips and "
                "point TPU_PROCESS_ADDRESSES at machines that were "
                "never contacted. Fix the transport or the "
                f"{HOSTS_ENV} spec."
            )
        remote_python = os.environ.get(REMOTE_PYTHON_ENV, sys.executable)

    # Cluster gangs on this host share a slot registry: wait while
    # another job's gang holds slots, launch when ours free up
    # (reference runner_base.py:56-58 — waiting is the contract;
    # np > total already failed fast above, using the same probe).
    # The registry tracks THIS machine's chips, so a hosts-spec gang
    # claims only its locally-placed ranks — remote ranks consume
    # remote slots, and claiming them here would starve concurrent
    # local gangs for capacity this job isn't using.
    # Local mode (np<-1) deliberately skips this: oversubscription is
    # allowed there. ONE try/finally owns every resource from here —
    # a leaked claim counts as busy for this driver's whole lifetime.
    # Gang health (same opt-in as telemetry): the detector consumes
    # HEARTBEAT frames on the control plane and declares stall/hang
    # verdicts; the monitor loop below acts on them — stack dumps from
    # stalled ranks, then a kind="hang" failure the supervisor
    # classifies as the transient HANG cause.
    detector = None
    statusz = None
    if telemetry is not None:
        from sparkdl_tpu.observe.health import HangDetector

        detector = HangDetector(num_workers)
        if alert_engine is not None:
            # The engine spans attempts (created in launch_gang); the
            # per-rank baselines/latches are rebuilt for THIS
            # attempt's world size — an elastic gang that shrank or
            # grew must not judge new ranks by a dead rank's history.
            alert_engine.set_world(num_workers, detector=detector)

    slot_claim = None
    if mode == "cluster":
        with observe.span("gang.slot_claim", cat="launch",
                          num_workers=num_workers):
            if spec_placement is not None:
                n_local = sum(
                    1 for r in range(num_workers)
                    if is_local_host(spec_placement.host(r))
                )
                local_total = sum(
                    s for h, s in spec_placement.hosts if is_local_host(h)
                )
                if n_local:
                    slot_claim = claim_slots(n_local, local_total)
            else:
                slot_claim = claim_slots(num_workers, total_slots)
    server = None
    procs = []
    boot_logs = []
    boot_paths = {}  # payload path -> staged secret+payload boot file
    try:
        if telemetry is not None:
            # Start INSIDE the resource-owning try so the finally's
            # close() covers every exit, including a failed spawn —
            # a leaked statusz thread would hold the port against the
            # supervisor's next attempt.
            from sparkdl_tpu.observe.statusz import maybe_start_statusz

            statusz = maybe_start_statusz(
                telemetry, detector=detector, num_workers=num_workers,
                alerts=alert_engine, elastic=controller,
                forensics=forensics)
            if statusz is not None:
                logger.info("statusz live at http://%s/statusz",
                            statusz.address)
        job_dir = tempfile.mkdtemp(prefix="sparkdl-tpu-job-")
        if telemetry is not None:
            # Flight-recorder recovery root: rank rings live in the
            # attempt's job dir, and the merged run dir must include
            # their tails even for ranks SIGKILLed mid-flush.
            telemetry.note_job_dir(job_dir)
        payload_paths = []
        for r in range(num_workers):
            rank_kwargs = dict(kwargs)
            if per_rank_kwargs is not None:
                rank_kwargs.update(per_rank_kwargs[r])
            payload = cloudpickle.dumps((main, rank_kwargs))
            if r == 0 and len(payload) > LARGE_PAYLOAD_BYTES:
                # Contract: pickling a large main slows job start
                # (reference runner_base.py:90-91).
                logger.warning(
                    "Pickled main + kwargs is %.1f MB; large closures make "
                    "HorovodRunner jobs slow to start. Move data loading "
                    "inside main().", len(payload) / 2**20,
                )
            path = os.path.join(job_dir, f"payload-{r}.pkl")
            with open(path, "wb") as f:
                f.write(payload)
            payload_paths.append(path)
            if per_rank_kwargs is None:
                # identical payload for everyone: write once, share
                payload_paths = [path] * num_workers
                break

        # Prebuild the native log transport once on the driver so
        # workers don't each pay (or race) the compile inside the gang
        # start timeout; workers then dlopen the cached .so.
        try:
            from sparkdl_tpu.native import load_ctrl_lib

            load_ctrl_lib()
        except Exception:  # pragma: no cover - never block launch on this
            pass

        # Local subprocess mode streams training stdout/stderr to the
        # driver unconditionally (reference README.md:44-47: "Training
        # stdout and stderr messages go to the notebook cell output");
        # cluster mode honors driver_log_verbosity (runner_base.py:62-72).
        effective_verbosity = (
            "all" if mode == "local" else driver_log_verbosity
        )
        platform = os.environ.get(WORKER_PLATFORM_ENV)
        server = ControlPlaneServer(
            num_workers,
            verbosity=effective_verbosity,
            log_path=os.path.join(job_dir, "job.log"),
            # Remote workers dial back in: bind beyond loopback and
            # advertise a routable address.
            bind_host="0.0.0.0" if remote_hosts else "127.0.0.1",
            telemetry=telemetry,
            health=detector,
        )
        if forensics is not None:
            # PROFILE_REQ frames go out through THIS attempt's control
            # plane; its PROFILE_DONE callback clears the per-rank
            # in-flight latch.
            forensics.bind_server(server)
        # jax.distributed's coordinator lives in RANK 0, so the
        # rendezvous address must name rank 0's host, reachable from
        # every worker. Operators behind NAT/DNS oddities can pin it.
        coordinator = os.environ.get(COORD_ENV)
        if not coordinator:
            host0 = gang_placement.host(0)
            if not remote_hosts:
                # all ranks on this machine (incl. local-mode
                # simulation of multi-host specs): loopback rendezvous
                coordinator = f"127.0.0.1:{_free_port()}"
            elif is_local_host(host0):
                coordinator = (
                    f"{server.address.rsplit(':', 1)[0]}:{_free_port()}")
            else:
                # Can't probe a free port on a remote machine. A FIXED
                # well-known port would collide the moment two gangs'
                # rank 0 land on the same host, so derive the default
                # from this job's unique job_dir — stable for the gang
                # (every rank computes the rendezvous from the same
                # coordinator string), near-unique across jobs.
                # Operators pin it via env when a firewall needs one
                # known port.
                port = os.environ.get(COORD_PORT_ENV)
                if not port:
                    import hashlib

                    digest = hashlib.sha256(
                        job_dir.encode()).digest()
                    port = str(49152 + int.from_bytes(
                        digest[:2], "big") % 16384)
                coordinator = f"{host0}:{port}"

        logger.info(
            "Launching HorovodRunner gang: %d worker(s), mode=%s, job_dir=%s",
            num_workers, mode, job_dir,
        )
        compile_cache = os.environ.get(COMPILE_CACHE_DIR_ENV)
        if compile_cache:
            # Relaunches of a preempted gang warm-start from here: the
            # env rides every worker env (and every supervised
            # attempt), so the replacement rank deserializes instead
            # of recompiling.
            logger.info(
                "warm-start compile cache for this gang: %s",
                compile_cache,
            )
        observe.instant("gang.spawn", cat="launch",
                        num_workers=num_workers, mode=mode,
                        job_dir=job_dir,
                        compile_cache=compile_cache or "")
        # Autotuned perf profile pre-flight (ISSUE 12): resolve the
        # committed per-device-kind profile and ship its knobs in
        # every worker env, UNDER the operator (an env var already set
        # in the driver's environment is never overridden). Applied
        # here — inside the function the supervisor retries — so every
        # relaunched attempt re-inherits the profile through the same
        # env-forwarding path as the restart context; a degraded or
        # malformed profile applies nothing and says so in the log.
        from sparkdl_tpu.perf.profile import preflight_env

        profile_env = preflight_env(os.environ)
        for r in range(num_workers):
            env = _worker_env(
                os.environ, rank=r, size=num_workers,
                coordinator=coordinator, control_addr=server.address,
                control_secret=server.secret,
                payload_path=payload_paths[r], job_dir=job_dir,
                platform=platform, placement=gang_placement,
            )
            for pk, pv in profile_env.items():
                env.setdefault(pk, pv)
            if extra_env:
                # Supervisor restart context (attempt number, resume
                # step) — merged per worker, never into the driver's
                # own os.environ.
                env.update(extra_env)
            # Boot-phase output (before the worker installs its log tee
            # — e.g. import errors) lands in the same per-rank log file
            # via an O_APPEND handle, so nothing is ever lost.
            boot_log = open(
                os.path.join(job_dir, f"rank-{r}.log"), "ab", buffering=0
            )
            boot_logs.append(boot_log)
            host_r = gang_placement.host(r)
            # remote_hosts is [] in local mode (simulation rig): every
            # rank spawns locally no matter what the spec names
            if host_r not in remote_hosts:
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "sparkdl_tpu.horovod._worker"],
                    env=env,
                    stdout=boot_log,
                    stderr=subprocess.STDOUT,
                ))
            else:
                cmd = _remote_worker_cmd(
                    remote_shell, host_r, env, os.environ, remote_python
                )
                # Boot stream: secret line + payload bytes, staged in
                # a driver-local file so the kernel (not this loop)
                # streams it — a PIPE write would block on large
                # payloads until the remote end drains. Staged ONCE
                # per unique payload (a shared payload re-copied per
                # rank would write rank-count × GBs); each rank's open
                # gets its own fd/offset. Unlinked in the finally:
                # job_dir outlives the job for postmortems, the secret
                # must not outlive launch.
                boot_path = boot_paths.get(payload_paths[r])
                if boot_path is None:
                    import shutil

                    boot_path = os.path.join(job_dir, f"boot-{r}.bin")
                    with open(boot_path, "wb") as bf:
                        bf.write(server.secret.encode() + b"\n")
                        with open(payload_paths[r], "rb") as pf:
                            shutil.copyfileobj(pf, bf)
                    boot_paths[payload_paths[r]] = boot_path
                with open(boot_path, "rb") as boot_in:
                    procs.append(subprocess.Popen(
                        cmd,
                        stdin=boot_in,
                        stdout=boot_log,
                        stderr=subprocess.STDOUT,
                    ))

        # The spawned children hold their own fds on the boot streams:
        # unlink the secret-bearing files NOW, before the (possibly
        # hours-long) job runs — the finally's unlink is only the
        # backstop for exceptions inside the spawn loop itself.
        for bp in boot_paths.values():
            try:
                os.unlink(bp)
            except OSError:
                pass
        boot_paths.clear()

        def _fail(reason, exit_codes=None, kind="unknown"):
            excs = server.exceptions
            detail = "\n".join(
                f"--- rank {r} ---\n{tb}" for r, tb in sorted(excs.items())
            )
            if not detail:
                bad = (
                    [r for r, c in enumerate(exit_codes) if c]
                    if exit_codes is not None
                    else range(num_workers)
                )
                detail = "\n".join(
                    f"--- rank {r} log tail ---\n"
                    + _tail(os.path.join(job_dir, f"rank-{r}.log"))
                    for r in bad
                )
            # GangFailure (a RuntimeError) carries the evidence the
            # supervisor's transient-vs-permanent classifier reads:
            # per-rank exit codes (negative = signal = what preemption
            # looks like) and EXC tracebacks.
            raise GangFailure(
                f"{reason}\n{detail}", kind=kind,
                exit_codes=list(exit_codes or []), exceptions=excs,
            )

        # Gang rendezvous with fail-fast (reference runner_base.py:54-58):
        # abort immediately if any worker dies before READY, not after
        # the full start timeout.
        timeout = float(os.environ.get(START_TIMEOUT_ENV, DEFAULT_START_TIMEOUT))
        deadline = time.monotonic() + timeout
        # The span closes however the loop exits, so an aborted
        # rendezvous still shows its (partial) duration on the gang
        # timeline next to the failure instants.
        with observe.span("gang.rendezvous", cat="launch",
                          num_workers=num_workers):
            while server.ready_count() < num_workers:
                dead = [
                    (r, p.poll()) for r, p in enumerate(procs)
                    if p.poll() is not None and p.poll() != 0
                ]
                if dead:
                    time.sleep(0.5)  # let EXC frames drain
                    _fail(
                        "HorovodRunner gang failed to start: worker(s) "
                        f"{[r for r, _ in dead]} exited during rendezvous "
                        f"(codes {[c for _, c in dead]}). Worker logs: {job_dir}",
                        [p.poll() or 0 for p in procs], kind="start_failure",
                    )
                if time.monotonic() > deadline:
                    _fail(
                        f"HorovodRunner gang failed to start: only "
                        f"{server.ready_count()}/{num_workers} workers reached "
                        f"the rendezvous within {timeout:.0f}s (fail-fast, "
                        f"reference runner_base.py:54-58). Worker logs: {job_dir}",
                        kind="rendezvous_timeout",
                    )
                time.sleep(0.05)
        observe.instant("gang.ready", cat="launch",
                        num_workers=num_workers)

        # Monitor the running gang. If one rank dies while others are
        # blocked in a collective (which has no timeout on ICI), give the
        # survivors a grace period, then kill them — a failed gang must
        # not wedge the pod (SURVEY.md §7 hard part #3).
        grace = float(os.environ.get("SPARKDL_TPU_ABORT_GRACE", "30"))
        first_death = None
        while any(p.poll() is None for p in procs):
            codes = [p.poll() for p in procs]
            if alert_engine is not None:
                # Streaming SLO rules over the live telemetry window
                # (throttled internally to its check cadence). Firings
                # land as alert.* instants + gang_alerts_total here;
                # the merged report is attached to the run dir in
                # launch_gang's finally. Perf-rule firings also feed
                # the forensics hook: with SPARKDL_TPU_PROFILE_ON_ALERT
                # set, the offending rank is told to capture a profile
                # window and the baseline-vs-regressed diff lands in
                # regression_report.json.
                fired = alert_engine.poll()
                if forensics is not None and fired:
                    forensics.on_alerts(fired)
            if controller is not None and first_death is None:
                # Elastic tick (throttled internally): capacity watch,
                # debounce, arbiter. A non-None return means a planned
                # resize reached its checkpoint boundary — recycle the
                # gang NOW; the supervisor classifies the typed
                # elastic_resize kind as a zero-budget, zero-backoff
                # relaunch at the controller's target np.
                resize = controller.poll()
                if resize is not None:
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    for p in procs:
                        p.wait()
                    err = GangFailure(
                        f"elastic resize: {resize['direction']} to "
                        f"np={resize['target_np']} "
                        f"({resize['reason']}); resuming from step "
                        f"{resize.get('resume_step')}",
                        kind="elastic_resize",
                        exit_codes=[p.poll() or 0 for p in procs],
                    )
                    err.elastic_direction = resize["direction"]
                    err.elastic_target = resize["target_np"]
                    raise err
            if detector is not None and first_death is None:
                report = detector.poll()
                for r in report["new_stalled"]:
                    # Diagnose while the evidence is live: the stalled
                    # rank's watchdog thread answers with faulthandler
                    # stacks even though its training thread is wedged.
                    server.request_dump(r, reason="stall")
                if report["hang"]:
                    verdict = report["hang"]
                    stalled = detector.stalled_ranks
                    # Final dump sweep over every rank still holding a
                    # control socket (peers' stacks show WHICH
                    # collective the gang is wedged in), then a
                    # bounded wait for the stalled ranks' answers —
                    # the kill below destroys the evidence.
                    for r in range(num_workers):
                        server.request_dump(r, reason=f"hang:{verdict}")
                    dump_grace = float(os.environ.get(
                        "SPARKDL_TPU_DUMP_GRACE", "10"))
                    dump_deadline = time.monotonic() + dump_grace
                    while time.monotonic() < dump_deadline and not all(
                            server.stack_dumps(r) for r in stalled):
                        time.sleep(0.1)
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    for p in procs:
                        p.wait()
                    raise GangFailure(
                        "HorovodRunner gang hung: beats continued but "
                        f"no rank made progress for "
                        f"{detector.stall_s:.0f}s "
                        f"(verdict: {verdict}; stalled rank(s) "
                        f"{stalled}).\n{detector.describe()}\n"
                        f"Stack dumps captured from rank(s) "
                        f"{sorted(server.stack_dumps())}. "
                        f"Worker logs: {job_dir}",
                        kind="hang", hang_verdict=verdict,
                        exit_codes=[p.poll() or 0 for p in procs],
                        exceptions=server.exceptions,
                    )
            if any(c not in (None, 0) for c in codes):
                if first_death is None:
                    first_death = time.monotonic()
                elif time.monotonic() - first_death > grace:
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    _fail(
                        "HorovodRunner job failed: worker(s) "
                        f"{[r for r, c in enumerate(codes) if c not in (None, 0)]} "
                        f"died; surviving ranks were killed after a "
                        f"{grace:.0f}s grace period to avoid a wedged "
                        f"collective.", [c or 0 for c in codes],
                        kind="worker_death",
                    )
            time.sleep(0.1)
        exit_codes = [p.wait() for p in procs]
        if any(exit_codes):
            _fail(
                f"HorovodRunner job failed (exit codes {exit_codes}).",
                exit_codes, kind="worker_death",
            )

        # Drain the control plane: all workers have exited, so their
        # connections are at EOF — process every buffered frame before
        # returning (no tail-of-job log lines lost).
        server.wait_drained(5.0)

        result_bytes = None
        deadline = time.monotonic() + 30
        while result_bytes is None and time.monotonic() < deadline:
            result_bytes = server.result_bytes
            if result_bytes is None:
                time.sleep(0.05)
        if result_bytes is None:
            # Workers all exited 0 but the RESULT frame never arrived:
            # a control-plane delivery failure, classified transient
            # (a relaunch re-runs the job and re-ships the result).
            raise GangFailure(
                "HorovodRunner job finished but rank 0 returned no result "
                f"over the control plane. Worker logs: {job_dir}",
                kind="no_result",
            )
        return cloudpickle.loads(result_bytes)
    finally:
        if statusz is not None:
            # Stop serving BEFORE the teardown below: a scrape racing
            # the kill path would read half-dismantled state.
            statusz.close()
        if detector is not None and telemetry is not None:
            # However this attempt ended, its detector state (per-rank
            # last beat/step/collective, any verdicts) goes into the
            # merged health.json — the doctor's primary evidence.
            telemetry.add_health_summary(detector.summary())
        for bp in boot_paths.values():
            # spawned children hold their own fds; the secret-bearing
            # file must not persist in the postmortem-kept job_dir
            try:
                os.unlink(bp)
            except OSError:
                pass
        for p in procs:
            if p.poll() is None:
                p.kill()  # a failed gang must not wedge the pod
        for f in boot_logs:
            try:
                f.close()
            except OSError:
                pass
        if server is not None:
            server.close()
        if slot_claim is not None:
            slot_claim.release()
