"""Spark barrier-mode cluster backend for HorovodRunner(np>0).

Implements the reference's documented DBR behavior (``runner_base.py:
54-61``): the gang is "the 2nd spark job started by HorovodRunner",
launched with barrier scheduling so all np tasks start together, one
task per slot, fail-fast when slots are unavailable. Inside each barrier
task we run the same worker bootstrap as the local backend
(:mod:`sparkdl_tpu.horovod._worker` logic), with the coordinator address
elected from the barrier task infos — rank 0's host — and
``jax.distributed`` providing rendezvous over DCN.

This module imports pyspark at module scope on purpose: the launcher
imports it inside ``try: ... except ImportError`` and falls back to the
local-process gang when no Spark is attached (the common case on a bare
TPU VM and in CI — pyspark is an optional dependency, matching the
reference's zero-install_requires packaging, reference ``setup.py:41``).
"""

import os

from pyspark.sql import SparkSession
from pyspark import BarrierTaskContext


class SparkGangResult:
    def __init__(self, value):
        self.value = value


def _barrier_main(payload_bytes, verbosity, control_addr, control_secret,
                  worker_platform=None, pass_partition=False):
    """Runs inside each barrier task (executor-side).

    ``pass_partition=True``: the task's partition rows are collected
    into a pandas frame EXECUTOR-SIDE and passed to ``main`` as its
    first positional arg — the partition-resident estimator data path
    (reference ``xgboost.py:58-80``: each worker trains on its own
    partition; the driver never materializes the dataset)."""

    def run_partition(part_iter):
        import os
        import socket

        import cloudpickle

        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()
        size = len(infos)
        # Coordinator election: rank 0 binds a free port on its own host
        # and the address is gossiped to the gang via the barrier's
        # allGather — no hardcoded ports, no loopback assumptions.
        if rank == 0:
            from sparkdl_tpu.horovod.control_plane import routable_host_ip

            probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            probe.bind(("", 0))
            port = probe.getsockname()[1]
            probe.close()
            coord = f"{routable_host_ip()}:{port}"
        else:
            coord = ""
        coords = ctx.allGather(coord)
        os.environ["SPARKDL_TPU_RANK"] = str(rank)
        os.environ["SPARKDL_TPU_SIZE"] = str(size)
        os.environ["SPARKDL_TPU_COORDINATOR"] = coords[0]
        if control_addr:
            os.environ["SPARKDL_TPU_CONTROL_ADDR"] = control_addr
            os.environ["SPARKDL_TPU_CONTROL_SECRET"] = control_secret

        # Multi-host topology from the barrier task infos: local rank =
        # position among this host's tasks (reference runner_base.py:
        # 44-45 — slots live on task NODES), plus TPU pod-slice env
        # when the executors hold chips.
        from sparkdl_tpu.horovod.topology import placement_from_task_hosts

        hosts = [i.address.rsplit(":", 1)[0] for i in infos]
        placement = placement_from_task_hosts(hosts)
        # The DRIVER decides the platform (its env ships through this
        # closure) — the executor's own env says nothing, and assuming
        # TPU on a CPU cluster would inject pod env (and reject
        # non-uniform task layouts) where none applies.
        on_tpu = worker_platform == "tpu"
        # Force-assign: pyspark reuses python workers across jobs
        # (spark.python.worker.reuse), so a setdefault would keep the
        # PREVIOUS job's rank-specific TPU identity.
        os.environ.update(placement.env_for_rank(rank, tpu=on_tpu))
        if worker_platform:
            os.environ["SPARKDL_TPU_FORCE_PLATFORM"] = worker_platform

        ctx.barrier()  # gang start: all together (runner_base.py:54-55)

        # Same observability bootstrap as the local worker: stdout/
        # stderr tee'd to the driver per driver_log_verbosity, EXC
        # frames, driver watchdog (reference runner_base.py:62-72 — a
        # barrier worker's failure must surface as a rank-tagged
        # traceback on the driver, not an opaque Spark task error).
        from sparkdl_tpu.horovod._worker import worker_io

        if pass_partition:
            import pandas as pd

            rows = list(part_iter)
            partition_pdf = (
                pd.DataFrame([r.asDict() for r in rows]) if rows else None
            )

        out = []
        with worker_io(rank) as client:
            import sparkdl_tpu.hvd as hvd

            hvd.init()
            if client is not None:
                client.send_ready()
            user_main, kwargs = cloudpickle.loads(payload_bytes)
            if pass_partition:
                result = user_main(partition_pdf, **kwargs)
            else:
                result = user_main(**kwargs)
            if hvd.rank() == 0:
                out.append(cloudpickle.dumps(result))
        return out

    return run_partition


def _check_slots(sc, num_workers):
    # Fail fast if the cluster cannot host the gang (runner_base.py:56-58).
    # (Busy-slot WAITING is Spark's own scheduler behavior: a barrier
    # job with free total capacity queues until slots drain.)
    total_slots = int(sc.defaultParallelism)
    if num_workers > total_slots:
        from sparkdl_tpu.horovod.launcher import SlotExhaustionError

        raise SlotExhaustionError(
            f"HorovodRunner requested np={num_workers} but the cluster has "
            f"only {total_slots} task slots; failing fast."
        )


def _run_barrier_job(barrier_rdd, num_workers, main, kwargs,
                     driver_log_verbosity, pass_partition=False):
    """Shared barrier-job machinery: control plane, payload shipping,
    rank-tagged failure surfacing, rank-0 result return."""
    import cloudpickle

    from sparkdl_tpu.horovod.control_plane import ControlPlaneServer

    import tempfile

    job_dir = tempfile.mkdtemp(prefix="sparkdl-tpu-spark-job-")
    # Bind on all interfaces and advertise a routable driver address —
    # executors on other hosts must be able to reach log_to_driver's
    # channel (reference sparkdl/horovod/__init__.py:20-25).
    server = ControlPlaneServer(
        num_workers, verbosity=driver_log_verbosity, bind_host="0.0.0.0",
        log_path=os.path.join(job_dir, "job.log"),
    )
    try:
        payload = cloudpickle.dumps((main, kwargs))
        try:
            pickled = barrier_rdd.mapPartitions(
                _barrier_main(payload, driver_log_verbosity, server.address,
                              server.secret,
                              os.environ.get("SPARKDL_TPU_WORKER_PLATFORM"),
                              pass_partition=pass_partition)
            ).collect()
        except Exception as e:
            # Surface the rank-tagged tracebacks the workers shipped
            # over the control plane instead of Spark's opaque task
            # failure (reference runner_base.py:62-72).
            server.wait_drained(5.0)
            excs = server.exceptions
            detail = "\n".join(
                f"--- rank {r} ---\n{tb}" for r, tb in sorted(excs.items())
            )
            if detail:
                raise RuntimeError(
                    f"HorovodRunner Spark job failed:\n{detail}\n"
                    f"Merged job log: {job_dir}/job.log"
                ) from e
            raise
        if not pickled:
            raise RuntimeError("Spark barrier job returned no rank-0 result")
        return SparkGangResult(cloudpickle.loads(pickled[0]))
    finally:
        server.close()


def maybe_launch_on_spark(num_workers, main, kwargs, driver_log_verbosity):
    """Launch the gang as a Spark barrier job; returns None when no
    active SparkSession exists (caller falls back to the local gang).
    ``num_workers == 0`` (deprecated np=0) means all cluster slots —
    resolved HERE against the cluster, not the driver machine."""
    spark = SparkSession.getActiveSession()
    if spark is None:
        return None
    sc = spark.sparkContext
    if num_workers == 0:
        num_workers = int(sc.defaultParallelism)
    _check_slots(sc, num_workers)
    rdd = sc.parallelize(range(num_workers), num_workers).barrier()
    return _run_barrier_job(rdd, num_workers, main, kwargs,
                            driver_log_verbosity)


def maybe_transform_on_spark(dataset, get_broadcast, extra_cols):
    """Executor-side model inference via ``mapInPandas``: pandas
    batches flow over Arrow straight into the model's pandas->pandas
    closure — no Row pickling, no per-cell dtype coercion (Arrow +
    the EXPLICIT output schema handle numpy dtypes and nulls), and no
    schema-inference job running inference on a sampled partition.
    Prediction is embarrassingly parallel, so unlike training this
    needs no gang, no coordinator, and tolerates Spark's per-task
    retries. The driver never materializes the dataset (reference
    ``xgboost.py:81-97`` — the large-data contract cuts both ways: a
    fit that never collects is defeated by a transform that does).

    ``get_broadcast(spark)``: returns a Broadcast of the CLOUDPICKLED
    closure (bytes — Spark's broadcast serializer is plain pickle,
    which the model's Param lambdas defeat) — owned by the CALLER
    (the model), which caches it so repeated transforms reuse one
    executor-resident model copy instead of leaking one per call.
    ``extra_cols``: ``[(name, "double" | "array<double>"), ...]``
    appended by the closure.

    Returns None when no active SparkSession exists (caller falls back
    to driver-side pandas)."""
    spark = SparkSession.getActiveSession()
    if spark is None:
        return None
    # Arrow (mapInPandas' transport) cannot convert UDT columns —
    # pyspark.ml Vector features among them, at ANY nesting depth
    # (array<Vector>, struct fields...). The driver-side pandas path
    # handles those (extract_matrix understands Vector cells), so fall
    # back rather than fail at action time.
    def _has_udt(dt):
        if type(dt).__name__.endswith("UDT"):
            return True
        if hasattr(dt, "elementType"):
            return _has_udt(dt.elementType)
        if hasattr(dt, "fields"):
            return any(_has_udt(f.dataType) for f in dt.fields)
        return False

    if any(_has_udt(f.dataType) for f in dataset.schema.fields):
        return None
    from pyspark.sql.types import (
        ArrayType,
        DoubleType,
        StructField,
        StructType,
    )

    # Input columns colliding with the prediction columns are REPLACED
    # (the pandas path's overwrite semantics) — duplicated field names
    # would make every select on them ambiguous.
    extra_names = {name for name, _ in extra_cols}
    schema = StructType(
        [f for f in dataset.schema.fields if f.name not in extra_names]
        + [
            StructField(
                name,
                ArrayType(DoubleType()) if typ == "array<double>"
                else DoubleType(),
                True,
            )
            for name, typ in extra_cols
        ])
    names = [f.name for f in schema.fields]
    bc = get_broadcast(spark)

    def run(batches):
        import cloudpickle as _cp

        fn = _cp.loads(bc.value)  # once per partition task
        for pdf in batches:
            yield fn(pdf)[names]

    return dataset.mapInPandas(run, schema)


def maybe_launch_estimator_on_spark(dataset, num_workers, main, kwargs,
                                    driver_log_verbosity,
                                    force_repartition=False):
    """Partition-resident estimator training (reference
    ``xgboost.py:58-80``): the DataFrame is repartitioned to one
    partition per worker when needed, and each barrier task extracts
    ITS OWN partition's rows executor-side — the driver never
    materializes the dataset (the round-2 path collected the full
    frame with toPandas, defeating 'exceptionally large dataset'
    workflows, reference ``xgboost.py:81-97``).

    Returns None when no active SparkSession exists."""
    spark = SparkSession.getActiveSession()
    if spark is None:
        return None
    sc = spark.sparkContext
    _check_slots(sc, num_workers)
    if force_repartition or dataset.rdd.getNumPartitions() != num_workers:
        # force_repartition also serves its contract role: reshuffle
        # even when the partition count already matches (reference
        # xgboost.py:72-80).
        dataset = dataset.repartition(num_workers)
    rdd = dataset.rdd.barrier()
    return _run_barrier_job(rdd, num_workers, main, kwargs,
                            driver_log_verbosity, pass_partition=True)
