"""HorovodRunner: the gang launcher for distributed training functions.

API parity with the reference ``sparkdl/horovod/runner_base.py:39-103``:
the constructor is keyword-only ``(*, np, driver_log_verbosity=
"log_callback_only")`` and ``run(main, **kwargs)`` returns ``main``'s
return value. The reference only implements local mode (``run`` calls
``main`` in-process, reference ``runner_base.py:97-103``) and documents
the distributed behavior in docstrings; here every documented mode is
implemented for real, TPU-native:

- ``np == -1``  : run ``main(**kwargs)`` in the current process (exact
  parity with the reference OSS behavior, which its tests lock in:
  reference ``tests/horovod/runner_base_test.py:44-59``).
- ``np <= -2``  : spawn ``-np`` subprocesses on this host (reference
  contract ``runner_base.py:48-53``), gang-started together, each
  ``jax.distributed.initialize``'d against a local coordinator; on TPU
  hosts each process binds its own chip(s), on CPU each gets one
  virtual device.
- ``np > 0``    : launch ``np`` tasks on the cluster "starting all
  together" with fail-fast slot checking (reference contract
  ``runner_base.py:54-58``). One task <-> one TPU chip replaces the
  reference's one task <-> one GPU (``runner_base.py:44-45``).
- ``np == 0``   : deprecated "use all task slots" mode (reference
  ``README.md:57-61``); resolves to all available slots with a warning.

The worker→driver log routing policy follows the contract at reference
``runner_base.py:62-72``: all workers' logs are merged into a single
driver-side job log; ``driver_log_verbosity="all"`` additionally streams
every line to the driver's stdout, while the default
``"log_callback_only"`` surfaces only messages sent through
``sparkdl_tpu.horovod.log_to_driver`` (and callbacks built on it).
The return value of rank 0's ``main`` is shipped back to the driver via
cloudpickle (reference contract ``runner_base.py:93-95``).

Fault tolerance: gangs remain fail-fast per the reference contract,
but the launch is wrapped by a supervisor
(:mod:`sparkdl_tpu.horovod.supervisor`) that classifies failures and
— opted in via env so the locked ``run`` signature stays untouched —
relaunches *transient* ones (preemption-style signal deaths,
rendezvous timeouts, control-plane resets) under exponential backoff,
shipping a restart context that checkpoint-aware mains read via
:func:`sparkdl_tpu.horovod.restart_context`. See
``docs/fault_tolerance.rst``.
"""

import logging

_LOG_VERBOSITY_VALUES = ("all", "log_callback_only")


class HorovodRunner:
    """HorovodRunner runs distributed deep learning training jobs.

    The open-source reference runs the training function locally and
    defers distributed launching to Databricks Runtime (reference
    ``runner_base.py:32-37``); this implementation launches real gangs
    of TPU-bound worker processes using ``jax.distributed`` for
    rendezvous and XLA collectives over ICI/DCN for communication.
    """

    def __init__(self, *, np, driver_log_verbosity="log_callback_only"):
        """
        :param np: number of parallel processes to use for the Horovod job.
            This argument only takes effect on Databricks Runtime in the
            reference; here it is honored everywhere:

            - If np >= 0, launch a gang of np cluster tasks, each bound
              to one TPU chip (one task slot <-> one chip, replacing the
              reference's GPU binding, reference ``runner_base.py:44-45``).
              The tasks start all together; if np is greater than the
              total number of task slots, the job fails fast (reference
              ``runner_base.py:54-58``). np = 0 (use all

              slots) is deprecated (reference ``README.md:57-61``).
            - If np < 0, spawn ``-np`` subprocesses on the driver node
              (reference ``runner_base.py:48-53``). np = -1 runs
              ``main`` in the current process, which is the mode the
              reference's own unit tests lock in (reference
              ``tests/horovod/runner_base_test.py:44-59``).

        :param driver_log_verbosity: driver log verbosity for CLUSTER
            jobs (np >= 0): "all" streams every worker's logs to the
            driver in real time (may be noisy during training,
            reference ``runner_base.py:65-68``); the default
            "log_callback_only" surfaces only logs sent via
            :func:`sparkdl_tpu.horovod.log_to_driver` and callbacks
            built on it (reference ``runner_base.py:68-72``). Local
            subprocess mode (np < 0) always streams training
            stdout/stderr to the driver output (reference
            ``README.md:44-47``). In every mode the full merged worker
            logs are written to a job log file (reference
            ``runner_base.py:62-64``).
        """
        if not isinstance(np, int) or isinstance(np, bool):
            raise TypeError(
                f"HorovodRunner np must be an int, got {type(np).__name__}: {np!r}"
            )
        if driver_log_verbosity not in _LOG_VERBOSITY_VALUES:
            raise ValueError(
                "driver_log_verbosity must be one of "
                f"{_LOG_VERBOSITY_VALUES}, got {driver_log_verbosity!r}"
            )
        self.num_processor = np
        self.driver_log_verbosity = driver_log_verbosity

    def run(self, main, **kwargs):
        """Runs a Horovod training job invoking main(**kwargs).

        The main function and the keyword arguments are serialized using
        cloudpickle and distributed to the gang's workers (reference
        contract ``runner_base.py:82-83``); pickling a large closure
        makes the job slow to start (reference ``runner_base.py:90-91``),
        so change global state inside ``main`` rather than capturing
        large objects.

        :return: return value of rank 0's ``main`` (shipped back to the
            driver with cloudpickle, reference ``runner_base.py:93-95``);
            in-process for np = -1 (reference ``runner_base.py:103``).

        Retry policy (env-driven; the signature above is locked to the
        reference, so the knobs ride the environment — see
        ``docs/fault_tolerance.rst`` for the full contract):

        - ``SPARKDL_TPU_GANG_MAX_RETRIES=N`` relaunches the gang up to
          N times when the failure classifies as transient (a rank
          killed by a signal — what preemption looks like — a
          rendezvous timeout, a control-plane reset). User-code
          exceptions and slot errors are never retried.
        - ``SPARKDL_TPU_GANG_RESUME_DIR=<dir>`` makes each relaunch
          ship the latest committed
          :class:`~sparkdl_tpu.utils.checkpoint.TrainCheckpointer`
          step from ``<dir>``; ``main`` reads it via
          :func:`sparkdl_tpu.horovod.restart_context` and resumes
          instead of restarting from step 0.
        - ``SPARKDL_TPU_GANG_BACKOFF_BASE/_FACTOR/_MAX/_JITTER``
          shape the exponential backoff between relaunches.
        - ``SPARKDL_TPU_PREFLIGHT_LINT=1`` statically lints the
          payload, ``main``'s live captures, and any train step
          registered via
          :func:`sparkdl_tpu.analysis.register_preflight` on the
          driver; ERROR-severity findings raise
          :class:`sparkdl_tpu.analysis.PreflightLintError` before any
          worker process is spawned (see ``docs/analysis.rst``).
        """
        np_arg = self.num_processor
        logger = logging.getLogger("HorovodRunner")
        if np_arg == -1:
            # Same opt-in pre-flight as the gang path (the local mode
            # is where users iterate before paying for chips — catch
            # the graph bug here, not on the pod).
            from sparkdl_tpu.analysis.preflight import preflight_lint

            preflight_lint(main, kwargs)
            logger.warning(
                "HorovodRunner is running in local mode (np=-1): main() is "
                "invoked in the current process with a single worker. Use "
                "np<=-2 for a local multi-process gang or np>0 for a "
                "cluster gang."
            )
            from sparkdl_tpu.hvd import _state as hvd_state

            with hvd_state.local_mode():
                return main(**kwargs)
        # All other modes launch a real gang of worker processes.
        from sparkdl_tpu.horovod.launcher import launch_gang

        return launch_gang(
            np=np_arg,
            main=main,
            kwargs=kwargs,
            driver_log_verbosity=self.driver_log_verbosity,
        )
