"""Worker-process bootstrap for HorovodRunner gangs.

Executed as ``python -m sparkdl_tpu.horovod._worker`` by the launcher.
Reconstructs the distributed contract the reference documents but never
implements (reference ``runner_base.py:54-61``): join the gang
rendezvous, bind the device, deserialize the user ``main`` (cloudpickle,
reference ``runner_base.py:82-83``), run it, and ship rank 0's return
value back to the driver (reference ``runner_base.py:93-95``).

Log routing: this process's stdout/stderr are tee'd — every line goes to
a per-rank file in the job dir AND over the control plane to the driver,
which merges all ranks into the job log (reference ``runner_base.py:
62-72``).
"""

import contextlib
import io
import os
import sys
import traceback


class _NullFile:
    """Stand-in local log for environments without a job dir (Spark
    barrier tasks tee straight to the control plane)."""

    def write(self, s):
        return len(s)

    def flush(self):
        pass

    def close(self):
        pass


class _TeeStream(io.TextIOBase):
    """Line-buffering tee: forwards complete lines to the control plane
    and writes through to a local per-rank log file."""

    def __init__(self, stream_name, local_file, client):
        self.stream_name = stream_name
        self.local_file = local_file
        self.client = client
        self._buf = ""

    def write(self, s):
        if not isinstance(s, str):
            s = s.decode("utf-8", "replace")
        self.local_file.write(s)
        self._buf += s
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            if self.client is not None:
                self.client.send_log(self.stream_name, line)
        return len(s)

    def flush(self):
        self.local_file.flush()
        if self._buf:
            if self.client is not None:
                self.client.send_log(self.stream_name, self._buf)
            self._buf = ""

    @property
    def closed(self):
        return False

    def writable(self):
        return True


def _set_parent_death_signal():
    """Linux second line of defense: SIGTERM this worker if its parent
    (the launcher) dies before the watchdog notices."""
    try:
        import ctypes
        import signal

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGTERM)
    except OSError:
        pass


@contextlib.contextmanager
def worker_io(rank, local_log_path=None):
    """The worker observability bootstrap, shared by the local gang
    worker and Spark barrier tasks: control-plane client + driver
    watchdog, parent-death signal, stdout/stderr tee to the driver (so
    ``driver_log_verbosity`` works in EVERY backend, reference
    ``runner_base.py:62-72``), EXC frames on failure, BYE on exit.

    Yields the control-plane client (None outside a job). Exceptions
    propagate to the caller after their traceback has been teed and
    shipped as an EXC frame."""
    from sparkdl_tpu import observe
    from sparkdl_tpu.horovod.control_plane import get_worker_client

    client = get_worker_client()
    if client is not None:
        # Fail-fast failure detection in BOTH directions: the launcher
        # reaps dead workers; this reaps workers whose DRIVER died
        # (even via SIGKILL) so orphans never pin chips or leases —
        # and the same watchdog thread answers the driver's
        # hang-diagnosis DUMP_REQ frames with faulthandler stacks.
        client.start_driver_watchdog()
    heartbeat = None
    flightrec = None
    capture = None
    if client is not None and observe.enabled():
        # Telemetry transport: periodic batched flushes of this
        # worker's metric snapshot + timeline events over the control
        # plane (TELEMETRY frames), merged gang-wide on the driver.
        observe.set_sink(client.send_telemetry)
        observe.start_flusher()
        # Flight recorder: mirror every timeline event into an
        # mmap-backed ring in the job dir so the tail survives a
        # SIGKILL between flushes (the driver recovers it into the
        # merged run dir). Job-dir-less backends (Spark barrier
        # tasks) skip it — there is no shared dir to recover from.
        job_dir = os.environ.get("SPARKDL_TPU_JOB_DIR")
        if job_dir:
            from sparkdl_tpu.observe.flightrec import (
                FlightRecorder,
                ring_path,
            )

            try:
                flightrec = FlightRecorder(ring_path(job_dir, rank))
                observe.set_flight_recorder(flightrec)
            except OSError:
                flightrec = None  # unwritable dir: telemetry still works
        # Gang health: liveness beacons on the guaranteed control
        # socket — they keep flowing while the training thread is
        # wedged, which is what lets the driver tell a hang from a
        # long step (sparkdl_tpu.observe.health).
        from sparkdl_tpu.observe.health import HeartbeatSender

        heartbeat = HeartbeatSender(client, rank)
        heartbeat.start()
        # Memory accounting: the low-rate sampler keeps the beacon's
        # mem field fresh (category gauges, host RSS, unattributed
        # residual) — behind the same latch, so no env means no
        # thread (sparkdl_tpu.observe.mem).
        from sparkdl_tpu.observe import mem

        mem.maybe_start_sampler()
        # Perf forensics: answer the driver's PROFILE_REQ frames (and
        # the fixed-step self-trigger) with bounded capture windows —
        # xprof trace + uncapped attribution rows into the job dir.
        # Installed AFTER the flight recorder so its timeline tap
        # chains over the recorder's mirror; None without a job dir
        # (sparkdl_tpu.observe.capture).
        from sparkdl_tpu.observe.capture import (
            maybe_start_capture_service,
        )

        capture = maybe_start_capture_service(client, rank)
        observe.instant("worker.start", cat="worker", rank=rank)
    _set_parent_death_signal()
    local_log = (
        open(local_log_path, "a", buffering=1) if local_log_path
        else _NullFile()
    )
    orig_stdout, orig_stderr = sys.stdout, sys.stderr
    sys.stdout = _TeeStream("stdout", local_log, client)
    sys.stderr = _TeeStream("stderr", local_log, client)
    exit_code = 0
    try:
        yield client
    except BaseException as e:
        exit_code = 1
        tb = traceback.format_exc()
        sys.stderr.write(tb + "\n")
        if client is not None:
            client.send_exception(tb)
        # Mark as already-recorded so outer handlers don't duplicate
        # the traceback into the same log.
        e._sparkdl_recorded = True
        raise
    finally:
        sys.stdout.flush()
        sys.stderr.flush()
        # Interpreter shutdown flushes sys.stdout/err; the tees' backing
        # file is about to close, so restore the originals first.
        sys.stdout, sys.stderr = orig_stdout, orig_stderr
        if client is not None:
            if observe.enabled():
                if capture is not None:
                    # BEFORE the flight recorder teardown below: the
                    # capture tap chains over the recorder's mirror
                    # and must restore it cleanly.
                    capture.stop()
                if heartbeat is not None:
                    heartbeat.stop()
                from sparkdl_tpu.observe import mem

                mem.stop_sampler()
                # Final flush BEFORE the BYE: the driver treats BYE as
                # this rank's last word, and the tail of the timeline
                # (checkpoint saves, the last step spans) must not
                # die with the process.
                observe.instant("worker.exit", cat="worker", rank=rank,
                                exit_code=exit_code)
                observe.stop_flusher()
                observe.flush()
                observe.set_sink(None)
                if flightrec is not None:
                    observe.set_flight_recorder(None)
                    flightrec.close()
            client.send_bye(exit_code)
            client.close()
        local_log.close()


def main():
    from sparkdl_tpu.hvd import _state
    from sparkdl_tpu.utils import locksan

    # Opt-in lock-order sanitizer: must run before any worker-side
    # lock is constructed (control-plane client, observe sinks) so the
    # observed acquisition-order graph covers them all.
    locksan.maybe_install()

    rank = int(os.environ["SPARKDL_TPU_RANK"])
    job_dir = os.environ["SPARKDL_TPU_JOB_DIR"]
    payload_path = os.environ["SPARKDL_TPU_PAYLOAD"]

    # Remote-exec'd workers (ssh transport): the boot stream arrives
    # over stdin ("-") — control-plane secret first (argv/env on the
    # ssh command line are world-readable in /proc; stdin is not),
    # then the payload — and the driver's job dir doesn't exist on
    # this machine, so make a local copy for the per-rank log. Only
    # the secret LINE is read eagerly: the payload body can be GBs
    # over a slow link, and draining it here would burn the gang
    # start timeout that local workers (who open a file at step 5)
    # never pay. The body waits in the pipe until after READY.
    payload_from_stdin = payload_path == "-"
    if payload_from_stdin and (
            os.environ.get("SPARKDL_TPU_CONTROL_SECRET") == "stdin"):
        secret = sys.stdin.buffer.readline().rstrip(b"\n")
        os.environ["SPARKDL_TPU_CONTROL_SECRET"] = secret.decode()
    os.makedirs(job_dir, exist_ok=True)

    # 1. Platform selection must happen before any JAX backend init.
    _state.ensure_jax_platform()

    # 1b. Warm-start compilation: point JAX's persistent compile cache
    # at the gang-wide dir BEFORE backend init, so this worker — a
    # fresh attempt's relaunch included — reuses every XLA artifact a
    # previous incarnation paid for. No-op unless the launcher shipped
    # SPARKDL_TPU_COMPILE_CACHE_DIR (see sparkdl_tpu/parallel/compile).
    from sparkdl_tpu.parallel.compile import enable_persistent_cache

    enable_persistent_cache()

    exit_code = 0
    try:
        # 2. Control plane + log tee (before anything can print).
        with worker_io(
            rank, os.path.join(job_dir, f"rank-{rank}.log")
        ) as client:
            # 3. Gang rendezvous: jax.distributed.initialize against
            # the launcher's coordinator (replaces MPI rendezvous,
            # BASELINE.json). The chaos hook sits in front of it so a
            # fault-injection schedule can stall or kill this rank
            # before it joins — inert without SPARKDL_TPU_CHAOS_* env.
            from sparkdl_tpu.utils.chaos import on_worker_boot

            on_worker_boot(rank)

            import sparkdl_tpu.hvd as hvd

            hvd.init()

            # 4. Tell the driver this worker is up (gang barrier on the
            # driver side — fail-fast if any worker never arrives,
            # reference runner_base.py:54-58).
            if client is not None:
                client.send_ready()
            from sparkdl_tpu import observe

            observe.instant("worker.ready", cat="worker", rank=rank)
            if observe.enabled():
                # Build-info correlation (ISSUE 14 satellite): stamp
                # build_info{git_sha,jax_version,device_kind} AFTER
                # backend init so the device kind is real — every
                # telemetry flush from here carries it, so the gang
                # /metrics scrape and the run-dir metrics.prom join
                # on sha without guessing.
                from sparkdl_tpu.observe.metrics import ensure_build_info

                ensure_build_info(observe.metrics())

            # 5. Deserialize and run the user main (under a per-rank
            # profiler trace when SPARKDL_TPU_PROFILE is set).
            import cloudpickle

            from sparkdl_tpu.utils.profiler import maybe_trace_worker

            if payload_from_stdin:
                user_main, kwargs = cloudpickle.loads(
                    sys.stdin.buffer.read())
            else:
                with open(payload_path, "rb") as f:
                    user_main, kwargs = cloudpickle.load(f)
            with maybe_trace_worker(rank):
                result = user_main(**kwargs)

            # 6. Rank 0's return value goes back to the driver.
            if hvd.rank() == 0 and client is not None:
                client.send_result(cloudpickle.dumps(result))
    except BaseException as e:
        exit_code = 1
        if not getattr(e, "_sparkdl_recorded", False):
            # Bootstrap failure BEFORE the tee existed (control plane
            # unreachable, unwritable job dir): stderr is still the
            # launcher's O_APPEND boot log — print there or the
            # launcher reports an opaque 'exited 1' with an empty log.
            traceback.print_exc()
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
