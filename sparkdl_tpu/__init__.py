"""sparkdl_tpu: a TPU-native framework with the capabilities of
databricks/spark-deep-learning.

Public surface parity (reference ``sparkdl/__init__.py:19-24``):
``HorovodRunner`` is re-exported at the package root and ``__version__``
is defined here. Unlike the reference — which only ships a local-mode
stub and defers the distributed runtime to closed-source Databricks
Runtime (reference ``README.md:10-11``) — this package implements the
full distributed contract on JAX/XLA: gang launch, TPU chip binding,
``jax.distributed`` rendezvous, XLA collectives over ICI/DCN, and a real
worker→driver control plane.
"""

from sparkdl_tpu.horovod.runner_base import HorovodRunner
from sparkdl_tpu.version import __version__

__all__ = ["HorovodRunner"]
