"""Continuous-batching decode engine: slot-mapped KV cache, per-slot
positions, admission of new sequences between decode chunks.

Single-stream serving (models/generate.py) leaves the chip idle
whenever one sequence finishes before another would start; production
serving interleaves many requests through a fixed set of batch SLOTS
(vLLM-style iteration-level scheduling, re-thought for XLA):

- The KV cache is one batched pytree with leading dim = n_slots; slot
  ``i``'s rows belong to whichever request currently occupies it.
- Every decode step runs ONE jitted program over all slots with an
  explicit per-slot position vector (``positions`` in the model's
  decode path — the slot-mapped branch in ``models/llama.py``).
- Python-level scheduling happens only every ``chunk`` tokens: the
  decode loop is a ``lax.scan`` (per-token host dispatch would pay a
  ~25 ms tunnel round trip per token), so admission granularity is the
  chunk, a deliberate XLA-first trade-off against per-iteration
  admission.
- Admission: a finished slot is refilled by PREFILLING the queued
  request's prompt (bucket-padded to bound recompiles; the sampled
  first token is taken at the true prompt end) and inserting its cache
  rows, position, and first token into the batched state.

Inactive slots keep decoding junk into their frozen position — one
overwritten, never-visible cache row — which costs nothing extra on
the MXU (the batch dim is fixed) and keeps every program shape static.

No reference counterpart (the reference is a training-launcher stub);
this is the serving-depth side of SURVEY.md §2's model-zoo story.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np


def _bucket(n, buckets=(32, 64, 128, 256, 512, 1024, 2048, 4096)):
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest bucket")


def _hits_stop(tokens, stops):
    """True when any stop sequence is a suffix of ``tokens``."""
    return any(len(tokens) >= len(st)
               and tuple(tokens[-len(st):]) == st for st in stops)


def _pad_bucket(tokens, cap):
    """Bucket-pad a 1-D token array to ``min(_bucket(len), cap)`` as a
    (1, bucket) int32 batch — ONE definition of the prefill padding
    policy (target + draft, full prompts + suffixes)."""
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    bucket = min(_bucket(len(tokens)), cap)
    padded = np.zeros((1, bucket), np.int32)
    padded[0, :len(tokens)] = tokens
    return padded


@functools.lru_cache(maxsize=64)
def _engine_programs(dec_cfg, temperature, sharded_mesh=None, top_k=0,
                     top_p=1.0):
    """(prefill, suffix_prefill, paged_prefill, insert, decode_chunk,
    copy_pages)
    — positional order is load-bearing (the engine's _programs[i]
    properties index it) — jitted once per (decode config,
    temperature, sharded mesh) — module-level like
    generate._decode_programs, so a fresh engine instance reuses
    compiled programs instead of paying XLA again (an engine per
    request burst is the normal usage).

    ``sharded_mesh``: a TP mesh to bind the paged decode kernel to
    (shard_map over the kv-head axis) — set by the engine only when
    the cache is actually head-sharded and the kernel mode is on."""
    from sparkdl_tpu.models.llama import Llama

    paged_fn = None
    if sharded_mesh is not None:
        from sparkdl_tpu.ops.pallas.paged_attention import (
            paged_attention_decode_sharded,
        )

        paged_fn = paged_attention_decode_sharded(
            sharded_mesh, axis_name="model",
            interpret=(dec_cfg.paged_kernel == "force_interpret"),
        )
    model = Llama(dec_cfg, paged_attention_fn=paged_fn)

    def _sample(logits, rng):
        from sparkdl_tpu.models.generate import sample_logits

        return sample_logits(logits, rng, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    def _sample_lp(logits, rng):
        from sparkdl_tpu.models.generate import sample_logits_with_lp

        return sample_logits_with_lp(
            logits, rng, temperature=temperature, top_k=top_k,
            top_p=top_p)

    @jax.jit
    def prefill(params, padded_prompt, rng, true_len, adapter_ids=None):
        # standard shared-index decode-mode prefill, batch 1; junk pad
        # rows land at positions >= true_len where the causal cache
        # mask keeps them invisible until overwritten. true_len is a
        # TRACED scalar: one compile per bucket, not per prompt length.
        logits, state = model.apply(
            {"params": params}, padded_prompt,
            adapter_ids=adapter_ids, mutable=["cache"],
        )
        last = logits[:, true_len - 1]
        tok, lp = _sample_lp(last, rng)
        return state["cache"], tok, lp

    @jax.jit
    def suffix_prefill(params, prefix_cache, padded_suffix, rng,
                       true_len, adapter_ids=None):
        # prefix caching: continue a STORED prefix cache (its shared
        # index already sits at the prefix length) over the request's
        # suffix only — the prefix rows are copied, never recomputed
        logits, state = model.apply(
            {"params": params, "cache": prefix_cache}, padded_suffix,
            adapter_ids=adapter_ids, mutable=["cache"],
        )
        last = logits[:, true_len - 1]
        tok, lp = _sample_lp(last, rng)
        return state["cache"], tok, lp

    @functools.partial(jax.jit, donate_argnums=(1,))
    def paged_prefill(params, cache, padded_prompt, table_row, rng,
                      true_len, start_pos, adapter_ids=None):
        """Paged admission: prefill writes STRAIGHT into the pooled
        physical cache through this slot's block table — there is no
        per-slot cache to copy afterwards. ``start_pos`` supports
        future prefix reuse (0 today)."""
        s = padded_prompt.shape[1]
        positions = start_pos + jnp.arange(s)[None, :]
        logits, state = model.apply(
            {"params": params, "cache": cache}, padded_prompt,
            positions=positions, block_tables=table_row,
            adapter_ids=adapter_ids, mutable=["cache"],
        )
        last = logits[:, true_len - 1]
        tok, lp = _sample_lp(last, rng)
        return state["cache"], tok, lp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def copy_pages(cache, src_pages, dst_pages):
        """Copy physical pages src->dst inside the pool (paged prefix
        sharing: the PARTIAL boundary page of a shared prefix must be
        per-slot — a suffix starting mid-page writes into it)."""
        def leaf(x):
            if x.ndim != 4:  # scalar cache_index leaves pass through
                return x
            return x.at[dst_pages].set(x[src_pages])

        return jax.tree.map(leaf, cache)

    @jax.jit
    def insert(cache, pos, token, one_cache, new_token, p_len, slot):
        # scalar leaves (the shared cache_index, unused on the
        # slot-mapped path) pass through; K/V rows land in the slot
        cache = jax.tree.map(
            lambda full, one: (
                full if full.ndim == 0 else full.at[slot].set(one[0])
            ),
            cache, one_cache,
        )
        return (cache, pos.at[slot].set(p_len),
                token.at[slot].set(new_token[0]))

    @functools.partial(jax.jit, static_argnums=(6,),
                       donate_argnums=(1,))
    def decode_chunk(params, cache, token, pos, active, rng, n,
                     tables=None, adapter_ids=None):
        def body(carry, _):
            cache, token, pos, rng = carry
            logits, st = model.apply(
                {"params": params, "cache": cache},
                token[:, None], positions=pos[:, None],
                block_tables=tables, adapter_ids=adapter_ids,
                mutable=["cache"],
            )
            rng, sub = jax.random.split(rng)
            nxt, lp = _sample_lp(logits[:, -1], sub)
            # inactive slots freeze: position pinned (their junk
            # write is overwritten in place, never visible). Active
            # slots clamp at the last cache row: chunk lengths round
            # up to a power of two, so a slot whose budget ends
            # mid-chunk keeps stepping — without the clamp its writes
            # would pass max_cache_len (out of bounds for the dense
            # scatter, junk into a neighbour's page when paged). The
            # overshot tokens are discarded host-side.
            pos = jnp.where(
                active,
                jnp.minimum(pos + 1, dec_cfg.max_cache_len - 1),
                pos)
            return (st["cache"], nxt, pos, rng), (nxt, lp)

        (cache, token, pos, rng), (toks, lps) = jax.lax.scan(
            body, (cache, token, pos, rng), None, length=n
        )
        return cache, token, pos, rng, toks, lps  # (n, n_slots) each

    return (prefill, suffix_prefill, paged_prefill, insert,
            decode_chunk, copy_pages)


@dataclasses.dataclass
class _Slot:
    req_id: int = -1
    active: bool = False
    remaining: int = 0
    tokens: list = dataclasses.field(default_factory=list)
    logprobs: list = dataclasses.field(default_factory=list)


class ContinuousBatchingEngine:
    """Greedy/temperature decoding over ``n_slots`` concurrent streams.

    Usage::

        eng = ContinuousBatchingEngine(model, params, n_slots=4)
        rid = eng.submit(prompt_tokens_1d, max_new_tokens=64)
        results = eng.run()          # {rid: np.ndarray of new tokens}

    ``stats`` afterwards holds steps, slot-step counts, and the slot
    utilization ratio (active slot-steps / total slot-steps).
    """

    def __init__(self, model, params, *, n_slots=4, temperature=0.0,
                 eos_id=None, chunk=16, rng=None, mesh=None,
                 rules=None, page_size=0, n_pages=None,
                 prefill_chunk=0, top_k=0, top_p=1.0, quant="",
                 quant_kernel=""):
        """``mesh`` enables tensor-parallel serving: params are placed
        per ``rules`` (default TRANSFORMER_RULES — Megatron column/row
        splits) and the KV cache is sharded over its kv-heads axis on
        the ``model`` mesh axis; GSPMD inserts the collectives in the
        same jitted programs the single-device engine runs.

        ``quant`` ("int8" | "int4") selects weight-only quantized
        serving PER ENGINE: the dense ``params`` tree is quantized at
        construction (models.quant.quantize_llama_params) and every
        decode matmul runs through QuantDense/QuantDense4 — one fleet
        can mix bf16 and int8 replicas off the same checkpoint.
        Composes with ``mesh``: the sharding rules match the
        ``kernel_q``/``kernel_q4`` leaves through the same Megatron
        patterns as dense kernels (scales replicate). Pass a tree
        that is ALREADY quantized (cfg.quant set on ``model``) with
        ``quant=""`` — quantizing twice is refused.

        ``quant_kernel`` routes the engine's dequant GEMMs: "" defers
        to the ``SPARKDL_TPU_KERNEL_QUANT_MATMUL`` knob, "auto" runs
        the fused pallas quant-matmul on TPU (XLA dequant elsewhere),
        "off" pins the XLA lowering, "force_interpret" emulates the
        kernel on any backend (the token-exactness oracle). Becomes
        ``cfg.quant_kernel``, so it is part of the engine's program
        cache key.

        ``page_size`` > 0 switches to a PAGED KV cache: one pooled
        physical store of ``n_pages`` pages shared by every slot
        through per-slot block tables, so memory is sized to the POOL
        (actual concurrent context), not n_slots × max_cache_len.
        Admission allocates a request's worst-case pages up front and
        queues the request when the pool is exhausted (capacity
        admission control); a finished request's pages return to the
        pool. Page 0 is a write-only dump for bucket-padding junk.
        Default ``n_pages`` reproduces dense capacity exactly.

        ``prefill_chunk`` (paged only): prompts longer than this
        prefill in segments interleaved with decode chunks
        (Sarathi-style), bounding the decode stall a long admission
        causes to one segment instead of the whole prompt."""
        cfg = model.cfg
        if quant:
            if quant not in ("int8", "int4"):
                raise ValueError(
                    f"unknown quant mode {quant!r}; expected 'int8' "
                    "or 'int4'"
                )
            if cfg.quant:
                raise ValueError(
                    f"model is already quantized (cfg.quant="
                    f"{cfg.quant!r}); pass quant= only with a dense "
                    "tree"
                )
            from sparkdl_tpu.models.quant import quantize_llama_params

            # replace() re-runs __post_init__, which enforces the
            # quant/LoRA/multi-adapter exclusivity rules
            cfg = dataclasses.replace(cfg, quant=quant)
            params = quantize_llama_params(
                params, bits=8 if quant == "int8" else 4,
                group=cfg.quant_group)
        if quant_kernel:
            if not cfg.quant:
                raise ValueError(
                    "quant_kernel routes the dequant GEMMs of a "
                    "quantized engine; pass quant= (or a quantized "
                    "model) with it")
            cfg = dataclasses.replace(cfg, quant_kernel=quant_kernel)
        self.page_size = int(page_size)
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {self.prefill_chunk}"
            )
        if self.prefill_chunk and not self.page_size:
            raise ValueError(
                "prefill_chunk requires the paged cache (page_size>0): "
                "the dense slot cache has no per-slot write path for "
                "partial prompts"
            )
        self._prefilling = {}  # slot -> staged chunked-prefill state
        self._on_token = None  # streaming callback, set per run()
        self._max_pages = (
            -(-cfg.max_cache_len // self.page_size) if page_size else 0)
        self._paged_sharded_mesh = None  # set only by the TP+kernel path
        if page_size:
            n_pages = (int(n_pages) if n_pages is not None
                       else int(n_slots) * self._max_pages + 1)
            cfg = dataclasses.replace(
                cfg, page_size=self.page_size, n_pages=n_pages)
            if mesh is not None and cfg.paged_kernel != "off":
                # A raw pallas_call cannot be partitioned by GSPMD, so
                # under TP the kernel runs through its shard_map
                # binding over the kv-head axis (one kernel per shard,
                # no collectives — GQA query groups are co-resident
                # with their kv heads). Engage only when the cache is
                # actually head-sharded (divisibility) and the kernel
                # would run at all; otherwise the gather path, which
                # GSPMD shards fine.
                from sparkdl_tpu.ops._dispatch import use_pallas

                model_size = dict(mesh.shape).get("model", 0)
                engaged = (
                    model_size > 0
                    and cfg.n_kv_heads % model_size == 0
                    and (cfg.paged_kernel == "force_interpret"
                         or use_pallas())
                )
                if engaged:
                    self._paged_sharded_mesh = mesh
                elif cfg.paged_kernel == "auto":
                    cfg = dataclasses.replace(cfg, paged_kernel="off")
                # an explicit force_interpret stays: with kv heads not
                # divisible the cache_spec REPLICATES the pool, where
                # the raw (unsharded) kernel call is valid — never
                # silently downgrade a user's explicit kernel mode
        self.cfg = dataclasses.replace(cfg, decode=True)
        self.n_slots = int(n_slots)
        self.temperature = float(temperature)
        # sampling restrictions (temperature > 0): top_k keeps the k
        # most likely tokens, top_p the minimal nucleus reaching p
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_id = eos_id
        self.chunk = int(chunk)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        from sparkdl_tpu.models.llama import Llama

        self._model = Llama(self.cfg)
        self._queue = []    # (rid, prompt, max_new, prefix_id,
                            #  adapter_id)
        self._prefixes = {}  # prefix_id -> (tokens,
                             #   cache | pool pages, adapter_id)
        self._slots = [_Slot() for _ in range(self.n_slots)]
        self._results = {}
        self._stops = {}           # rid -> tuple of stop token tuples
        self._finish_reasons = {}  # rid -> "eos" | "length" | "stop"
        self.finish_reasons = {}   # last drained burst's reasons
        self._logprobs = {}        # rid -> finished logprob array
        self.logprobs = {}         # last drained burst's logprobs
        self._next_id = 0
        self.stats = {"steps": 0, "active_slot_steps": 0,
                      "total_slot_steps": 0}
        # Request-level observability hook (a ServingTelemetry from
        # sparkdl_tpu.observe.serving, installed by the HTTP frontend
        # only when SPARKDL_TPU_TELEMETRY_DIR opted in). None keeps the
        # decode loop's hot path at ONE `is not None` test per chunk —
        # the zero-overhead contract the serving latch test pins.
        self.telemetry = None

        # Device state: batched (or pooled paged) cache, per-slot
        # position, last token.
        dummy = jnp.zeros((self.n_slots, 1), jnp.int32)
        init_kw = {}
        if self.page_size:
            init_kw["block_tables"] = jnp.zeros(
                (self.n_slots, self._max_pages), jnp.int32)
            # host-side allocator: page 0 reserved as the junk dump
            self._free_pages = list(range(1, self.cfg.n_pages))
            self._tables = np.zeros(
                (self.n_slots, self._max_pages), np.int32)
            self._slot_pages = [[] for _ in range(self.n_slots)]
        state = self._model.init(jax.random.PRNGKey(0), dummy,
                                 positions=jnp.zeros((self.n_slots, 1),
                                                     jnp.int32),
                                 **init_kw)
        self._cache = state["cache"]
        # Categorized accounting (ISSUE 18): the KV cache/pool and the
        # serving params are long-lived trees — register them so the
        # mem sampler's category table attributes them instead of
        # lumping them into 'unattributed'. No-ops with telemetry off.
        from sparkdl_tpu.observe import mem as _mem_acct

        _mem_acct.register_tree(
            "kv_pages", lambda: _mem_acct.tree_nbytes(self._cache))
        _mem_acct.register_tree("params", params)
        self._pos = jnp.zeros((self.n_slots,), jnp.int32)
        self._token = jnp.zeros((self.n_slots,), jnp.int32)
        self._adapter_ids = np.zeros((self.n_slots,), np.int32)
        self.mesh = mesh
        self.params = params
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from sparkdl_tpu.parallel.sharding import (
                TRANSFORMER_RULES,
                param_sharding,
            )

            axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            missing = {"model", "fsdp"} - set(axis_sizes)
            if missing:
                raise ValueError(
                    f"TP serving needs mesh axes 'model' and 'fsdp' "
                    f"(missing {sorted(missing)}); build the mesh with "
                    "sparkdl_tpu.parallel.mesh.make_mesh"
                )
            self.params = jax.device_put(
                params,
                param_sharding(
                    params,
                    rules if rules is not None else TRANSFORMER_RULES,
                    mesh,
                ),
            )
            model_size = axis_sizes["model"]

            def cache_spec(leaf):
                # (n_slots, max_len, kv_heads, head_dim): kv heads ride
                # the TP axis alongside the head-sharded projections
                if leaf.ndim == 4 and leaf.shape[2] % model_size == 0:
                    return NamedSharding(mesh, P(None, None, "model"))
                return NamedSharding(mesh, P())

            self._cache = jax.device_put(
                self._cache, jax.tree.map(cache_spec, self._cache))
            rep = NamedSharding(mesh, P())
            self._pos = jax.device_put(self._pos, rep)
            self._token = jax.device_put(self._token, rep)
            self._rng = jax.device_put(self._rng, rep)

    # -- public API ---------------------------------------------------

    @property
    def _programs(self):
        return _engine_programs(self.cfg, self.temperature,
                                self._paged_sharded_mesh,
                                self.top_k, self.top_p)

    @property
    def _prefill_fn(self):
        return self._programs[0]

    @property
    def _suffix_prefill_fn(self):
        return self._programs[1]

    @property
    def _paged_prefill_fn(self):
        return self._programs[2]

    @property
    def _insert_fn(self):
        return self._programs[3]

    @property
    def _decode_chunk_fn(self):
        return self._programs[4]

    @property
    def _copy_pages_fn(self):
        return self._programs[5]


    def _adapter_arg(self, adapter_id):
        """adapter_ids argument for a batch-1 program call — None on
        single-adapter engines (keeps program signatures identical)."""
        if not self.cfg.multi_lora:
            return None
        return jnp.asarray([adapter_id], jnp.int32)

    def register_prefix(self, prefix_tokens, adapter_id=0):
        """Prefill a shared prompt PREFIX (a system prompt) once and
        cache its K/V rows; requests submitted with the returned
        ``prefix_id`` prefill only their suffix — admission cost drops
        from O(full prompt) to O(suffix) compute plus a device-side
        row copy. The cached rows are ADAPTER-SPECIFIC when the engine
        serves multi-LoRA (k/v projections carry the adapter), so a
        prefix is bound to ``adapter_id`` and only same-adapter
        requests may use it."""
        if self.cfg.multi_lora:
            if not 0 <= adapter_id < self.cfg.multi_lora:
                raise ValueError(
                    f"adapter_id {adapter_id} outside the stacked "
                    f"range [0, {self.cfg.multi_lora})"
                )
        elif adapter_id:
            raise ValueError(
                "adapter_id requires a multi_lora model "
                "(LlamaConfig.multi_lora > 0)"
            )
        prefix = np.asarray(prefix_tokens, np.int32).reshape(-1)
        if not len(prefix):
            raise ValueError("empty prefix")
        # < (not <=): a prefix filling the whole cache leaves no room
        # for even a one-token suffix, so it could never be used
        if len(prefix) >= self.cfg.max_cache_len:
            raise ValueError(
                f"prefix ({len(prefix)}) must be shorter than "
                f"max_cache_len ({self.cfg.max_cache_len})"
            )
        p_len = len(prefix)
        self._rng, sub = jax.random.split(self._rng)
        if self.page_size:
            # paged sharing: prefill the prefix ONCE into pool pages
            # that every consumer's block table will reference
            # read-only (the partial boundary page gets copied per
            # slot at admission — suffix writes land in it)
            need = -(-p_len // self.page_size)
            if need > len(self._free_pages):
                raise RuntimeError(
                    f"paged pool exhausted registering prefix: needs "
                    f"{need} pages, {len(self._free_pages)} free"
                )
            pages = [self._free_pages.pop() for _ in range(need)]
            table = np.zeros((1, self._max_pages), np.int32)
            table[0, :need] = pages
            padded = _pad_bucket(prefix, self.cfg.max_cache_len)
            self._cache, _tok, _lp = self._paged_prefill_fn(
                self.params, self._cache, jnp.asarray(padded),
                jnp.asarray(table), sub,
                jnp.asarray(p_len, jnp.int32), jnp.asarray(0, jnp.int32),
                adapter_ids=self._adapter_arg(adapter_id),
            )
            pid = f"prefix-{len(self._prefixes)}"
            self._prefixes[pid] = (prefix, pages, adapter_id)
            return pid
        padded = _pad_bucket(prefix, self.cfg.max_cache_len)
        cache, _, _ = self._prefill_fn(
            self.params, jnp.asarray(padded), sub, p_len,
            adapter_ids=self._adapter_arg(adapter_id),
        )
        # pin the shared index to the TRUE length (the bucket-padded
        # prefill advanced it to the bucket; junk rows beyond p_len
        # stay invisible and get overwritten by the suffix)
        cache = jax.tree.map(
            lambda x: jnp.full(x.shape, p_len, x.dtype)
            if x.ndim == 0 else x, cache)
        pid = f"prefix-{len(self._prefixes)}"
        self._prefixes[pid] = (prefix, cache, adapter_id)
        return pid

    def submit(self, prompt_tokens, max_new_tokens, prefix_id=None,
               adapter_id=0, stop=None):
        """Queue a request; returns its id. ``prefix_id`` (from
        :meth:`register_prefix`): the prompt must START with that
        prefix and extend it by at least one token. ``adapter_id``
        selects this request's LoRA adapter when the engine serves a
        multi-adapter tree (cfg.multi_lora). ``stop``: token-id
        sequences that end THIS request's generation when they appear
        (the stop sequence is included in the output, like eos);
        finish causes land in :attr:`finish_reasons` after run()."""
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if self.cfg.multi_lora:
            if not 0 <= adapter_id < self.cfg.multi_lora:
                raise ValueError(
                    f"adapter_id {adapter_id} outside the stacked "
                    f"range [0, {self.cfg.multi_lora})"
                )
        elif adapter_id:
            raise ValueError(
                "adapter_id requires a multi_lora model "
                "(LlamaConfig.multi_lora > 0)"
            )
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if len(prompt) + max_new_tokens > self.cfg.max_cache_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_cache_len "
                f"({self.cfg.max_cache_len})"
            )
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise ValueError(
                    f"unknown prefix_id {prefix_id!r}; call "
                    "register_prefix first"
                )
            prefix, _, pfx_adapter = self._prefixes[prefix_id]
            if self.cfg.multi_lora and pfx_adapter != adapter_id:
                raise ValueError(
                    f"prefix {prefix_id} is bound to adapter "
                    f"{pfx_adapter}; request uses {adapter_id} — "
                    "cached K/V rows are adapter-specific"
                )
            if (len(prompt) <= len(prefix)
                    or not np.array_equal(prompt[:len(prefix)], prefix)):
                raise ValueError(
                    f"prompt must extend the registered prefix "
                    f"{prefix_id} by at least one token"
                )
        rid = self._next_id
        self._next_id += 1
        if stop:
            seqs = tuple(
                tuple(int(t) for t in np.asarray(s).reshape(-1))
                for s in stop)
            if any(not s for s in seqs):
                raise ValueError("empty stop sequence")
            self._stops[rid] = seqs
        self._queue.append(
            (rid, prompt, int(max_new_tokens), prefix_id,
             int(adapter_id)))
        return rid

    def _try_admit_paged(self, slot_idx):
        """Paged admission: allocate the request's worst-case pages
        (whole prompt + budget) from the pool, point the slot's block
        table at them, prefill straight into the physical pages. With
        a prefix_id, the prefix's FULL pages are shared read-only
        across slots (only the partial boundary page is copied) and
        only the suffix is prefilled. Returns False (request left at
        the queue head) when the pool can't cover it yet — capacity
        admission control."""
        rid, prompt, max_new, prefix_id, adapter_id = self._queue[0]
        P = self.page_size
        p_len = len(prompt)
        total_pages = -(-self._worst_case_tokens(p_len, max_new) // P)
        # no-prefix admission = the empty-prefix special case: zero
        # shared pages, zero-length start, the whole prompt as suffix
        prefix = np.zeros((0,), np.int32)
        prefix_pages = []
        if prefix_id is not None:
            prefix, prefix_pages, _pfx_adapter = self._prefixes[prefix_id]
        n_full = len(prefix) // P
        shared = prefix_pages[:n_full]
        need = total_pages - len(shared)
        if need > len(self._free_pages):
            return False
        self._queue.pop(0)
        if self.telemetry is not None:
            # queue wait ends HERE — the engine is about to spend
            # prefill compute on this request
            self.telemetry.request_admitted(rid)
            # per-request worst-case KV footprint (ISSUE 18) — the
            # getattr guard keeps older three-hook telemetry adapters
            # (tests stub them) working unchanged
            hook = getattr(self.telemetry, "request_pages", None)
            if hook is not None:
                hook(rid, total_pages)
        own = [self._free_pages.pop() for _ in range(need)]
        self._slot_pages[slot_idx] = own
        self._tables[slot_idx] = 0
        self._tables[slot_idx, :total_pages] = shared + own

        # copy the partial boundary page (suffix writes land in it);
        # full shared pages are referenced, never written
        if len(prefix) % P:
            self._cache = self._copy_pages_fn(
                self._cache,
                jnp.asarray([prefix_pages[n_full]]),
                jnp.asarray([own[0]]),
            )
        suffix = prompt[len(prefix):]
        start = len(prefix)
        if len(prefix):
            self.stats["prefill_tokens_saved"] = (
                self.stats.get("prefill_tokens_saved", 0) + len(prefix))
        if self.prefill_chunk and len(suffix) > self.prefill_chunk:
            # Chunked prefill: this admission only STAGES the slot —
            # segments run one per engine-loop iteration, interleaved
            # with decode chunks, so a long prompt can't stall running
            # streams for its whole length. The slot stays inactive
            # (masked out of decode tables) until the final segment.
            self._prefilling[slot_idx] = {
                "rid": rid, "suffix": suffix, "start": start,
                "done": 0, "max_new": max_new,
                "adapter_id": adapter_id,
            }
            # first segment runs in the run-loop's advance phase — a
            # staging-time segment would make admission a TWO-segment
            # decode stall, breaking the one-per-iteration bound
            return True
        self._prefill_segment(slot_idx, suffix, start, len(suffix),
                              adapter_id, final=True,
                              rid=rid, max_new=max_new)
        return True

    def _prefill_segment(self, slot_idx, seg_tokens, start, true_len,
                         adapter_id, *, final, rid=None, max_new=None):
        """Run one paged prefill program over ``seg_tokens`` at logical
        offset ``start``. On the FINAL segment the sampled token (the
        request's first generated token) activates the slot."""
        self._rng, sub = jax.random.split(self._rng)
        # power-of-two pad with a floor of 8 (the global _bucket floor
        # of 32 would multiply the compute of small prefill_chunk
        # segments); the cache-end cap can't undercut true_len because
        # submit() bounds every position below max_cache_len
        b = 8
        while b < true_len:
            b *= 2
        bucket = min(b, self.cfg.max_cache_len - start)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :true_len] = seg_tokens[:true_len]
        self._cache, tok, lp = self._paged_prefill_fn(
            self.params, self._cache, jnp.asarray(padded),
            jnp.asarray(self._tables[slot_idx][None]), sub,
            jnp.asarray(true_len, jnp.int32),
            jnp.asarray(start, jnp.int32),
            adapter_ids=self._adapter_arg(adapter_id),
        )
        self.stats["prefill_segments"] = (
            self.stats.get("prefill_segments", 0) + 1)
        if final:
            p_len = start + true_len
            self._pos = self._pos.at[slot_idx].set(p_len)
            self._token = self._token.at[slot_idx].set(tok[0])
            self._adapter_ids[slot_idx] = adapter_id
            self._activate_slot(slot_idx, rid, max_new, tok, lp)

    def _advance_prefill(self, slot_idx):
        """One more segment for a mid-prefill slot; activates it on
        the last one."""
        st = self._prefilling[slot_idx]
        seg = min(self.prefill_chunk, len(st["suffix"]) - st["done"])
        final = st["done"] + seg == len(st["suffix"])
        self._prefill_segment(
            slot_idx, st["suffix"][st["done"]:st["done"] + seg],
            st["start"] + st["done"], seg, st["adapter_id"],
            final=final, rid=st["rid"], max_new=st["max_new"],
        )
        st["done"] += seg
        if final:
            del self._prefilling[slot_idx]

    def _pages_needed(self, req):
        """Fresh pages the queue-head request needs: its worst case
        minus the prefix pages it would SHARE (run()'s dead-end check
        must agree with _try_admit_paged or it cries exhaustion over
        requests that would admit)."""
        _, prompt, max_new, prefix_id, _aid = req
        total = -(-self._worst_case_tokens(len(prompt), max_new)
                  // self.page_size)
        if prefix_id is not None:
            prefix, _, _pfx = self._prefixes[prefix_id]
            total -= len(prefix) // self.page_size
        return total

    def _worst_case_tokens(self, p_len, max_new):
        """Cache rows a request can ever touch — page reservation AND
        the pool dead-end check size worst cases with this ONE hook
        (the speculative engine adds its k-token verify scratch)."""
        return p_len + max_new

    def _activate_slot(self, slot_idx, rid, max_new, tok, lp):
        """Shared admission epilogue: slot bookkeeping + the
        instant-finish check (first token is eos, or a one-token
        budget) — ONE definition for both admission paths."""
        s = self._slots[slot_idx]
        s.req_id, s.active = rid, True
        s.remaining = max_new - 1  # the prefill emitted token #1
        s.tokens = [int(np.asarray(tok)[0])]
        s.logprobs = [float(np.asarray(lp)[0])]
        if self._on_token is not None:
            self._on_token(rid, s.tokens[0])
        if self.eos_id is not None and s.tokens[0] == self.eos_id:
            self._finish(slot_idx, "eos")
        elif _hits_stop(s.tokens, self._stops.get(rid, ())):
            self._finish(slot_idx, "stop")
        elif s.remaining == 0:
            self._finish(slot_idx, "length")

    def _admit(self, slot_idx):
        rid, prompt, max_new, prefix_id, adapter_id = self._queue.pop(0)
        if self.telemetry is not None:
            self.telemetry.request_admitted(rid)
        p_len = len(prompt)
        self._rng, sub = jax.random.split(self._rng)
        if prefix_id is not None:
            prefix, prefix_cache, _pfx_adapter = self._prefixes[prefix_id]
            suffix = prompt[len(prefix):]
            padded = _pad_bucket(
                suffix, self.cfg.max_cache_len - len(prefix))
            one_cache, tok, lp = self._suffix_prefill_fn(
                self.params, prefix_cache, jnp.asarray(padded), sub,
                len(suffix),
                adapter_ids=self._adapter_arg(adapter_id),
            )
            self.stats["prefill_tokens_saved"] = (
                self.stats.get("prefill_tokens_saved", 0) + len(prefix))
        else:
            padded = _pad_bucket(prompt, self.cfg.max_cache_len)
            one_cache, tok, lp = self._prefill_fn(
                self.params, jnp.asarray(padded), sub, p_len,
                adapter_ids=self._adapter_arg(adapter_id),
            )
        self._cache, self._pos, self._token = self._insert_fn(
            self._cache, self._pos, self._token, one_cache, tok,
            p_len, slot_idx,
        )
        self._adapter_ids[slot_idx] = adapter_id
        self._activate_slot(slot_idx, rid, max_new, tok, lp)

    def _finish(self, slot_idx, reason="length"):
        s = self._slots[slot_idx]
        self._results[s.req_id] = np.asarray(s.tokens, np.int32)
        self._finish_reasons[s.req_id] = reason
        self._logprobs[s.req_id] = np.asarray(s.logprobs, np.float32)
        self._stops.pop(s.req_id, None)
        s.active = False
        s.tokens = []
        s.logprobs = []
        if self.page_size:
            self._free_pages.extend(self._slot_pages[slot_idx])
            self._slot_pages[slot_idx] = []
            self._tables[slot_idx] = 0

    def run(self, progress=None, on_token=None):
        """Drain the queue; returns {req_id: generated tokens}.

        Each ``run()`` returns only the requests finished during THIS
        drain — completed results are handed to the caller and cleared,
        so a reused engine neither replays old bursts nor grows its
        result map without bound.

        ``on_token(req_id, token)``: streaming callback invoked for
        every accepted token in generation order (a serving front-end
        pushes these to clients; delivery granularity is the decode
        chunk — the XLA-first trade-off documented on the class).
        ``progress(engine)``: coarse per-iteration hook."""
        self._on_token = on_token
        try:
            return self._run(progress)
        finally:
            # never retain the caller's closure (and whatever client
            # buffers/connections it holds) past this run
            self._on_token = None

    def _run(self, progress):
        while (self._queue or self._prefilling
               or any(s.active for s in self._slots)):
            # fill free slots from the queue (paged: only while the
            # pool covers the next request's worst case)
            active = self._fill_slots()
            if not active.any():
                self._deadend_check()
                continue
            # Chunk length: sized to the soonest-finishing active slot
            # (so its replacement isn't kept waiting), then rounded UP
            # to a power of two — the scan program compiles O(log
            # chunk) times total instead of once per distinct tail
            # length. Overshoot is discarded host-side (same as
            # mid-chunk eos); decode_chunk clamps the position advance
            # at max_cache_len-1 so overshot steps of a budget-exhausted
            # slot can never write past the cache.
            need = min(s.remaining for s in self._slots if s.active)
            n = 1
            while n < need and n < self.chunk:
                n *= 2
            n = min(n, self.chunk)
            (self._cache, self._token, self._pos, self._rng,
             toks, lps) = self._decode_chunk_fn(
                self.params, self._cache, self._token, self._pos,
                jnp.asarray(active), self._rng, n,
                # non-active rows masked to the dump page: a
                # mid-prefill slot's junk writes must not corrupt the
                # rows it has already prefilled
                tables=(jnp.asarray(
                    np.where(active[:, None], self._tables, 0))
                        if self.page_size else None),
                adapter_ids=(jnp.asarray(self._adapter_ids)
                             if self.cfg.multi_lora else None),
            )
            toks = np.asarray(toks)                 # (n, n_slots)
            lps = np.asarray(lps)
            self.stats["steps"] += n
            self.stats["total_slot_steps"] += n * self.n_slots
            self.stats["active_slot_steps"] += int(active.sum()) * n
            self._observe_chunk(int(active.sum()), n)
            for i, s in enumerate(self._slots):
                if s.active:
                    self._accept_tokens(i, toks[:, i], lps[:, i])
            if progress is not None:
                progress(self)
        return self._drain_results()

    def _fill_slots(self):
        """Admit queued requests into free slots (paged: only while the
        pool covers worst cases), advance any staged chunked prefills,
        and return the active mask. Shared by both decode loops."""
        for i, s in enumerate(self._slots):
            if (not s.active and i not in self._prefilling
                    and self._queue):
                if self.page_size:
                    if not self._try_admit_paged(i):
                        if self.telemetry is not None:
                            # requeued, not refused: the pool can't
                            # cover the head's worst case yet
                            self.telemetry.admission_deferred(
                                "pool_exhausted")
                        break
                else:
                    self._admit(i)
        # one prefill segment per staged slot per iteration:
        # long-prompt admission interleaves with decode instead of
        # stalling it for the whole prompt
        for i in list(self._prefilling):
            self._advance_prefill(i)
        return np.array([s.active for s in self._slots])

    def _observe_chunk(self, active_count, n_tokens):
        """Telemetry for one decode chunk (or speculation round) —
        ONE definition for both decode loops, so utilization metrics
        can never skew between the plain and speculative engines."""
        if self.telemetry is not None:
            self.telemetry.decode_chunk(
                active_count, self.n_slots, n_tokens,
                free_pages=(len(self._free_pages)
                            if self.page_size else None),
                n_pages=(self.cfg.n_pages if self.page_size else None),
            )

    def _deadend_check(self):
        """Nothing active: raise when the queue head can NEVER admit
        (genuine pool shortfall) rather than spinning forever — an
        instantly-finished admission (eos / one-token budget) also
        lands here, with pages free again, and is not a dead end."""
        if self._queue and self.page_size and not self._prefilling:
            need = self._pages_needed(self._queue[0])
            if need > len(self._free_pages):
                err = RuntimeError(
                    f"paged pool exhausted: request needs "
                    f"{need} fresh pages, pool has "
                    f"{len(self._free_pages)} free and nothing "
                    "left to drain — raise n_pages"
                )
                # Engine-admission OOM forensics (ISSUE 18): the pool
                # shortfall is the serving tier's allocation failure —
                # write the report before the engine thread unwinds.
                # Inert without SPARKDL_TPU_TELEMETRY_DIR.
                from sparkdl_tpu.observe import mem

                mem.write_oom_report(
                    "admission", err,
                    extra={"pages_needed": need,
                           "pages_free": len(self._free_pages),
                           "n_pages": self.cfg.n_pages,
                           "page_size": self.page_size})
                raise err

    def _accept_tokens(self, slot_idx, tokens, logprobs):
        """Append generated tokens to a slot (streaming callback, eos
        and budget enforcement). Returns True when the slot finished —
        trailing tokens past eos/budget are discarded. ONE definition
        shared by the chunked and the speculative decode loops."""
        s = self._slots[slot_idx]
        stops = self._stops.get(s.req_id, ())
        for t, lp in zip(tokens, logprobs):
            s.tokens.append(int(t))
            s.logprobs.append(float(lp))
            s.remaining -= 1
            if self._on_token is not None:
                self._on_token(s.req_id, int(t))
            if self.eos_id is not None and int(t) == self.eos_id:
                self._finish(slot_idx, "eos")
                return True
            if stops and _hits_stop(s.tokens, stops):
                self._finish(slot_idx, "stop")
                return True
            if s.remaining == 0:
                self._finish(slot_idx, "length")
                return True
        return False

    def abort_requests(self):
        """Discard every queued and active request WITHOUT producing
        results — service fault recovery (models/server.py): after a
        run() fault the engine may hold a poison request queued or
        mid-slot, and re-running it would re-fire the fault forever.
        Frees paged pool pages and deactivates slots; abandoned cache
        rows are junk that later admissions overwrite (the same
        invariant slot reuse already relies on)."""
        self._queue.clear()
        self._prefilling.clear()
        self._stops.clear()
        self._finish_reasons.clear()
        self._logprobs.clear()
        self._results.clear()
        for i, s in enumerate(self._slots):
            if self.page_size and self._slot_pages[i]:
                self._free_pages.extend(self._slot_pages[i])
                self._slot_pages[i] = []
                self._tables[i] = 0
            s.active = False
            s.req_id = -1
            s.remaining = 0
            s.tokens = []
            s.logprobs = []

    def _drain_results(self):
        """Final stats + hand the burst's results to the caller;
        per-request finish causes land in :attr:`finish_reasons`."""
        self.stats["utilization"] = (
            self.stats["active_slot_steps"]
            / max(1, self.stats["total_slot_steps"])
        )
        self.finish_reasons = self._finish_reasons
        self._finish_reasons = {}
        self.logprobs = self._logprobs
        self._logprobs = {}
        out = self._results
        self._results = {}
        return out


# ---------------------------------------------------------------------------
# Speculative continuous batching: the engine's slot scheduler composed
# with draft-propose / target-verify rounds (models/speculative.py has
# the single-burst lockstep version; production stacks run speculation
# INSIDE the batching engine, per-slot).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _spec_engine_programs(dec_cfg, draft_cfg, k, temperature, top_k=0,
                          top_p=1.0):
    """(draft_prefill, draft_insert, draft_suffix_prefill,
    spec_round) — jitted once per (target config, draft config, k,
    temperature, top_k, top_p). temperature == 0:
    greedy longest-agreeing-prefix acceptance (token-exact vs plain
    greedy decode). temperature > 0: distribution-exact rejection
    sampling (models/speculative.spec_sample_tokens) — marginals equal
    target-only sampling, the draft moves only throughput."""
    from sparkdl_tpu.models.generate import restrict_logits
    from sparkdl_tpu.models.llama import Llama
    from sparkdl_tpu.models.speculative import spec_sample_tokens

    target = Llama(dec_cfg)
    draft = Llama(draft_cfg)

    def _restricted_probs(logits):
        # the rejection scheme is exact for whatever target
        # distribution it is fed: restricting BOTH p and q to the
        # top-k/nucleus support makes the output distribution equal
        # restricted-target-only sampling (vLLM's composition)
        return jax.nn.softmax(
            restrict_logits(logits / temperature, top_k=top_k,
                            top_p=top_p),
            axis=-1,
        )

    @jax.jit
    def draft_prefill(d_params, padded_prompt):
        """Prompt through the DRAFT (logits discarded): its slot cache
        only has to hold the prompt's K/V — junk pad rows beyond the
        true length stay invisible under the position mask."""
        _, st = draft.apply(
            {"params": d_params}, padded_prompt, mutable=["cache"])
        return st["cache"]

    @jax.jit
    def draft_insert(d_cache, one_cache, slot):
        return jax.tree.map(
            lambda full, one: (
                full if full.ndim == 0 else full.at[slot].set(one[0])
            ),
            d_cache, one_cache,
        )

    @jax.jit
    def draft_suffix_prefill(d_params, prefix_cache, padded_suffix):
        """Continue a stored DRAFT prefix cache over a request's
        suffix (logits discarded) — the draft-side twin of the
        engine's suffix_prefill."""
        _, st = draft.apply(
            {"params": d_params, "cache": prefix_cache}, padded_suffix,
            mutable=["cache"],
        )
        return st["cache"]

    @functools.partial(jax.jit, donate_argnums=(1, 3))
    def spec_round(params, cache, d_params, d_cache, token, pos,
                   active, rng, tables=None):
        """One speculation round over every slot: the draft scans k
        slot-mapped steps, then ONE target forward scores the k+1
        positions, and acceptance runs IN-GRAPH — the host reads back
        only (tokens, counts). Rejected rows above each slot's
        accepted position are junk that the NEXT round's writes cover
        before any query can see them (write window [pos', pos'+k]
        always spans the previous round's junk because pos advances
        by at most k+1)."""
        L = dec_cfg.max_cache_len
        rng, d_rng = jax.random.split(rng)

        def body(carry, step_rng):
            d_cache, tok, p = carry
            logits, st = draft.apply(
                {"params": d_params, "cache": d_cache}, tok[:, None],
                positions=p[:, None], mutable=["cache"],
            )
            last = logits[:, -1]
            if temperature == 0.0:
                nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
                q_row = jnp.zeros_like(last)  # unused in greedy
            else:
                q_row = _restricted_probs(last)
                nxt = jax.random.categorical(
                    step_rng, jnp.log(jnp.maximum(q_row, 1e-30)),
                    axis=-1,
                ).astype(jnp.int32)
            p = jnp.where(active, jnp.minimum(p + 1, L - 1), p)
            return (st["cache"], nxt, p), (nxt, q_row)

        (d_cache, last_tok, last_p), (prop, q_probs) = jax.lax.scan(
            body, (d_cache, token, pos), jax.random.split(d_rng, k))
        # one extra logits-discarded step writes the LAST proposal's
        # K/V row: a fully-accepted round advances past it, and
        # without this write the draft's next round attends a junk
        # row — acceptance collapses (exactness is unaffected; the
        # verify is authoritative). Same trick as
        # speculative_generate's propose.
        _, st = draft.apply(
            {"params": d_params, "cache": d_cache}, last_tok[:, None],
            positions=last_p[:, None], mutable=["cache"],
        )
        d_cache = st["cache"]
        prop = prop.T                                     # (b, k)

        offs = jnp.arange(k + 1)
        ppos = jnp.minimum(pos[:, None] + offs[None, :], L - 1)
        ppos = jnp.where(active[:, None], ppos, pos[:, None])
        seq = jnp.concatenate([token[:, None], prop], axis=1)
        logits, st = target.apply(
            {"params": params, "cache": cache}, seq, positions=ppos,
            block_tables=tables, mutable=["cache"],
        )
        if temperature == 0.0:
            from sparkdl_tpu.models.speculative import assemble_round

            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            agree = prop == greedy[:, :k]
            all_acc = agree.all(-1)
            m = jnp.where(all_acc, k, jnp.argmin(agree, -1))
            final = jnp.take_along_axis(
                greedy, m[:, None], axis=1)[:, 0]
            tokens, counts = assemble_round(prop, m, final)
            lp_all = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1)
        else:
            rng, s_rng = jax.random.split(rng)
            p_probs = _restricted_probs(logits)
            tokens, counts = spec_sample_tokens(
                q_probs.transpose(1, 0, 2), p_probs, prop, s_rng)
            lp_all = jnp.log(jnp.maximum(p_probs, 1e-30))
        # chosen-token logprob under the TARGET distribution at each
        # verified position (the same convention as _sample_lp)
        lps = jnp.take_along_axis(
            lp_all, tokens[..., None], axis=-1)[..., 0]   # (b, k+1)
        return st["cache"], d_cache, tokens, counts, lps, rng

    return draft_prefill, draft_insert, draft_suffix_prefill, spec_round


class SpeculativeBatchingEngine(ContinuousBatchingEngine):
    """Continuous batching with per-slot speculative decoding: an int8
    (or any same-interface) DRAFT proposes ``k`` tokens per slot, one
    target forward verifies all slots, and each slot independently
    accepts its longest agreeing prefix plus the target's bonus token
    — greedy outputs are EXACTLY the plain engine's (speculative
    identity per slot; no lockstep barrier like
    :func:`speculative_generate`'s whole-batch agree).

    ``temperature > 0`` switches the round to distribution-exact
    rejection sampling (:func:`~sparkdl_tpu.models.speculative.
    spec_sample_tokens`): accept proposal x with prob min(1, p(x)/q(x)),
    resample the first rejection from the residual (p-q)+ — marginals
    equal target-only sampling; the draft moves only throughput.

    The TARGET cache may be paged (``page_size=``): verify writes ride
    the slot's block table, and page reservation adds the k-token
    scratch via :meth:`_worst_case_tokens`. The DRAFT always keeps a
    dense slot cache — proposals are the draft's problem, and a dense
    (typically int8) draft cache is simpler than a second page pool.

    Prefix caching works on both sides: the target through the base
    engine's dense-copy / shared-pool-pages machinery, the draft
    through its own dense prefix caches — prefixed admissions prefill
    only the suffix on both models.

    Out of scope (raises): multi-adapter, chunked prefill, TP mesh.
    """

    def __init__(self, model, params, draft_params, *, n_slots=4,
                 eos_id=None, k=4, rng=None, draft_model=None,
                 temperature=0.0, page_size=0, n_pages=None,
                 top_k=0, top_p=1.0):
        cfg = model.cfg
        if cfg.multi_lora:
            raise ValueError(
                "SpeculativeBatchingEngine is single-adapter only")
        # set before super(): _worst_case_tokens (k-dependent) is live
        # as soon as the base class can admit
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        super().__init__(model, params, n_slots=n_slots,
                         temperature=temperature, eos_id=eos_id,
                         rng=rng, page_size=page_size, n_pages=n_pages,
                         top_k=top_k, top_p=top_p)
        d_base = draft_model.cfg if draft_model is not None else cfg
        self._draft_cfg = dataclasses.replace(
            d_base, decode=True, max_cache_len=self.cfg.max_cache_len,
            page_size=0, n_pages=0,
        )
        self.draft_params = draft_params
        self._draft_prefixes = {}  # prefix_id -> draft dense cache
        from sparkdl_tpu.models.llama import Llama

        dummy = jnp.zeros((self.n_slots, 1), jnp.int32)
        self._d_cache = Llama(self._draft_cfg).init(
            jax.random.PRNGKey(1), dummy,
            positions=jnp.zeros((self.n_slots, 1), jnp.int32),
        )["cache"]
        self.stats.update(rounds=0, proposed=0, accepted=0)

    @property
    def _spec_programs(self):
        return _spec_engine_programs(self.cfg, self._draft_cfg, self.k,
                                     self.temperature, self.top_k,
                                     self.top_p)

    def _worst_case_tokens(self, p_len, max_new):
        # + k scratch: a verify may write k positions past the final
        # accepted token; those rows (and, paged, their pages) must be
        # the request's OWN scratch, never a neighbour's data.
        return p_len + max_new + self.k

    def submit(self, prompt_tokens, max_new_tokens, prefix_id=None,
               adapter_id=0, stop=None):
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if self._worst_case_tokens(len(prompt), max_new_tokens) \
                > self.cfg.max_cache_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) + k ({self.k}) speculation "
                f"scratch exceeds max_cache_len "
                f"({self.cfg.max_cache_len}); raise max_cache_len or "
                "lower k"
            )
        return super().submit(prompt, max_new_tokens,
                              prefix_id=prefix_id,
                              adapter_id=adapter_id, stop=stop)

    def register_prefix(self, prefix_tokens, adapter_id=0):
        """Shared-prefix caching for BOTH models: the target side goes
        through the base engine (dense cache copy or read-only shared
        pool pages); the draft keeps its own dense prefix cache, so a
        prefixed admission prefills only the suffix on both sides —
        and the draft stays position-correct, which speculation's
        acceptance rate depends on."""
        pid = super().register_prefix(prefix_tokens, adapter_id)
        draft_prefill = self._spec_programs[0]
        prefix = np.asarray(prefix_tokens, np.int32).reshape(-1)
        padded = _pad_bucket(prefix, self.cfg.max_cache_len)
        d_cache = draft_prefill(self.draft_params, jnp.asarray(padded))
        # pin the shared index to the TRUE length (the bucket-padded
        # prefill advanced it to the bucket) — mirrors the base
        # engine's dense prefix path
        d_cache = jax.tree.map(
            lambda x: jnp.full(x.shape, len(prefix), x.dtype)
            if x.ndim == 0 else x, d_cache)
        self._draft_prefixes[pid] = d_cache
        return pid

    def _draft_admit(self, slot_idx, prompt, prefix_id):
        """Prompt (or its suffix past a cached prefix) through the
        draft into its dense slot cache — shared epilogue of both
        admission paths."""
        if slot_idx in self._prefilling:
            # chunked prefill STAGES the slot inactive; the early
            # return below would then skip the draft prefill and this
            # request would speculate against the previous occupant's
            # draft K/V (silent acceptance collapse) — fail fast
            # instead. __init__ never enables prefill_chunk; this
            # guards future plumbing.
            raise RuntimeError(
                "speculative engine does not support chunked prefill"
            )
        if not self._slots[slot_idx].active:
            # instantly finished (first token was eos / 1-token
            # budget): the slot will be re-admitted fresh — don't pay
            # a draft prefill + full-tree insert for it
            return
        draft_prefill, draft_insert, draft_suffix_prefill = \
            self._spec_programs[:3]
        if prefix_id is not None:
            prefix, _, _aid = self._prefixes[prefix_id]
            padded = _pad_bucket(prompt[len(prefix):],
                                 self.cfg.max_cache_len - len(prefix))
            one = draft_suffix_prefill(
                self.draft_params, self._draft_prefixes[prefix_id],
                jnp.asarray(padded))
        else:
            padded = _pad_bucket(prompt, self.cfg.max_cache_len)
            one = draft_prefill(self.draft_params, jnp.asarray(padded))
        self._d_cache = draft_insert(self._d_cache, one, slot_idx)

    def _admit(self, slot_idx):
        # capture before super() pops the queue head
        _, prompt, _, prefix_id, _ = self._queue[0]
        super()._admit(slot_idx)
        self._draft_admit(slot_idx, prompt, prefix_id)

    def _try_admit_paged(self, slot_idx):
        _, prompt, _, prefix_id, _ = self._queue[0]
        if not super()._try_admit_paged(slot_idx):
            return False
        self._draft_admit(slot_idx, prompt, prefix_id)
        return True

    def _run(self, progress):
        spec_round = self._spec_programs[3]
        while (self._queue or self._prefilling
               or any(s.active for s in self._slots)):
            active = self._fill_slots()
            if not active.any():
                self._deadend_check()
                continue
            (self._cache, self._d_cache, tokens, counts, lps,
             self._rng) = spec_round(
                self.params, self._cache, self.draft_params,
                self._d_cache, self._token, self._pos,
                jnp.asarray(active), self._rng,
                tables=(jnp.asarray(
                    np.where(active[:, None], self._tables, 0))
                        if self.page_size else None),
            )
            tokens = np.asarray(tokens)               # (b, k+1)
            counts = np.asarray(counts)               # (b,)
            lps = np.asarray(lps)
            n_act = int(active.sum())
            self.stats["rounds"] += 1
            self.stats["proposed"] += self.k * n_act
            self.stats["steps"] += 1
            self.stats["total_slot_steps"] += self.n_slots
            self.stats["active_slot_steps"] += n_act
            # one speculation round = one "chunk" of up to k+1 tokens
            # per slot
            self._observe_chunk(n_act, self.k + 1)
            new_pos = np.asarray(self._pos).copy()
            new_tok = np.asarray(self._token).copy()
            for i, s in enumerate(self._slots):
                if not s.active:
                    continue
                cnt = int(counts[i])
                # cnt-1 proposals survived; the last token is the
                # bonus (full acceptance) or the corrected/resampled
                # one (first rejection)
                self.stats["accepted"] += cnt - 1
                if not self._accept_tokens(i, tokens[i, :cnt],
                                           lps[i, :cnt]):
                    new_pos[i] += cnt
                    new_tok[i] = tokens[i, cnt - 1]
            self._pos = jnp.asarray(new_pos)
            self._token = jnp.asarray(new_tok)
            if progress is not None:
                progress(self)
        self.stats["acceptance_rate"] = (
            self.stats["accepted"] / max(1, self.stats["proposed"])
        )
        return self._drain_results()
